"""Admission control — mutating/validating plugin chain + policy rules.

Reference: ``staging/src/k8s.io/apiserver/pkg/admission/`` (two-phase chain:
all mutating plugins, then all validating), built-ins from
``plugin/pkg/admission/``:
  DefaultTolerationSeconds  defaulttolerationseconds/admission.go — add 300s
                            not-ready/unreachable NoExecute tolerations
  PodPriority               priority/admission.go — resolve priorityClassName
                            to spec.priority via PriorityClass objects
  ResourceQuota             resourcequota/admission.go — enforce per-namespace
                            hard limits against live usage
  LimitRanger               limitranger/admission.go — default container
                            requests from LimitRange objects
and ``ValidatingAdmissionPolicy`` (CEL upstream) as a small field-path
expression engine with the same match-conditions shape.

Every plugin is ``fn(verb, kind, obj) -> obj`` raising AdmissionError to
reject — the signature APIServer.admission already dispatches.
"""

from __future__ import annotations

import itertools
import logging
import operator
import threading
import time
from typing import Any, Callable, Optional

from kubernetes_tpu.api.resource import canonical
from kubernetes_tpu.store.apiserver import AdmissionError

_LOG = logging.getLogger(__name__)
from kubernetes_tpu.store.store import ObjectStore

DEFAULT_TOLERATION_SECONDS = 300
_AUTO_TOLERATIONS = ("node.kubernetes.io/not-ready",
                     "node.kubernetes.io/unreachable")


class AdmissionChain:
    """Ordered mutating plugins then validating plugins, as one callable."""

    wants_subresource = True  # threads the subresource to webhook dispatch

    def __init__(self):
        self.mutating: list[Callable] = []
        self.validating: list[Callable] = []

    @staticmethod
    def _invoke(fn, verb, kind, obj, sub):
        if getattr(fn, "wants_subresource", False):
            return fn(verb, kind, obj, sub)
        return fn(verb, kind, obj)

    def __call__(self, verb: str, kind: str, obj: dict, sub=None) -> dict:
        hooks = []
        try:
            for fn in self.mutating:
                r = self._invoke(fn, verb, kind, obj, sub)
                if callable(r):
                    hooks.append(r)
                elif r:
                    obj = r
            for fn in self.validating:
                out = self._invoke(fn, verb, kind, obj, sub)
                if callable(out):  # two-phase plugin: commit hook (see _admit)
                    hooks.append(out)
                elif out is not None and out is not obj:
                    raise AdmissionError(
                        f"validating plugin {getattr(fn, '__name__', fn)!r} mutated")
        except Exception:
            # a later plugin denied: earlier plugins' reservations must not
            # linger until their TTL (a quota hold would phantom-count 30s)
            for h in hooks:
                try:
                    h(False)
                except Exception:
                    _LOG.debug("admission rollback hook failed",
                               exc_info=True)
            raise
        if hooks:
            obj.setdefault("\x00admission_commits", []).extend(hooks)
        return obj

    def install(self, server) -> "AdmissionChain":
        server.admission.append(self)
        return self


# ---------------------------------------------------------------- mutating

def default_toleration_seconds(verb: str, kind: str, obj: dict):
    """Every pod tolerates not-ready/unreachable for 300s unless it already
    addresses those taints (defaulttolerationseconds/admission.go)."""
    if kind != "Pod" or verb not in ("CREATE",):
        return obj
    spec = obj.setdefault("spec", {})
    tols = list(spec.get("tolerations") or [])
    for key in _AUTO_TOLERATIONS:
        if any(t.get("key") == key or (not t.get("key") and
                                       t.get("operator") == "Exists")
               for t in tols):
            continue
        tols.append({"key": key, "operator": "Exists", "effect": "NoExecute",
                     "tolerationSeconds": DEFAULT_TOLERATION_SECONDS})
    spec["tolerations"] = tols
    return obj


def pod_priority_resolver(store: ObjectStore):
    """priorityClassName -> spec.priority (priority/admission.go)."""
    def resolve(verb: str, kind: str, obj: dict):
        if kind != "Pod" or verb != "CREATE":
            return obj
        spec = obj.setdefault("spec", {})
        name = spec.get("priorityClassName", "")
        if not name:
            return obj
        try:
            pc = store.get("PriorityClass", "", name)
        except Exception:
            raise AdmissionError(f"no PriorityClass with name {name} found") \
                from None
        spec["priority"] = int(pc.get("value", 0))
        return obj
    return resolve


def limit_ranger(store: ObjectStore):
    """Default container requests from the namespace LimitRange
    (limitranger/admission.go, type Container defaultRequest)."""
    def default_requests(verb: str, kind: str, obj: dict):
        if kind != "Pod" or verb != "CREATE":
            return obj
        ns = (obj.get("metadata") or {}).get("namespace", "default")
        items, _ = store.list("LimitRange", namespace=ns)
        defaults: dict[str, Any] = {}
        for lr in items:
            for lim in (lr.get("spec") or {}).get("limits") or []:
                if lim.get("type", "Container") == "Container":
                    defaults.update(lim.get("defaultRequest") or {})
        if not defaults:
            return obj
        for c in (obj.get("spec") or {}).get("containers") or []:
            res = c.setdefault("resources", {})
            req = res.setdefault("requests", {})
            for r, q in defaults.items():
                req.setdefault(r, q)
        return obj
    return default_requests


# --------------------------------------------------------------- validating

QUOTA_TRACKED = ("cpu", "memory", "pods")


def _pod_usage(obj: dict) -> dict[str, int]:
    use = {"pods": 1}
    for c in (obj.get("spec") or {}).get("containers") or []:
        for r, q in ((c.get("resources") or {}).get("requests") or {}).items():
            if r in QUOTA_TRACKED:
                use[r] = use.get(r, 0) + canonical(r, q)
    return use


def resource_quota(store: ObjectStore):
    """Enforce ResourceQuota.spec.hard against live namespace usage
    (resourcequota/admission.go; usage recomputed per decision — the
    controller-cached usage status is an optimization we skip).

    Admission returns before the pod is persisted, so an admitted-but-not-
    yet-visible pod reserves its usage in ``inflight`` under a UNIQUE token
    (names are useless here: generateName pods have none yet) and returns a
    commit hook the apiserver invokes once the create commits or fails —
    releasing the reservation exactly when the pod becomes countable in the
    store listing. Racing creates see each other's reservations and cannot
    jointly exceed the quota; a 30s TTL backstops crashed request paths."""
    lock = threading.Lock()
    seq = itertools.count()
    inflight: dict[tuple, tuple[dict, float]] = {}  # (ns,tok) -> (usage, ts)

    def enforce(verb: str, kind: str, obj: dict):
        if kind != "Pod" or verb != "CREATE":
            return None
        ns = (obj.get("metadata") or {}).get("namespace", "default")
        quotas, _ = store.list("ResourceQuota", namespace=ns)
        if not quotas:
            return None
        with lock:  # serialize check-then-admit so racing creates can't slip past
            pods, _ = store.list("Pod", namespace=ns)
            now = time.time()
            for k in list(inflight):
                if now - inflight[k][1] > 30.0:
                    del inflight[k]
            used: dict[str, int] = {}
            for p in pods:
                if (p.get("status") or {}).get("phase") in ("Succeeded", "Failed"):
                    continue
                for r, v in _pod_usage(p).items():
                    used[r] = used.get(r, 0) + v
            for (res_ns, _tok), (u, _ts) in inflight.items():
                if res_ns == ns:
                    for r, v in u.items():
                        used[r] = used.get(r, 0) + v
            want = _pod_usage(obj)
            for q in quotas:
                hard = (q.get("spec") or {}).get("hard") or {}
                for r, lim in hard.items():
                    key = r.split("requests.", 1)[-1]
                    if key not in want:
                        continue
                    if used.get(key, 0) + want[key] > canonical(key, lim):
                        raise AdmissionError(
                            f"exceeded quota: {q['metadata']['name']}, "
                            f"requested: {key}={want[key]}, "
                            f"used: {key}={used.get(key, 0)}, "
                            f"limited: {key}={canonical(key, lim)}")
            token = (ns, next(seq))
            inflight[token] = (want, now)

        def release(ok: bool):
            with lock:
                inflight.pop(token, None)
        return release
    return enforce


# ----------------------------------------------------- policy engine (CEL-ish)

_OPS = {"==": operator.eq, "!=": operator.ne, ">": operator.gt,
        "<": operator.lt, ">=": operator.ge, "<=": operator.le,
        "in": lambda a, b: a in b, "exists": lambda a, b: a is not None}


def _field(obj: dict, path: str):
    cur: Any = obj
    for part in path.split("."):
        if isinstance(cur, list):
            try:
                cur = cur[int(part)]
                continue
            except (ValueError, IndexError):
                return None
        if not isinstance(cur, dict):
            return None
        cur = cur.get(part)
    return cur


class ValidatingPolicy:
    """ValidatingAdmissionPolicy analog: match kinds + rule list.

    Rules: {"field": "spec.replicas", "op": "<=", "value": 10,
            "message": "..."}. The reference expresses these in CEL; the
    field-path/op/value triple covers the same match shape without an
    expression VM.
    """

    def __init__(self, name: str, kinds: tuple[str, ...],
                 rules: list[dict], verbs: tuple[str, ...] = ("CREATE", "UPDATE")):
        self.name = name
        self.kinds = kinds
        self.rules = rules
        self.verbs = verbs
        self.__name__ = f"policy/{name}"

    def __call__(self, verb: str, kind: str, obj: dict):
        if kind not in self.kinds or verb not in self.verbs:
            return None
        for rule in self.rules:
            got = _field(obj, rule["field"])
            op = _OPS[rule.get("op", "==")]
            try:
                ok = op(got, rule.get("value"))
            except TypeError:
                ok = False
            if not ok:
                raise AdmissionError(
                    rule.get("message",
                             f"policy {self.name}: {rule['field']} "
                             f"{rule.get('op')} {rule.get('value')} violated"))
        return None


def default_chain(store: ObjectStore) -> AdmissionChain:
    """The default plugin set, in upstream enablement order: built-in
    mutators, then MutatingAdmissionWebhook; ValidatingAdmissionWebhook
    before ResourceQuota LAST (the reference's AllOrderedPlugins tail —
    quota must only be charged for objects the webhooks already allowed,
    or a slow/denying webhook pins phantom reservations)."""
    from kubernetes_tpu.store.webhooks import (MutatingWebhooks,
                                               ValidatingWebhooks)
    chain = AdmissionChain()
    chain.mutating += [
        pod_priority_resolver(store),
        default_toleration_seconds,
        limit_ranger(store),
        MutatingWebhooks(store),
    ]
    from kubernetes_tpu.store.podsecurity import pod_security
    # PodSecurity before the webhooks (upstream runs it among the
    # built-ins; a policy-rejected pod must not reach external hooks)
    chain.validating += [pod_security(store), ValidatingWebhooks(store),
                         resource_quota(store)]
    return chain
