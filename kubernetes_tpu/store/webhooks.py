"""External admission webhooks — HTTP(S) transport for the admission chain.

Reference: ``staging/src/k8s.io/apiserver/pkg/admission/plugin/webhook/``
(mutating + validating dispatchers) and the admission/v1 wire types
(``staging/src/k8s.io/api/admission/v1/types.go``): the apiserver POSTs an
``AdmissionReview`` carrying the object, the webhook answers
``{response: {uid, allowed, status, patch}}`` where a mutating webhook's
patch is a base64 RFC-6902 JSON Patch. Configuration objects
(``MutatingWebhookConfiguration`` / ``ValidatingWebhookConfiguration``,
admissionregistration.k8s.io/v1) live in the store like any resource; the
dispatchers re-read them on a short poll so registering a webhook takes
effect without an apiserver restart (upstream watches the same configs).

failurePolicy semantics (per webhook, default ``Fail``): a transport error
or timeout DENIES the request under ``Fail`` and is skipped under
``Ignore``. ``timeoutSeconds`` (default 10) bounds each call.
"""

from __future__ import annotations

import base64
import json
import threading
import time
import urllib.request
from typing import Callable, Optional

from kubernetes_tpu.store.apiserver import AdmissionError
from kubernetes_tpu.store.store import ObjectStore

_CONFIG_POLL_S = 1.0  # config freshness window (upstream watches; we poll)


# ------------------------------------------------------------- JSON Patch

def apply_json_patch(obj: dict, patch: list) -> dict:
    """RFC 6902 subset: add / replace / remove with /-escaped pointers
    (``~1`` = ``/``, ``~0`` = ``~``; trailing ``-`` appends to a list).
    The reference applies exactly this to mutating webhook responses."""
    import copy
    out = copy.deepcopy(obj)
    for op in patch:
        kind = op.get("op")
        parts = [p.replace("~1", "/").replace("~0", "~")
                 for p in op.get("path", "").split("/")[1:]]
        parent = out
        for p in parts[:-1]:
            parent = parent[int(p)] if isinstance(parent, list) else parent.setdefault(p, {})
        leaf = parts[-1] if parts else ""
        if kind in ("add", "replace"):
            if isinstance(parent, list):
                if leaf == "-":
                    parent.append(op.get("value"))
                elif kind == "add":
                    parent.insert(int(leaf), op.get("value"))
                else:
                    parent[int(leaf)] = op.get("value")
            else:
                parent[leaf] = op.get("value")
        elif kind == "remove":
            if isinstance(parent, list):
                del parent[int(leaf)]
            else:
                parent.pop(leaf, None)
        else:
            raise AdmissionError(f"unsupported patch op {kind!r}")
    return out


# ------------------------------------------------------------- transport

def _call_webhook(url: str, review: dict, timeout_s: float) -> dict:
    req = urllib.request.Request(
        url, data=json.dumps(review).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=timeout_s) as resp:
        return json.loads(resp.read())


def _review(verb: str, kind: str, obj: dict, uid: str) -> dict:
    from kubernetes_tpu.store.apiserver import KIND_TO_GROUP
    md = obj.get("metadata") or {}
    return {
        "apiVersion": "admission.k8s.io/v1", "kind": "AdmissionReview",
        "request": {
            "uid": uid,
            "kind": {"group": KIND_TO_GROUP.get(kind, ""),
                     "version": "v1", "kind": kind},
            "operation": verb,
            "name": md.get("name", ""),
            "namespace": md.get("namespace", ""),
            "object": obj,
        },
    }


class _Dispatcher:
    """Base dispatcher: reads the relevant *WebhookConfiguration objects
    (short poll), matches rules, calls each webhook in name order with
    failurePolicy/timeout semantics."""

    CONFIG_KIND = ""  # subclass

    def __init__(self, store: ObjectStore):
        self.store = store
        self._lock = threading.Lock()
        self._cached: tuple[float, list] = (0.0, [])
        self._uid = 0
        self.__name__ = type(self).__name__

    def _webhooks(self) -> list:
        now = time.monotonic()
        with self._lock:
            ts, hooks = self._cached
            if now - ts < _CONFIG_POLL_S:
                return hooks
        configs, _ = self.store.list(self.CONFIG_KIND)
        hooks = []
        for cfg in configs:
            for wh in cfg.get("webhooks") or []:
                hooks.append(wh)
        hooks.sort(key=lambda w: w.get("name", ""))
        with self._lock:
            self._cached = (now, hooks)
        return hooks

    @staticmethod
    def _matches(wh: dict, verb: str, kind: str,
                 sub: Optional[str] = None) -> bool:
        """Rule matching with upstream's resource/subresource split
        (``plugin/webhook/rules/rules.go`` Matcher.resource): ``pods``
        matches only the main resource, ``pods/status`` that subresource,
        ``pods/*`` any, ``*`` all resources but NO subresources."""
        from kubernetes_tpu.store.apiserver import KIND_TO_PLURAL
        rules = wh.get("rules")
        if not rules:
            return False
        plural = KIND_TO_PLURAL.get(kind, kind.lower() + "s")
        req_sub = sub or ""
        for rule in rules:
            ops = rule.get("operations") or ["*"]
            # upstream validation requires non-empty resources; a rule
            # without them matches NOTHING here rather than everything
            kinds = rule.get("resources") or rule.get("kinds")
            if not kinds:
                continue
            if "*" not in ops and verb not in ops:
                continue
            for entry in kinds:
                res, _, rsub = str(entry).partition("/")
                res_ok = res == "*" or res == plural or res == kind
                sub_ok = rsub == "*" or rsub == req_sub
                if res_ok and sub_ok:
                    return True
        return False

    def _call(self, wh: dict, verb: str, kind: str, obj: dict
              ) -> Optional[dict]:
        """-> webhook response dict, or None when failurePolicy=Ignore ate
        a transport failure. Raises AdmissionError on Fail."""
        url = ((wh.get("clientConfig") or {}).get("url")) or ""
        policy = wh.get("failurePolicy", "Fail")
        timeout_s = float(wh.get("timeoutSeconds", 10))
        with self._lock:
            self._uid += 1
            uid = f"rev-{self._uid}"
        try:
            out = _call_webhook(url, _review(verb, kind, obj, uid),
                                timeout_s)
        except Exception as e:
            if policy == "Ignore":
                return None
            raise AdmissionError(
                f"webhook {wh.get('name', url)!r} failed "
                f"(failurePolicy=Fail): {e}") from None
        resp = out.get("response") or {}
        if resp.get("uid") not in (uid, "", None):
            if policy == "Ignore":
                return None
            raise AdmissionError(
                f"webhook {wh.get('name', url)!r}: response uid mismatch")
        if not resp.get("allowed", False):
            msg = (resp.get("status") or {}).get(
                "message", f"denied by webhook {wh.get('name', url)!r}")
            raise AdmissionError(msg)
        return resp


class MutatingWebhooks(_Dispatcher):
    """MutatingAdmissionWebhook analog: applies each allowed response's
    JSONPatch in webhook order."""

    CONFIG_KIND = "MutatingWebhookConfiguration"
    wants_subresource = True

    def __call__(self, verb: str, kind: str, obj: dict,
                 sub: Optional[str] = None):
        if kind == self.CONFIG_KIND or kind == "ValidatingWebhookConfiguration":
            return None  # the configs themselves bypass the webhooks
        for wh in self._webhooks():
            if not self._matches(wh, verb, kind, sub):
                continue
            resp = self._call(wh, verb, kind, obj)
            if resp is None:
                continue
            patch_b64 = resp.get("patch")
            if patch_b64:
                if resp.get("patchType", "JSONPatch") != "JSONPatch":
                    raise AdmissionError(
                        f"webhook {wh.get('name')!r}: unsupported patchType")
                try:
                    patch = json.loads(base64.b64decode(patch_b64))
                except Exception:
                    raise AdmissionError(
                        f"webhook {wh.get('name')!r}: undecodable patch"
                    ) from None
                obj = apply_json_patch(obj, patch)
        return obj


class ValidatingWebhooks(_Dispatcher):
    """ValidatingAdmissionWebhook analog: any deny rejects; responses
    cannot mutate."""

    CONFIG_KIND = "ValidatingWebhookConfiguration"
    wants_subresource = True

    def __call__(self, verb: str, kind: str, obj: dict,
                 sub: Optional[str] = None):
        if kind in ("MutatingWebhookConfiguration", self.CONFIG_KIND):
            return None
        for wh in self._webhooks():
            if self._matches(wh, verb, kind, sub):
                self._call(wh, verb, kind, obj)
        return None


# ------------------------------------------------------------ test server

class WebhookTestServer:
    """A tiny admission webhook endpoint for tests/examples: pass
    ``mutate(review) -> patch list | None`` and/or
    ``validate(review) -> (allowed, message)``."""

    def __init__(self, mutate: Optional[Callable] = None,
                 validate: Optional[Callable] = None,
                 latency_s: float = 0.0):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
        outer = self
        self.calls = 0

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_POST(self):
                outer.calls += 1
                if latency_s:
                    time.sleep(latency_s)
                n = int(self.headers.get("Content-Length", 0))
                review = json.loads(self.rfile.read(n))
                uid = (review.get("request") or {}).get("uid", "")
                resp = {"uid": uid, "allowed": True}
                if validate is not None:
                    allowed, msg = validate(review)
                    resp["allowed"] = allowed
                    if not allowed:
                        resp["status"] = {"message": msg}
                if resp["allowed"] and mutate is not None:
                    patch = mutate(review)
                    if patch:
                        resp["patchType"] = "JSONPatch"
                        resp["patch"] = base64.b64encode(
                            json.dumps(patch).encode()).decode()
                body = json.dumps({"apiVersion": "admission.k8s.io/v1",
                                   "kind": "AdmissionReview",
                                   "response": resp}).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self.url = f"http://127.0.0.1:{self.port}"
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "WebhookTestServer":
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()
