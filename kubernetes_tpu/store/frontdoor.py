"""The front door: a read-replica serving plane over the raft group.

One APIServer fronts EVERY raft node, not just the leader. Followers
serve GET/list/watch from their local (replicated) store — the watch
fan-out cost that otherwise concentrates on the leader spreads across
the group — while mutations on a follower answer 421 + an
``X-KTPU-Leader`` hint that the spread client chases. Reference role:
apiserver replicas in front of etcd, where any replica serves reads
from the watch cache and linearizable traffic goes through the leader.

Three pieces live here:

  FrontDoorCluster    in-process n-node group (RaftNode + APIServer per
                      node, ``api_urls`` cross-wired so NotLeader hints
                      are API urls, not raft peer urls). Tier-1 tests
                      and the WatchStorm bench's in-process legs use it.

  FrontDoorPublisher  leader-side loop that polls every replica's
                      ``GET /frontdoor/status`` and publishes the
                      aggregate into the ``kubernetes-tpu-frontdoor-
                      status`` ConfigMap — the feed ``ktpu status``
                      renders as its "Front door:" line.

  fetch_status /      the probe + aggregation helpers the publisher and
  aggregate_frontdoor the CLI share (plain dict in, str->str ConfigMap
                      data out; ``nodes`` is a JSON-encoded list).
"""

from __future__ import annotations

import json
import logging
import threading
import urllib.request
from typing import Optional

from kubernetes_tpu.store.replication import RaftNode, ReplicatedStore
from kubernetes_tpu.store.store import ObjectStore
from kubernetes_tpu.utils.configmap import upsert_configmap

_LOG = logging.getLogger(__name__)

FRONTDOOR_CONFIGMAP = "kubernetes-tpu-frontdoor-status"
FRONTDOOR_NAMESPACE = "kube-system"


def fetch_status(api_url: str, timeout: float = 2.0) -> Optional[dict]:
    """One replica's ``GET /frontdoor/status`` -> dict, or None when the
    replica is unreachable (the aggregate renders it as down)."""
    try:
        with urllib.request.urlopen(api_url.rstrip("/")
                                    + "/frontdoor/status",
                                    timeout=timeout) as resp:
            return json.loads(resp.read())
    except Exception:  # ktpu-lint: disable=KTL002 -- liveness probe: any failure (refused, timeout, bad payload) = peer down, rendered as unreachable
        return None


def aggregate_frontdoor(statuses: "dict[str, Optional[dict]]") -> dict:
    """Per-endpoint status dicts -> the str->str ConfigMap ``data``
    payload. Scalar keys give ``ktpu status`` its one-line summary
    without parsing; ``nodes`` carries the full per-replica detail as a
    JSON list for ``-o json`` consumers."""
    nodes = []
    leader_url = ""
    replicas = 0
    watchers = drops = 0
    max_lag_ms = 0.0
    shards = 0
    for url in sorted(statuses):
        st = statuses[url]
        if st is None:
            nodes.append({"url": url, "reachable": False})
            continue
        watch = st.get("watch") or {}
        entry = {"url": url, "reachable": True,
                 "role": st.get("role", ""),
                 "node": st.get("node"),
                 "ready": bool(st.get("ready")),
                 "replayLagMs": st.get("replayLagMs"),
                 "watchers": int(watch.get("watchersTotal", 0)),
                 "drops": int(watch.get("dropsTotal", 0))}
        nodes.append(entry)
        if entry["role"] == "leader":
            leader_url = url
        else:
            replicas += 1
            if entry["replayLagMs"] is not None:
                max_lag_ms = max(max_lag_ms, float(entry["replayLagMs"]))
        watchers += entry["watchers"]
        drops += entry["drops"]
        shards = max(shards, int(watch.get("shardsPerKind", 0)))
    return {"leader": leader_url,
            "replicas": str(replicas),
            "watchersTotal": str(watchers),
            "dropsTotal": str(drops),
            "maxReplayLagMs": f"{max_lag_ms:.3f}",
            "shardsPerKind": str(shards),
            "nodes": json.dumps(nodes)}


class FrontDoorPublisher:
    """Publishes the aggregated front-door picture into the
    ``kubernetes-tpu-frontdoor-status`` ConfigMap every ``interval_s``.
    Runs wherever a writing client lives (the leader, or any spread
    client — writes chase the leader hint on their own). Publishing is
    best-effort: a failed probe or write must never take the plane down."""

    def __init__(self, client, endpoints, *,
                 namespace: str = FRONTDOOR_NAMESPACE,
                 interval_s: float = 5.0):
        self._client = client
        self.endpoints = [e.rstrip("/") for e in endpoints]
        self.namespace = namespace
        self.interval_s = interval_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def publish_once(self) -> bool:
        statuses = {url: fetch_status(url) for url in self.endpoints}
        data = aggregate_frontdoor(statuses)
        return upsert_configmap(self._client, self.namespace,
                                FRONTDOOR_CONFIGMAP, data,
                                site="frontdoor_publish")

    def start(self) -> "FrontDoorPublisher":
        if self._thread is not None:
            return self
        self._thread = threading.Thread(target=self._loop,
                                        name="frontdoor-publisher",
                                        daemon=True)
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.publish_once()
            except Exception:
                # best-effort publisher: log and retry next tick
                _LOG.warning("frontdoor publish failed", exc_info=True)
            self._stop.wait(self.interval_s)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None


class FrontDoorCluster:
    """An in-process n-node front door: one RaftNode + one APIServer per
    node, ``api_urls`` cross-wired on every server so a follower's 421
    carries the LEADER'S API url (NotLeader.leader_hint is the raft peer
    url, which no API client can dial)."""

    def __init__(self, n: int = 3, host: str = "127.0.0.1",
                 data_dirs: Optional[list] = None,
                 max_replay_lag_s: float = 2.0,
                 commit_timeout: float = 15.0):
        if data_dirs is not None and len(data_dirs) != n:
            raise ValueError(f"need {n} data_dirs, got {len(data_dirs)}")
        self.n = n
        self.host = host
        self.data_dirs = data_dirs
        self.max_replay_lag_s = max_replay_lag_s
        self.commit_timeout = commit_timeout
        self.nodes: list[RaftNode] = []
        self.apis: list = []  # APIServer per node, same order as nodes

    # ---- lifecycle -------------------------------------------------------

    def start(self, leader_timeout: float = 30.0) -> "FrontDoorCluster":
        from kubernetes_tpu.chaos.apiserver import free_port
        from kubernetes_tpu.store.apiserver import APIServer
        raft_ports = [free_port(self.host) for _ in range(self.n)]
        for i in range(self.n):
            peers = {f"n{j}": f"http://{self.host}:{raft_ports[j]}"
                     for j in range(self.n) if j != i}
            store = ObjectStore(data_dir=self.data_dirs[i]) \
                if self.data_dirs else ObjectStore()
            self.nodes.append(RaftNode(f"n{i}", store, peers,
                                       port=raft_ports[i]))
        self.wait_leader(timeout=leader_timeout)
        for node in self.nodes:
            api = APIServer(
                host=self.host,
                store=ReplicatedStore(node,
                                      commit_timeout=self.commit_timeout))
            api.max_replay_lag_s = self.max_replay_lag_s
            self.apis.append(api.start())
        api_urls = {node.node_id: api.url
                    for node, api in zip(self.nodes, self.apis)}
        for api in self.apis:
            api.api_urls = dict(api_urls)
        return self

    def stop(self) -> None:
        for api in self.apis:
            try:
                api.stop()
            except Exception:
                # teardown best effort: one wedged server must not
                # leak the rest
                _LOG.warning("frontdoor api stop failed", exc_info=True)
        self.apis = []
        for node in self.nodes:
            node.stop()
        self.nodes = []

    # ---- topology --------------------------------------------------------

    def wait_leader(self, timeout: float = 30.0) -> RaftNode:
        """Block until exactly one live node leads -> that node. The wide
        default budget absorbs full-suite GIL contention (election
        timeouts stretch under hundreds of suite threads)."""
        import time as _time
        deadline = _time.monotonic() + timeout
        while _time.monotonic() < deadline:
            leaders = [nd for nd in self.nodes
                       if not nd._stop.is_set() and nd.is_leader()]
            if len(leaders) == 1:
                return leaders[0]
            _time.sleep(0.05)
        raise TimeoutError("no single leader elected: "
                           + str([nd.status() for nd in self.nodes]))

    @property
    def endpoints(self) -> list:
        return [api.url for api in self.apis]

    @property
    def leader_api(self):
        """The APIServer fronting the current leader (raises if the
        group is mid-election)."""
        leader = self.wait_leader()
        return self.apis[self.nodes.index(leader)]

    @property
    def replica_apis(self) -> list:
        leader = self.wait_leader()
        return [api for node, api in zip(self.nodes, self.apis)
                if node is not leader]

    def client(self, **kw):
        """A spread HTTPClient over every front-door endpoint."""
        from kubernetes_tpu.client.clientset import HTTPClient
        return HTTPClient(self.endpoints, **kw)
