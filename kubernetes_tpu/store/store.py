"""Versioned object store with watch — the etcd + watch-cache analog.

Reference: ``staging/src/k8s.io/apiserver/pkg/storage/etcd3/store.go`` (CRUD +
watch translation) fronted by ``storage/cacher/cacher.go`` (in-memory watch
fan-out). One process-local store stands in for both: a monotone
resourceVersion counter, per-(kind) keyspaces, optimistic-concurrency updates,
and buffered watch channels with bounded replay ("too old" -> relist, like
etcd compaction).

Checkpoint/resume: the cluster state IS the checkpoint (SURVEY §5) —
``save``/``load`` serialize the whole keyspace; components rebuild everything
else from watches.

Durability (etcd's WAL + snapshot analog): pass ``data_dir`` and every
mutation is journaled to ``wal.jsonl`` inside the store lock before the call
returns; the journal is folded into ``snapshot.json`` (atomic tmp+rename)
every ``wal_compact_every`` entries. A store opened on an existing data_dir
restores snapshot + replays the journal tail — an apiserver restart keeps
all pods/bindings, and watchers relist exactly as clients of a compacted
etcd would (TooOld on pre-restart resourceVersions).

Crash tolerance: a record commits when its trailing newline reaches the
file. A SIGKILL mid-append leaves a torn final line; restore drops it
(counting ``store_wal_torn_tail_total``) AND truncates the file back to
the last committed record — the WAL reopens in append mode, and a fresh
entry concatenated onto a torn line would corrupt a COMMITTED record at
the next restore. ``defer_restore=True`` constructs the store without
replaying (the apiserver's async-startup mode: serve /readyz 503 while
``finish_restore()`` runs on a background thread).
"""

from __future__ import annotations

import json
import logging
import os
import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from kubernetes_tpu.metrics.registry import WATCH_CLIENTS, WATCH_DROPS

_LOG = logging.getLogger("kubernetes_tpu.store")

ADDED, MODIFIED, DELETED, ERROR = "ADDED", "MODIFIED", "DELETED", "ERROR"

# Events kept per kind for watch replay before "too old" (etcd compaction
# analog). Sized so a reconnecting watcher survives a full binding storm
# (create+bind = 2 events/pod) at the 10k-pod benchmark scale.
REPLAY_WINDOW = 32768

# Watcher fan-out shards per kind: registration, removal and slow-consumer
# handling contend on a shard's own lock, never the store lock — watcher
# churn at 10k-client scale stays off the write path. Emission nests shard
# locks inside the store lock (store -> shard, never the reverse).
WATCH_SHARDS = 8

# Bounded per-watcher queue (reference analog: cacher.go's per-watcher
# channel budget). A consumer that falls this many events behind is
# disconnected with an ERROR event and a counted drop — it relists, exactly
# as it would after etcd compaction — instead of growing an unbounded queue
# and stalling shard siblings. A watch() whose replay backlog already
# exceeds this budget gets TooOld up front (a relist hands it the same
# state cheaper).
WATCH_QUEUE_MAX = 4096


class Conflict(Exception):
    """resourceVersion mismatch (optimistic concurrency failure)."""


class NotFound(Exception):
    pass


class AlreadyExists(Exception):
    pass


class TooOld(Exception):
    """Requested watch resourceVersion compacted away; caller must relist."""


def fastcopy(o):
    """Structural copy of an already wire-shaped object (dict/list/scalars).
    ~2x faster than a json round-trip; used for copies of objects the store
    has already normalized (create/update inputs still json-round-trip so
    tuples/np scalars are coerced to the wire shape exactly once)."""
    if isinstance(o, dict):
        return {k: fastcopy(v) for k, v in o.items()}
    if isinstance(o, list):
        return [fastcopy(v) for v in o]
    return o


@dataclass
class Event:
    type: str
    object: dict
    resource_version: int
    _wire: Optional[bytes] = None     # cached JSON watch line (lazy, shared)
    _wire_mp: Optional[bytes] = None  # cached msgpack frame (lazy, shared)

    def wire(self) -> bytes:
        """Serialized ``{"type":...,"object":...}\\n`` watch line. Computed
        once and shared by every HTTP watch stream fanning this event out —
        per-watcher re-serialization was the apiserver's top cost under
        binding storms. Benign race: two threads may both compute it."""
        w = self._wire
        if w is None:
            w = json.dumps({"type": self.type, "object": self.object}
                           ).encode() + b"\n"
            self._wire = w
        return w

    def wire_msgpack(self) -> bytes:
        """msgpack frame of the same payload — the binary watch stream
        (reference analog: protobuf watch negotiation,
        ``apimachinery/pkg/runtime/serializer``). ~4x cheaper to encode and
        ~2x to decode than the JSON line; cached and shared identically."""
        w = self._wire_mp
        if w is None:
            import msgpack
            w = msgpack.packb({"type": self.type, "object": self.object})
            self._wire_mp = w
        return w


def obj_key(obj: dict) -> tuple[str, str]:
    md = obj.get("metadata") or {}
    return (md.get("namespace") or "", md["name"])


class _WatchShard:
    """One independently-locked slice of a kind's watcher registry.

    The fan-out path (holding the store lock) takes each shard lock in
    turn; everything else — register, drop, slow-consumer eviction, stats
    — touches only this shard's lock. Lock order is store -> shard; no
    shard method ever takes the store lock, so a storm of watchers
    connecting/disconnecting serializes against 1/K of the registry and
    never against writers."""

    def __init__(self):
        self.lock = threading.Lock()
        # guarded by: self.lock
        self.queues: list[queue.Queue] = []
        self.drops = 0  # guarded by: self.lock

    def add(self, q: "queue.Queue[Event]") -> None:
        with self.lock:
            self.queues.append(q)

    def discard(self, q: "queue.Queue[Event]") -> bool:
        with self.lock:
            if q in self.queues:
                self.queues.remove(q)
                return True
            return False

    def stats(self) -> tuple[int, int]:
        """-> (live watcher queues, cumulative slow-consumer drops)."""
        with self.lock:
            return len(self.queues), self.drops

    @staticmethod
    def _overflow(q: "queue.Queue[Event]", rv: int) -> None:
        """Slow consumer: drain its queue and leave a single ERROR event —
        the stream closes and the client relists, identical to compaction.
        Draining here is safe: the producer side is this shard pass (we
        hold the shard lock) and the consumer only ever removes."""
        while True:
            try:
                q.get_nowait()
            except queue.Empty:
                break
        try:
            q.put_nowait(Event(ERROR, {}, rv))
        except queue.Full:
            pass  # consumer raced the drain; it still sees the severed stream

    def emit(self, evs) -> int:
        """Fan events to every queue in this shard; overflowing watchers
        are evicted with a counted drop. Returns drops this pass."""
        rv = evs[-1].resource_version
        dropped = []
        with self.lock:
            for q in self.queues:
                try:
                    for ev in evs:
                        q.put_nowait(ev)
                except queue.Full:
                    self._overflow(q, rv)
                    dropped.append(q)
            for q in dropped:
                self.queues.remove(q)
            self.drops += len(dropped)
        return len(dropped)

    def invalidate(self, rv: int) -> None:
        """Checkpoint restore / snapshot install: every stream on this
        shard is severed with ERROR (consumers must relist)."""
        with self.lock:
            for q in self.queues:
                try:
                    q.put_nowait(Event(ERROR, {}, rv))
                except queue.Full:
                    self._overflow(q, rv)
            self.queues.clear()


class Watcher:
    def __init__(self, store: "ObjectStore", kind: str, q: "queue.Queue[Event]"):
        self._store = store
        self._kind = kind
        self._q = q
        self.closed = False

    def __iter__(self):
        return self

    def __next__(self) -> Event:
        while not self.closed:
            ev = self.get(timeout=0.2)
            if ev is not None:
                return ev
        raise StopIteration

    def get(self, timeout: float = 0.2) -> Optional[Event]:
        if self.closed:
            return None
        try:
            ev = self._q.get(timeout=timeout)
        except queue.Empty:
            return None
        if ev.type == ERROR:
            # Stream invalidated (checkpoint restore); consumer must relist.
            self.closed = True
            return None
        return ev

    def stop(self):
        self.closed = True
        self._store._drop_watcher(self._kind, self._q)


class ObjectStore:
    """Thread-safe multi-kind object store. Objects are plain dicts in the k8s
    wire shape; metadata.resourceVersion is stamped on every write."""

    def __init__(self, data_dir: Optional[str] = None,
                 wal_compact_every: int = 4096, fsync: bool = False,
                 defer_restore: bool = False):
        self._lock = threading.Lock()
        self._rv = 0
        self._data: dict[str, dict[tuple[str, str], dict]] = {}
        self._history: dict[str, list[Event]] = {}
        # Highest rv trimmed out of each kind's replay history ("compaction
        # point"). TooOld is per kind: a quiet kind's full history stays
        # replayable no matter how fast the global rv advances. _floor_rv is
        # the all-kinds compaction point set by a checkpoint restore, which
        # discards every kind's history (including kinds absent from the
        # checkpoint blob).
        self._compacted: dict[str, int] = {}
        self._floor_rv = 0
        # Watcher registry: per kind, WATCH_SHARDS independently-locked
        # fan-out shards. Writes (creating a kind's shard list) happen under
        # the store lock; a shard list, once created, is never replaced —
        # invalidation clears queues in place — so _drop_watcher may read
        # the dict without the store lock (watcher churn must never contend
        # with the write path).
        self._shards: dict[str, list[_WatchShard]] = {}
        self._watch_seq = 0      # guarded by: self._lock
        self._fanout_ns = 0      # guarded by: self._lock
        self._fanout_events = 0  # guarded by: self._lock
        self._data_dir = data_dir
        self._journal_subs: list = []  # replication taps (under the lock)
        self._wal_compact_every = wal_compact_every
        self._fsync = fsync
        self._wal = None
        self._wal_count = 0
        self._closed = False
        # durability observability (ktpu status Durability line / readyz)
        self._last_snapshot_ts: Optional[float] = None
        self._restore_stats: dict = {}
        self._torn_tails = 0
        if data_dir:
            os.makedirs(data_dir, exist_ok=True)
            if not defer_restore:
                self.finish_restore()

    def finish_restore(self) -> None:
        """Replay snapshot + WAL and open the journal for appends. Called
        from __init__ unless ``defer_restore``; the deferred form lets the
        apiserver begin serving 503s while a long replay runs on a
        background thread (readyz flips when this returns). Idempotent."""
        if self._data_dir is None:
            return  # nothing to replay, nothing to journal
        with self._lock:
            if self._wal is not None or self._closed:
                # _closed: a deferred restore racing close() (server
                # stopped before the replay thread ran) must NOT reopen
                # the WAL — a successor process may already own the file,
                # and this instance's appends would interleave stale-rv
                # records into its journal
                return
            self._restore_locked()
            self._wal = open(self._wal_path, "a", buffering=1)

    @property
    def _snap_path(self):
        return os.path.join(self._data_dir, "snapshot.json")

    @property
    def _wal_path(self):
        return os.path.join(self._data_dir, "wal.jsonl")

    # ---- internals -------------------------------------------------------

    def _bump_locked(self) -> int:
        self._rv += 1
        return self._rv

    def _emit_many_locked(self, kind: str, evs: list[Event]):
        """Batched watch fan-out: one history append + trim and ONE pass
        per fan-out shard for a whole bulk verb's events, instead of
        per-event bookkeeping. Semantically identical to N _emit_locked
        calls — every surviving watcher still receives every event in
        order; a watcher whose bounded queue overflows is evicted with an
        ERROR event and a counted drop (it relists, compaction-style)."""
        if not evs:
            return
        hist = self._history.setdefault(kind, [])
        hist.extend(evs)
        if len(hist) > REPLAY_WINDOW:
            cut = len(hist) - REPLAY_WINDOW
            self._compacted[kind] = hist[cut - 1].resource_version
            del hist[:cut]
        shards = self._shards.get(kind)
        if not shards:
            return
        t0 = time.perf_counter_ns()
        dropped = 0
        for shard in shards:
            dropped += shard.emit(evs)
        self._fanout_ns += time.perf_counter_ns() - t0
        self._fanout_events += len(evs)
        if dropped:
            WATCH_DROPS.inc({"kind": kind}, by=dropped)

    def _emit_locked(self, kind: str, ev: Event):
        # Event payloads SHARE the authoritative object: the store never
        # mutates a stored dict in place (every write REPLACES space[k] with
        # a fresh object), so sharing is safe as long as consumers treat
        # event objects as read-only — the reference's informer-cache
        # convention ("you must deep-copy before mutating"), which get()/
        # list() honor by returning copies. A binding storm emits tens of
        # thousands of events; the per-event detach copy was measurable
        # against the whole connected path.
        self._emit_many_locked(kind, [ev])

    def _drop_watcher(self, kind: str, q):
        # shard-lock only: 10k clients connecting/disconnecting must not
        # contend with writers holding the store lock
        shards = self._shards.get(kind, ())
        for shard in shards:
            if shard.discard(q):
                break
        self._set_watch_gauge(kind, shards)

    @staticmethod
    def _set_watch_gauge(kind: str, shards) -> None:
        WATCH_CLIENTS.set(sum(s.stats()[0] for s in shards), {"kind": kind})

    # ---- durability ------------------------------------------------------

    def _journal_locked(self, entry: dict):
        for fn in self._journal_subs:
            fn(entry)  # replication taps the journal (store/replication.py)
        if self._wal is None:
            return
        self._wal.write(json.dumps(entry) + "\n")
        if self._fsync:
            self._wal.flush()
            os.fsync(self._wal.fileno())
        self._wal_count += 1
        if self._wal_count >= self._wal_compact_every:
            self._compact_wal_locked()

    def _compact_wal_locked(self):
        """Fold the journal into the snapshot: write snapshot.tmp, fsync,
        rename (atomic on POSIX), truncate the WAL."""
        blob = {kind: list(space.values())
                for kind, space in self._data.items()}
        from kubernetes_tpu.utils.atomicio import atomic_write_json
        atomic_write_json(self._snap_path, {"rv": self._rv, "data": blob})
        if self._wal is not None:
            self._wal.close()
        self._wal = open(self._wal_path, "w", buffering=1)
        self._wal_count = 0
        import time as _time
        self._last_snapshot_ts = _time.time()

    def _restore_locked(self):
        """Snapshot + WAL tail -> memory. Called once (no watchers exist
        yet). A record is committed iff its trailing newline reached the
        file: a SIGKILL mid-append leaves a torn final line, which is
        dropped (counted in ``store_wal_torn_tail_total``) AND truncated
        off the file — the WAL reopens for append, so surviving torn bytes
        would merge with the next record and corrupt a COMMITTED write at
        a later restore."""
        import time as _time
        t0 = _time.perf_counter()
        stats: dict = {"snapshotLoaded": False, "walEntriesReplayed": 0,
                       "tornTailDropped": 0}
        if os.path.exists(self._snap_path):
            with open(self._snap_path) as f:
                data = json.load(f)
            self._rv = data["rv"]
            self._data = {kind: {tuple(obj_key(o)): o for o in objs}
                          for kind, objs in data["data"].items()}
            stats["snapshotLoaded"] = True
            try:
                self._last_snapshot_ts = os.path.getmtime(self._snap_path)
            except OSError:
                pass
        if os.path.exists(self._wal_path):
            good_end = 0  # byte offset just past the last committed record
            torn = False
            with open(self._wal_path, "rb") as f:
                while True:
                    line = f.readline()
                    if not line:
                        break
                    if not line.endswith(b"\n"):
                        torn = True  # mid-append kill: newline never landed
                        break
                    try:
                        e = json.loads(line)
                        rv = int(e["rv"])
                        op, kind = e["op"], e["kind"]
                        key = (e["ns"], e["name"])
                        obj = e.get("obj")
                    except (ValueError, KeyError, TypeError):
                        # torn or corrupt record: everything before it is
                        # committed, nothing after it is trusted
                        torn = True
                        break
                    good_end = f.tell()
                    if rv <= self._rv:
                        # already folded into the snapshot (crash between
                        # snapshot rename and WAL truncate)
                        continue
                    space = self._data.setdefault(kind, {})
                    if op == "set":
                        space[key] = obj
                    elif op == "del":
                        space.pop(key, None)
                    self._rv = max(self._rv, rv)
                    stats["walEntriesReplayed"] += 1
            if torn:
                from kubernetes_tpu.metrics.registry import WAL_TORN_TAIL
                WAL_TORN_TAIL.inc()
                self._torn_tails += 1
                stats["tornTailDropped"] = 1
                _LOG.warning(
                    "WAL %s has a torn tail (crash mid-append): dropping "
                    "uncommitted bytes past offset %d", self._wal_path,
                    good_end)
                try:
                    os.truncate(self._wal_path, good_end)
                except OSError:
                    _LOG.exception("could not truncate torn WAL tail; the "
                                   "next append may corrupt a record")
        # compaction cadence counts entries since the last snapshot, and
        # survives restarts: a WAL that restores long must fold soon
        self._wal_count = stats["walEntriesReplayed"]
        stats["replayMs"] = round((_time.perf_counter() - t0) * 1000.0, 2)
        self._restore_stats = stats
        self._floor_rv = self._rv
        self._reseed_service_ips_locked()

    def _reseed_service_ips_locked(self):
        """Advance the ClusterIP allocator past every Service present —
        restores, snapshot installs, and replicated applies must never
        re-issue a VIP an existing Service holds (a promoted follower
        would otherwise hand out duplicates)."""
        seq = getattr(self, "_svc_ip_seq", 0)
        for (_ns, _n), svc in self._data.get("Service", {}).items():
            ip = (svc.get("spec") or {}).get("clusterIP") or ""
            parts = ip.split(".")
            if len(parts) == 4 and ip.startswith("10.96."):
                seq = max(seq, int(parts[2]) * 250 + int(parts[3]) - 1)
        if seq:
            self._svc_ip_seq = seq

    # ---- durability observability ----------------------------------------

    def durability_stats(self) -> dict:
        """The Durability block of ``ktpu status`` (published by the
        apiserver's status ConfigMap in data_dir mode): WAL growth since
        the last snapshot fold, snapshot age, what the last restore cost
        and whether it dropped a torn tail."""
        with self._lock:
            return {
                "durable": self._data_dir is not None,
                "walEntriesSinceSnapshot": self._wal_count,
                "lastSnapshotTime": self._last_snapshot_ts,
                "replayMs": self._restore_stats.get("replayMs"),
                "walEntriesReplayed":
                    self._restore_stats.get("walEntriesReplayed", 0),
                "snapshotLoaded":
                    self._restore_stats.get("snapshotLoaded", False),
                "tornTailsDropped": self._torn_tails,
                "rv": self._rv,
            }

    # ---- replication hooks (store/replication.py) ------------------------

    def snapshot_rv(self) -> int:
        """Current rv (method form for replication call sites)."""
        with self._lock:
            return self._rv

    def subscribe_journal(self, fn) -> None:
        """``fn(entry)`` fires under the store lock for every journaled
        mutation — keep it O(1) (append to a buffer; never do I/O)."""
        with self._lock:
            self._journal_subs.append(fn)

    def apply_replicated(self, entry: dict) -> None:
        """Apply a replicated journal entry at ITS rv (follower side): the
        twin of the WAL replay in _restore_locked, but live — watchers see
        the event, so informers on a follower stay current."""
        kind = entry["kind"]
        rv = int(entry["rv"])
        with self._lock:
            if rv <= self._rv:
                return  # duplicate delivery
            space = self._data.setdefault(kind, {})
            key = (entry["ns"], entry["name"])
            if entry["op"] == "set":
                existed = key in space
                space[key] = entry["obj"]
                self._rv = rv
                if kind == "Service":
                    self._reseed_service_ips_locked()
                # journal like a local write: quorum-acked entries must be
                # WAL-durable on FOLLOWERS too, and the journal tap is how
                # a follower's raft log stays populated (a promoted leader
                # with an empty log would force a snapshot storm)
                self._journal_locked(entry)
                self._emit_locked(kind, Event(
                    MODIFIED if existed else ADDED, entry["obj"], rv))
            else:
                old = space.pop(key, None)
                self._rv = rv
                self._journal_locked(entry)
                if old is not None:
                    self._emit_locked(kind, Event(DELETED, old, rv))

    def snapshot_blob(self) -> dict:
        with self._lock:
            return {"rv": self._rv,
                    "data": {kind: list(space.values())
                             for kind, space in self._data.items()}}

    def load_snapshot_blob(self, blob: dict) -> None:
        """Full-state resync (a follower too far behind the leader's
        replication window, or a rejoining ex-leader with a divergent
        uncommitted suffix) — the load() contract: live watch streams are
        invalidated (ERROR event -> informers relist), since a stream that
        silently missed the snapshot delta would retain phantoms forever."""
        with self._lock:
            self._install_state_locked(
                int(blob["rv"]),
                {kind: {tuple(obj_key(o)): o for o in objs}
                 for kind, objs in blob["data"].items()})

    # ---- CRUD ------------------------------------------------------------

    def _prepare_create_locked(self, kind: str, obj: dict) -> dict:
        """Registry PrepareForCreate hooks shared by create/create_many:
        Service ClusterIP allocation (pkg/registry/core/service/ipallocator)
        from 10.96.0.0/12."""
        if kind == "Service":
            spec = obj.get("spec") or {}
            if not spec.get("clusterIP") and spec.get("type") != "ExternalName":
                self._svc_ip_seq = getattr(self, "_svc_ip_seq", 0) + 1
                n = self._svc_ip_seq
                obj = dict(obj)
                obj["spec"] = {**spec,
                               "clusterIP": f"10.96.{n // 250}.{n % 250 + 1}"}
        return obj

    def create(self, kind: str, obj: dict, owned: bool = False) -> dict:
        """``owned=True``: the caller transfers ownership of ``obj`` (it is a
        freshly-parsed, wire-shaped dict nothing else aliases — e.g. an HTTP
        request body) so the defensive copy/normalization round-trip is
        skipped."""
        with self._lock:
            md = obj.get("metadata") or {}
            if not md.get("name") and md.get("generateName"):
                # names.SimpleNameGenerator analog: generateName + unique
                # suffix. The rv counter is the suffix source — monotone AND
                # checkpoint-persisted, so restored stores can never re-issue
                # a name that an existing object carries.
                obj = dict(obj)
                obj["metadata"] = {**md, "name": f"{md['generateName']}{self._rv + 1:05x}"}
            k = obj_key(obj)
            space = self._data.setdefault(kind, {})
            if k in space:
                raise AlreadyExists(f"{kind} {k}")
            obj = self._prepare_create_locked(kind, obj)
            rv = self._bump_locked()
            if not owned:
                obj = json.loads(json.dumps(obj))  # defensive copy, wire-shaped
            md = obj.setdefault("metadata", {})
            md["resourceVersion"] = str(rv)
            # registry.Store.Create stamps identity server-side
            md.setdefault("uid", f"uid-s{rv}")
            if "creationTimestamp" not in md:
                import time as _time
                md["creationTimestamp"] = _time.time()
            space[k] = obj
            self._journal_locked({"op": "set", "kind": kind, "ns": k[0],
                                  "name": k[1], "rv": rv, "obj": obj})
            self._emit_locked(kind, Event(ADDED, obj, rv))
            return fastcopy(obj)

    def create_many(self, kind: str, objs: list[dict]) -> list[dict]:
        """Create a batch of objects in one lock pass (seeding / apply of a
        manifest List). Per-item AlreadyExists surfaces as an exception AFTER
        the siblings commit — callers wanting all-or-nothing pre-check names.
        Semantically identical to N create() calls, minus N-1 lock
        round-trips and defensive-copy passes."""
        from kubernetes_tpu.metrics.registry import BULK_REQUESTS
        BULK_REQUESTS.inc({"endpoint": "bulk-create"})
        out = []
        errors = []
        with self._lock:
            space = self._data.setdefault(kind, {})
            for obj in objs:
                md = obj.get("metadata") or {}
                if not md.get("name") and md.get("generateName"):
                    obj = dict(obj)
                    obj["metadata"] = {**md,
                                       "name": f"{md['generateName']}{self._rv + 1:05x}"}
                k = obj_key(obj)
                if k in space:
                    errors.append(f"{kind} {k}")
                    continue
                obj = self._prepare_create_locked(kind, obj)
                rv = self._bump_locked()
                obj = json.loads(json.dumps(obj))
                md = obj.setdefault("metadata", {})
                md["resourceVersion"] = str(rv)
                md.setdefault("uid", f"uid-s{rv}")
                if "creationTimestamp" not in md:
                    import time as _time
                    md["creationTimestamp"] = _time.time()
                space[k] = obj
                self._journal_locked({"op": "set", "kind": kind, "ns": k[0],
                                      "name": k[1], "rv": rv, "obj": obj})
                self._emit_locked(kind, Event(ADDED, obj, rv))
                out.append(fastcopy(obj))
        if errors:
            raise AlreadyExists("; ".join(errors))
        return out

    def get(self, kind: str, namespace: str, name: str) -> dict:
        with self._lock:
            try:
                return fastcopy(self._data[kind][(namespace or "", name)])
            except KeyError:
                raise NotFound(f"{kind} {namespace}/{name}") from None

    def list(self, kind: str, namespace: Optional[str] = None,
             selector: Optional[Callable[[dict], bool]] = None
             ) -> tuple[list[dict], int]:
        """-> (items, listResourceVersion)."""
        with self._lock:
            items = []
            for (ns, _), obj in sorted(self._data.get(kind, {}).items()):
                if namespace is not None and ns != namespace:
                    continue
                if selector is not None and not selector(obj):
                    continue
                items.append(fastcopy(obj))
            return items, self._rv

    def update(self, kind: str, obj: dict, expect_rv: Optional[str] = None,
               owned: bool = False) -> dict:
        with self._lock:
            k = obj_key(obj)
            space = self._data.setdefault(kind, {})
            if k not in space:
                raise NotFound(f"{kind} {k}")
            current = space[k]
            if expect_rv is not None and current["metadata"]["resourceVersion"] != expect_rv:
                raise Conflict(f"{kind} {k}: rv {expect_rv} != "
                               f"{current['metadata']['resourceVersion']}")
            rv = self._bump_locked()
            if not owned:
                obj = json.loads(json.dumps(obj))
            md = obj.setdefault("metadata", {})
            md["resourceVersion"] = str(rv)
            # deletionTimestamp is SERVER-owned and sticky (apimachinery:
            # immutable once set): carry the stored value — a payload can
            # neither resurrect a terminating object by dropping it nor
            # destroy a live one by injecting it (which would bypass the
            # delete reactors and admission)
            cur_dt = (current.get("metadata") or {}).get("deletionTimestamp")
            if cur_dt is not None:
                md["deletionTimestamp"] = cur_dt
            else:
                md.pop("deletionTimestamp", None)
            if md.get("deletionTimestamp") and not md.get("finalizers"):
                # the last finalizer just came off a terminating object:
                # the update COMPLETES the graceful delete
                space.pop(k, None)
                self._journal_locked({"op": "del", "kind": kind,
                                      "ns": k[0], "name": k[1], "rv": rv})
                self._emit_locked(kind, Event(DELETED, obj, rv))
                return fastcopy(obj)
            space[k] = obj
            self._journal_locked({"op": "set", "kind": kind, "ns": k[0],
                                  "name": k[1], "rv": rv, "obj": obj})
            self._emit_locked(kind, Event(MODIFIED, obj, rv))
            return fastcopy(obj)

    def bind_many(self, bindings: list[tuple[str, str, str]]
                  ) -> list[Optional[str]]:
        """Apply many pod bindings in ONE lock pass: for each
        ``(namespace, name, node_name)`` set spec.nodeName if unset.
        Returns a per-item error string (or None on success) — successes
        commit even when siblings fail, exactly like N independent binding
        POSTs, minus N-1 round trips and lock acquisitions.

        This is the storage half of the bulk-binding fast path (reference:
        ``pkg/registry/core/pod/storage/storage.go`` BindingREST.Create,
        generalized to a batch — the reference has no bulk variant; its
        scheduler binds one pod per POST, which is exactly the per-pod
        round-trip cost this path removes)."""
        from kubernetes_tpu.metrics.registry import BULK_REQUESTS
        BULK_REQUESTS.inc({"endpoint": "pods/-/binding"})
        out: list[Optional[str]] = []
        with self._lock:
            space = self._data.setdefault("Pod", {})
            for ns, name, node_name in bindings:
                k = (ns or "", name)
                pod = space.get(k)
                if pod is None:
                    out.append(f"Pod {ns}/{name} not found")
                    continue
                if (pod.get("spec") or {}).get("nodeName"):
                    out.append("pod already bound")
                    continue
                # no expect_rv needed: the whole check-then-set runs under
                # the store lock, so no other writer can interleave
                rv = self._bump_locked()
                pod = fastcopy(pod)
                pod.setdefault("spec", {})["nodeName"] = node_name
                pod.setdefault("status", {}).setdefault("phase", "Pending")
                pod["metadata"]["resourceVersion"] = str(rv)
                space[k] = pod
                self._journal_locked({"op": "set", "kind": "Pod", "ns": k[0],
                                      "name": k[1], "rv": rv, "obj": pod})
                self._emit_locked("Pod", Event(MODIFIED, pod, rv))
                out.append(None)
        return out

    def update_status_many(self, kind: str, items: list[tuple[str, str, dict]]
                           ) -> list[Optional[str]]:
        """Apply many STATUS updates in ONE lock pass: for each
        ``(namespace, name, status)`` replace the object's status subtree.
        Returns a per-item error string (or None on success); successes
        commit even when siblings fail, exactly like N independent status
        PUTs minus N-1 round trips and lock acquisitions.

        No rv precondition: the kubelet owns its pods' status and already
        serializes per-pod writes (PodWorkers), so last-write-wins within
        one owner is the reference's status-manager semantics. This is the
        storage half of the kubemark status batcher — 500 hollow kubelets
        each PUTting Pending->Running transitions one at a time were the
        kubemark bottleneck."""
        from kubernetes_tpu.metrics.registry import BULK_REQUESTS
        BULK_REQUESTS.inc({"endpoint": "pods/-/status"})
        out: list[Optional[str]] = []
        with self._lock:
            space = self._data.setdefault(kind, {})
            for ns, name, status in items:
                k = (ns or "", name)
                cur = space.get(k)
                if cur is None:
                    out.append(f"{kind} {ns}/{name} not found")
                    continue
                rv = self._bump_locked()
                obj = fastcopy(cur)
                # detach from the caller's dict: DirectClient callers may
                # reuse/mutate their status template after the call, and the
                # stored object + emitted event must not change under them
                obj["status"] = fastcopy(status)
                obj["metadata"]["resourceVersion"] = str(rv)
                space[k] = obj
                self._journal_locked({"op": "set", "kind": kind, "ns": k[0],
                                      "name": k[1], "rv": rv, "obj": obj})
                self._emit_locked(kind, Event(MODIFIED, obj, rv))
                out.append(None)
        return out

    def heartbeat_many(self, items: list[tuple[str, dict]]
                       ) -> list[Optional[str]]:
        """Apply many NODE heartbeat status refreshes in ONE lock pass: for
        each ``(name, status_patch)`` merge the patch into the node's
        status — ``conditions`` merge BY TYPE (a Ready refresh replaces the
        Ready condition and leaves NetworkUnavailable & co alone; exactly
        what the per-node heartbeat's read-modify-write produced), every
        other key (addresses, daemonEndpoints, ...) replaces wholesale.
        Returns a per-item error string (or None); successes commit even
        when siblings fail, and each item gets its own resourceVersion +
        MODIFIED event — bulk and singleton heartbeats are
        indistinguishable to a watcher. Watch fan-out happens in one batch
        pass at the end (the hot cost at 10k-node fleet scale).

        No rv precondition: the kubelet owns its node's status and the
        fleet batcher serializes per-node writes, so last-write-wins
        within one owner — the update_status_many discipline."""
        from kubernetes_tpu.metrics.registry import BULK_REQUESTS
        BULK_REQUESTS.inc({"endpoint": "nodes/-/status"})
        out: list[Optional[str]] = []
        evs: list[Event] = []
        with self._lock:
            space = self._data.setdefault("Node", {})
            for name, patch in items:
                k = ("", name)
                cur = space.get(k)
                if cur is None:
                    out.append(f"Node {name} not found")
                    continue
                rv = self._bump_locked()
                obj = fastcopy(cur)
                st = obj.setdefault("status", {})
                patch = fastcopy(patch)
                for key, val in patch.items():
                    if key == "conditions":
                        by_type = {c.get("type"): c for c in val}
                        merged = [by_type.pop(c.get("type"), c)
                                  for c in st.get("conditions") or []]
                        st["conditions"] = merged + list(by_type.values())
                    else:
                        st[key] = val
                obj["metadata"]["resourceVersion"] = str(rv)
                space[k] = obj
                self._journal_locked({"op": "set", "kind": "Node", "ns": "",
                                      "name": name, "rv": rv, "obj": obj})
                evs.append(Event(MODIFIED, obj, rv))
                out.append(None)
            self._emit_many_locked("Node", evs)
        return out

    def renew_leases(self, namespace: str, items: list[tuple[str, float]]
                     ) -> list[Optional[str]]:
        """Bump ``spec.renewTime`` on many Leases in ONE lock pass: for
        each ``(name, renew_time)`` in ``namespace``. Returns per-item
        error string (or None); a missing Lease reports "not found"
        without failing its siblings (the fleet batcher bulk-creates the
        missing ones and renews them next period). Same per-item
        resourceVersion + MODIFIED-event discipline as N singleton
        updates, minus N-1 round trips; watch fan-out is one batch pass."""
        from kubernetes_tpu.metrics.registry import BULK_REQUESTS
        BULK_REQUESTS.inc({"endpoint": "leases/-/renew"})
        out: list[Optional[str]] = []
        evs: list[Event] = []
        with self._lock:
            space = self._data.setdefault("Lease", {})
            for name, renew_time in items:
                k = (namespace or "", name)
                cur = space.get(k)
                if cur is None:
                    out.append(f"Lease {namespace}/{name} not found")
                    continue
                rv = self._bump_locked()
                obj = fastcopy(cur)
                obj.setdefault("spec", {})["renewTime"] = float(renew_time)
                obj["metadata"]["resourceVersion"] = str(rv)
                space[k] = obj
                self._journal_locked({"op": "set", "kind": "Lease",
                                      "ns": k[0], "name": name, "rv": rv,
                                      "obj": obj})
                evs.append(Event(MODIFIED, obj, rv))
                out.append(None)
            self._emit_many_locked("Lease", evs)
        return out

    def delete(self, kind: str, namespace: str, name: str) -> dict:
        """Finalizer-aware deletion (apimachinery's graceful-deletion
        contract, ``registry.Store.Delete``): an object carrying
        ``metadata.finalizers`` is not removed — it gets a
        ``deletionTimestamp`` and persists (MODIFIED event) until the last
        finalizer is removed by whoever owns it (an update() dropping the
        final finalizer of a terminating object completes the delete).
        Objects without finalizers are removed immediately, as before."""
        import time as _time
        with self._lock:
            k = (namespace or "", name)
            space = self._data.setdefault(kind, {})
            if k not in space:
                raise NotFound(f"{kind} {namespace}/{name}")
            cur = space[k]
            md = cur.get("metadata") or {}
            if md.get("finalizers"):
                if md.get("deletionTimestamp"):
                    return fastcopy(cur)  # already terminating
                obj = fastcopy(cur)
                rv = self._bump_locked()
                obj["metadata"]["deletionTimestamp"] = _time.time()
                obj["metadata"]["resourceVersion"] = str(rv)
                space[k] = obj
                self._journal_locked({"op": "set", "kind": kind,
                                      "ns": k[0], "name": k[1], "rv": rv,
                                      "obj": obj})
                self._emit_locked(kind, Event(MODIFIED, obj, rv))
                return fastcopy(obj)
            obj = fastcopy(space.pop(k))
            rv = self._bump_locked()
            obj["metadata"]["resourceVersion"] = str(rv)
            self._journal_locked({"op": "del", "kind": kind, "ns": k[0],
                                  "name": k[1], "rv": rv})
            self._emit_locked(kind, Event(DELETED, obj, rv))
            return obj

    # ---- watch -----------------------------------------------------------

    def watch(self, kind: str, since_rv: int = 0) -> Watcher:
        """Watch events with rv > since_rv. Raises TooOld if the replay window
        no longer covers since_rv (caller must relist, Reflector-style) — or
        if the replay backlog alone would overflow the watcher's bounded
        queue (a relist hands the caller the same state cheaper than a
        replay that immediately evicts it)."""
        with self._lock:
            hist = self._history.get(kind, [])
            if since_rv < max(self._floor_rv, self._compacted.get(kind, 0)):
                raise TooOld(f"{kind} rv {since_rv} compacted")
            pending = [ev for ev in hist if ev.resource_version > since_rv]
            if len(pending) >= WATCH_QUEUE_MAX:
                raise TooOld(f"{kind} rv {since_rv}: replay backlog "
                             f"{len(pending)} exceeds watcher queue budget")
            q: queue.Queue = queue.Queue(maxsize=WATCH_QUEUE_MAX)
            for ev in pending:
                q.put_nowait(ev)
            shards = self._shards.setdefault(
                kind, [_WatchShard() for _ in range(WATCH_SHARDS)])
            self._watch_seq += 1
            shards[self._watch_seq % WATCH_SHARDS].add(q)
            self._set_watch_gauge(kind, shards)
            return Watcher(self, kind, q)

    def watch_stats(self) -> dict:
        """Front-door observability: live watcher counts, shard fan-out,
        cumulative slow-consumer drops, and the fan-out span (ns spent
        pushing events into watcher queues + events fanned) — the
        WatchStorm bench gates leader fan-out growth on ns/event."""
        with self._lock:
            shard_map = {k: list(v) for k, v in self._shards.items()}
            fanout_ns, fanout_events = self._fanout_ns, self._fanout_events
        watchers: dict[str, int] = {}
        drops: dict[str, int] = {}
        for kind, shards in shard_map.items():
            n = d = 0
            for s in shards:
                sn, sd = s.stats()
                n, d = n + sn, d + sd
            if n:
                watchers[kind] = n
            if d:
                drops[kind] = d
        return {"watchers": watchers,
                "watchersTotal": sum(watchers.values()),
                "shardsPerKind": WATCH_SHARDS,
                "queueMax": WATCH_QUEUE_MAX,
                "drops": drops, "dropsTotal": sum(drops.values()),
                "fanoutNs": fanout_ns, "fanoutEvents": fanout_events}

    # ---- checkpoint ------------------------------------------------------

    def save(self, path: str):
        with self._lock:
            blob = {kind: list(space.values()) for kind, space in self._data.items()}
            data = {"rv": self._rv, "data": blob}
        with open(path, "w") as f:
            json.dump(data, f)

    def load(self, path: str):
        with open(path) as f:
            data = json.load(f)
        with self._lock:
            self._install_state_locked(
                data["rv"], {kind: {obj_key(o): o for o in objs}
                             for kind, objs in data["data"].items()})

    def _install_state_locked(self, rv: int, data: dict) -> None:
        """Replace the whole store state (checkpoint restore / replication
        snapshot install). No replay history survives: every kind —
        including kinds absent from the blob — is compacted up to the
        installed rv, so stale watchers get TooOld and relist instead of
        silently missing pre-install events. Live watch streams are
        invalidated too (an object absent from the blob never emits
        DELETED; a connected informer would retain it as a phantom
        forever), the ClusterIP allocator re-seeds past installed
        Services, and durable stores fold the new state into the
        snapshot file."""
        self._rv = rv
        self._data = data
        self._history.clear()
        self._compacted = {}
        self._floor_rv = self._rv
        for shards in self._shards.values():
            for shard in shards:
                shard.invalidate(self._rv)
        self._reseed_service_ips_locked()
        if self._wal is not None:
            self._compact_wal_locked()

    def close(self):
        with self._lock:
            self._closed = True  # a deferred restore must not reopen
            if self._wal is not None:
                self._wal.close()
                self._wal = None

    @property
    def resource_version(self) -> int:
        with self._lock:
            return self._rv
