"""Recorders — turn a live run's artifacts into replayable traces.

Two capture paths, both offline (they read files a run already wrote,
never touch a live store):

* :func:`trace_from_wal` replays a durable store's ``wal.jsonl`` into a
  trace: every journaled mutation becomes a timed event, nodes present
  before the first pod op become the manifest fleet, and the active
  chaos seed (if the run had one) rides the manifest so the replay
  faces the same fault schedule. Any failed bench window or production
  incident with a WAL on disk is now a scenario file.

* :func:`trace_from_bundle` converts an audit repro bundle (the JSON
  the invariant auditor writes on every confirmed violation) into a
  trace: the pending pod batch at violation time becomes a correlated
  create burst, and the bundle's ``chaosSeed`` arms the same schedule —
  the "replay the incident" button the bundle always promised.
"""

from __future__ import annotations

import json
from typing import Optional

from kubernetes_tpu.scenario.generate import _node_template, _pod_template
from kubernetes_tpu.scenario.trace import (Trace, TraceEvent,
                                           TraceFormatError, TraceManifest)

#: kinds a recorded trace replays; everything else in a WAL (leases,
#: events, configmaps...) is control-plane chatter the scheduler stack
#: regenerates itself — replaying it would fight the live controllers
REPLAYED_KINDS = ("Pod", "Node")


def _strip_server_fields(obj: dict) -> dict:
    """Drop server-minted metadata so the replay target mints its own
    (a recorded uid/resourceVersion would collide or confuse)."""
    obj = json.loads(json.dumps(obj))  # deep copy, JSON-safe
    md = obj.get("metadata") or {}
    for k in ("uid", "resourceVersion", "creationTimestamp",
              "deletionTimestamp", "managedFields"):
        md.pop(k, None)
    return obj


def trace_from_wal(wal_path: str, name: str = "wal-capture",
                   spacing_s: float = 0.05,
                   chaos_seed: Optional[int] = None,
                   chaos_profile: str = "churn",
                   max_events: int = 5000) -> Trace:
    """Parse a durable store's ``wal.jsonl`` into a trace.

    WAL entries carry rv order but no wall time; creates are offset by
    their objects' ``creationTimestamp`` where present, and everything
    else advances by ``spacing_s`` — order is exact, pacing is a
    faithful-enough reconstruction for replay.
    """
    entries = []
    with open(wal_path, "rb") as f:
        for line in f:
            if not line.endswith(b"\n"):
                break  # torn tail: same trust boundary as WAL restore
            try:
                e = json.loads(line)
            except ValueError:
                break
            if e.get("kind") in REPLAYED_KINDS:
                entries.append(e)
    if not entries:
        raise TraceFormatError(f"{wal_path}: no replayable Pod/Node "
                               "entries")
    entries = entries[:max_events]

    fleet: list = []
    events: list[TraceEvent] = []
    seen: set = set()
    saw_pod = False
    t = 0.0
    t0_wall: Optional[float] = None
    for e in entries:
        kind, ns, nm = e["kind"], e.get("ns", ""), e["name"]
        key = (kind, ns, nm)
        obj = e.get("obj")
        if e["op"] == "set":
            verb = "update" if key in seen else "create"
            seen.add(key)
            if kind == "Node" and not saw_pod and verb == "create":
                # pre-existing fleet: seeded before replay starts
                fleet.append({"obj": _strip_server_fields(obj)})
                continue
            if kind == "Pod":
                saw_pod = True
            ct = ((obj or {}).get("metadata") or {}) \
                .get("creationTimestamp")
            if verb == "create" and isinstance(ct, (int, float)):
                if t0_wall is None:
                    t0_wall = float(ct)
                t = max(t, float(ct) - t0_wall)
            else:
                t += spacing_s
            events.append(TraceEvent(
                at_s=round(t, 4), verb=verb, kind=kind, ns=ns, name=nm,
                obj=_strip_server_fields(obj) if obj else None,
                phase="recorded"))
        elif e["op"] == "del":
            seen.discard(key)
            t += spacing_s
            events.append(TraceEvent(
                at_s=round(t, 4), verb="delete", kind=kind, ns=ns,
                name=nm, phase="recorded"))
    chaos = ({"seed": int(chaos_seed), "profile": chaos_profile}
             if chaos_seed is not None else None)
    manifest = TraceManifest(
        name=name, seed=int(chaos_seed or 0),
        description=f"captured from WAL {wal_path} "
                    f"({len(events)} events)",
        fleet=fleet, templates={}, chaos=chaos)
    return Trace(manifest, events)


def trace_from_bundle(bundle, name: Optional[str] = None,
                      nodes: int = 8, spacing_s: float = 0.05) -> Trace:
    """Convert an audit repro bundle (path or parsed dict) to a trace.

    The bundle records the pending pod batch (ns/name keys) and the
    chaos seed at violation time, not full specs — the conversion pairs
    each key with the standard heterogeneous pod template and replays
    the batch as one correlated burst under the same fault schedule.
    """
    if isinstance(bundle, str):
        with open(bundle) as f:
            bundle = json.load(f)
    batch = bundle.get("podBatch") or []
    if not batch:
        raise TraceFormatError("bundle carries no podBatch to replay")
    import random
    rng = random.Random(0)
    templates = {"node": _node_template(),
                 "incident-pod": _pod_template(rng, app="incident")}
    events: list[TraceEvent] = []
    t = 0.0
    for key in batch:
        ns, _, nm = key.partition("/")
        events.append(TraceEvent(
            at_s=round(t, 4), verb="create", kind="Pod",
            ns=ns or "default", name=nm, template="incident-pod",
            phase="incident"))
        t += spacing_s
    seed = bundle.get("chaosSeed")
    chaos = ({"seed": int(seed), "profile": "churn"}
             if seed is not None else None)
    manifest = TraceManifest(
        name=name or f"bundle-{bundle.get('invariant', 'incident')}",
        seed=int(seed or 0),
        description=(f"audit bundle replay: {bundle.get('invariant')} "
                     f"at rv {bundle.get('resourceVersion')} "
                     f"({len(batch)} pending pods)"),
        fleet=[{"template": "node", "count": int(nodes),
                "prefix": "sn"}],
        templates=templates, chaos=chaos)
    return Trace(manifest, events)
