"""Cluster time machine: trace-driven scenario engine.

Production-shaped workloads as versioned, replayable JSONL traces —
generated (diurnal waves, rolling updates, job storms, tenant
onboarding), recorded from live runs (WAL / audit bundles), and played
back through a real clientset by a time-warped driver.
"""

from kubernetes_tpu.scenario.driver import SCENARIO_CONFIGMAP, ScenarioDriver
from kubernetes_tpu.scenario.generate import BUILTINS, builtin_trace
from kubernetes_tpu.scenario.record import trace_from_bundle, trace_from_wal
from kubernetes_tpu.scenario.trace import (TRACE_VERSION, Trace, TraceEvent,
                                           TraceFormatError, TraceManifest)

__all__ = [
    "SCENARIO_CONFIGMAP", "ScenarioDriver", "BUILTINS", "builtin_trace",
    "trace_from_bundle", "trace_from_wal", "TRACE_VERSION", "Trace",
    "TraceEvent", "TraceFormatError", "TraceManifest",
]
