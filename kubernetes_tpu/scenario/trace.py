"""Trace format — the cluster time machine's on-disk scenario schema.

A trace is one JSONL file: line 1 is the manifest header (seed, node
fleet spec, object templates, chaos profile, SLO gates), every following
line is one event (``at_s`` offset from replay start, verb, object
template ref or inline object, tenant, phase, optional chaos-fault ref).
Serialization is canonical (sorted keys, no whitespace), so the SAME
trace always produces the SAME bytes: save -> load -> save is bit-equal,
and generator determinism is testable as string equality.

The format is versioned: a loader refuses a version it does not know
instead of guessing — a silently misread incident trace would "replay"
something other than the incident.
"""

from __future__ import annotations

import copy
import json
from dataclasses import dataclass, field
from typing import Iterator, Optional

TRACE_KIND = "ktpu-trace"
TRACE_VERSION = 1

#: verbs a trace event may carry (the driver rejects anything else at
#: load time, not at dispatch time — a typo'd verb fails the whole file)
VERBS = ("create", "update", "delete")

TENANT_LABEL = "kubernetes-tpu.io/scenario-tenant"


class TraceFormatError(ValueError):
    """The file is not a loadable trace (unknown version/kind, bad verb,
    malformed line). Deliberately loud: replaying a misparsed incident
    would manufacture false evidence."""


@dataclass
class TraceEvent:
    """One timed action against the cluster.

    ``template`` names a manifest template the driver materializes (with
    this event's name/ns/tenant stamped in); ``obj`` is an inline object
    for recorded traces whose specs came from a live WAL. delete events
    need neither.
    """
    at_s: float
    verb: str
    kind: str  # Pod | Node
    ns: str
    name: str
    template: str = ""
    tenant: str = ""
    phase: str = ""
    fault: str = ""  # chaos-fault site ref (informational; the schedule
    #                  itself rides the manifest's chaos block)
    obj: Optional[dict] = None

    def key(self) -> str:
        return f"{self.kind}:{self.ns}/{self.name}"

    def to_dict(self) -> dict:
        d = {"at_s": self.at_s, "verb": self.verb, "kind": self.kind,
             "ns": self.ns, "name": self.name}
        for k in ("template", "tenant", "phase", "fault"):
            v = getattr(self, k)
            if v:
                d[k] = v
        if self.obj is not None:
            d["obj"] = self.obj
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "TraceEvent":
        verb = d.get("verb")
        if verb not in VERBS:
            raise TraceFormatError(f"unknown event verb {verb!r} "
                                   f"(known: {', '.join(VERBS)})")
        return cls(at_s=float(d["at_s"]), verb=verb, kind=d["kind"],
                   ns=d.get("ns", "default"), name=d["name"],
                   template=d.get("template", ""),
                   tenant=d.get("tenant", ""), phase=d.get("phase", ""),
                   fault=d.get("fault", ""), obj=d.get("obj"))


@dataclass
class TraceManifest:
    """Line 1 of the file: everything the driver needs BEFORE t=0."""
    name: str
    seed: int = 0
    description: str = ""
    #: node fleet seeded before replay starts. Entries are either
    #: ``{"template": ref, "count": n, "prefix": p}`` (materialized) or
    #: ``{"obj": {...}}`` (inline, e.g. recorded from a WAL).
    fleet: list = field(default_factory=list)
    #: named object templates events reference by ``template``
    templates: dict = field(default_factory=dict)
    #: ``{"profile": ..., "seed": ...}`` — arm a FaultSchedule on the
    #: scheduler's transport for the replay window; None = no chaos
    chaos: Optional[dict] = None
    #: hard gates the bench case applies to the replay's result JSON
    #: (check_slo_gates vocabulary: p99AttemptLatencySeconds etc.)
    slo_gates: dict = field(default_factory=dict)

    def to_dict(self) -> dict:
        d = {"kind": TRACE_KIND, "version": TRACE_VERSION,
             "name": self.name, "seed": self.seed,
             "fleet": self.fleet, "templates": self.templates,
             "sloGates": self.slo_gates}
        if self.description:
            d["description"] = self.description
        if self.chaos is not None:
            d["chaos"] = self.chaos
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "TraceManifest":
        if d.get("kind") != TRACE_KIND:
            raise TraceFormatError(
                f"not a {TRACE_KIND} file (kind={d.get('kind')!r})")
        v = d.get("version")
        if v != TRACE_VERSION:
            raise TraceFormatError(
                f"unknown trace version {v!r} (this build reads "
                f"version {TRACE_VERSION}); refusing to guess")
        return cls(name=d.get("name", "<unnamed>"),
                   seed=int(d.get("seed", 0)),
                   description=d.get("description", ""),
                   fleet=list(d.get("fleet") or []),
                   templates=dict(d.get("templates") or {}),
                   chaos=d.get("chaos"),
                   slo_gates=dict(d.get("sloGates") or {}))


def _canon(d: dict) -> str:
    return json.dumps(d, sort_keys=True, separators=(",", ":"))


class Trace:
    """Manifest + time-ordered events, loadable/saveable/canonical."""

    def __init__(self, manifest: TraceManifest,
                 events: list[TraceEvent]):
        self.manifest = manifest
        # stable sort: events at the same offset keep generation order,
        # so a sorted file round-trips bit-identically
        self.events = sorted(events, key=lambda e: e.at_s)

    # ---- serialization ---------------------------------------------------

    def to_lines(self) -> list[str]:
        return ([_canon(self.manifest.to_dict())]
                + [_canon(e.to_dict()) for e in self.events])

    def save(self, path: str) -> str:
        from kubernetes_tpu.utils.atomicio import atomic_write
        atomic_write(path, "\n".join(self.to_lines()) + "\n")
        return path

    @classmethod
    def loads(cls, text: str) -> "Trace":
        lines = [ln for ln in text.splitlines() if ln.strip()]
        if not lines:
            raise TraceFormatError("empty trace file")
        try:
            head = json.loads(lines[0])
        except ValueError as e:
            raise TraceFormatError(f"manifest line is not JSON: {e}")
        manifest = TraceManifest.from_dict(head)
        events = []
        for i, ln in enumerate(lines[1:], start=2):
            try:
                events.append(TraceEvent.from_dict(json.loads(ln)))
            except TraceFormatError:
                raise
            except (ValueError, KeyError, TypeError) as e:
                raise TraceFormatError(f"bad event at line {i}: {e}")
        return cls(manifest, events)

    @classmethod
    def load(cls, path: str) -> "Trace":
        with open(path) as f:
            return cls.loads(f.read())

    def __eq__(self, other) -> bool:
        return (isinstance(other, Trace)
                and self.to_lines() == other.to_lines())

    # ---- derived views ---------------------------------------------------

    def duration_s(self) -> float:
        return self.events[-1].at_s if self.events else 0.0

    def phases(self) -> list[str]:
        """Phase labels in first-appearance order."""
        seen: dict = {}
        for e in self.events:
            seen.setdefault(e.phase or "default", None)
        return list(seen)

    def namespaces(self) -> list[str]:
        return sorted({e.ns for e in self.events if e.kind == "Pod"})

    def resident_pods(self) -> dict:
        """(ns, name) -> creating event, for pods created and never
        deleted by the trace — the set a replay gates 100% binding on."""
        live: dict = {}
        for e in self.events:
            if e.kind != "Pod":
                continue
            if e.verb == "create":
                live[(e.ns, e.name)] = e
            elif e.verb == "delete":
                live.pop((e.ns, e.name), None)
        return live

    def describe(self) -> dict:
        verbs: dict = {}
        phases: dict = {}
        for e in self.events:
            verbs[e.verb] = verbs.get(e.verb, 0) + 1
            ph = e.phase or "default"
            phases[ph] = phases.get(ph, 0) + 1
        return {"name": self.manifest.name,
                "version": TRACE_VERSION,
                "seed": self.manifest.seed,
                "description": self.manifest.description,
                "events": len(self.events),
                "duration_s": round(self.duration_s(), 3),
                "fleet_nodes": len(self.fleet_nodes()),
                "verbs": verbs, "phases": phases,
                "tenants": sorted({e.tenant for e in self.events
                                   if e.tenant}),
                "resident_pods": len(self.resident_pods()),
                "chaos": self.manifest.chaos,
                "sloGates": self.manifest.slo_gates}

    # ---- materialization -------------------------------------------------

    def _from_template(self, ref: str, kind: str, ns: str, name: str,
                       tenant: str) -> dict:
        tmpl = self.manifest.templates.get(ref)
        if tmpl is None:
            raise TraceFormatError(f"event references unknown template "
                                   f"{ref!r}")
        obj = copy.deepcopy(tmpl)
        md = obj.setdefault("metadata", {})
        md["name"] = name
        if kind == "Pod":
            md["namespace"] = ns
        elif kind == "Node":
            md.setdefault("labels", {})["kubernetes.io/hostname"] = name
        if tenant:
            md.setdefault("labels", {})[TENANT_LABEL] = tenant
        return obj

    def materialize(self, ev: TraceEvent) -> dict:
        """The full object dict an event creates/updates."""
        if ev.obj is not None:
            obj = copy.deepcopy(ev.obj)
            md = obj.setdefault("metadata", {})
            md.setdefault("name", ev.name)
            if ev.kind == "Pod":
                md.setdefault("namespace", ev.ns)
            return obj
        return self._from_template(ev.template or "pod", ev.kind,
                                   ev.ns, ev.name, ev.tenant)

    def fleet_nodes(self) -> list[dict]:
        """Node objects to seed before replay starts."""
        out: list[dict] = []
        for entry in self.manifest.fleet:
            if "obj" in entry:
                out.append(copy.deepcopy(entry["obj"]))
                continue
            ref = entry.get("template", "node")
            prefix = entry.get("prefix", "sn")
            for i in range(int(entry.get("count", 0))):
                out.append(self._from_template(
                    ref, "Node", "", f"{prefix}{i}",
                    entry.get("tenant", "")))
        return out

    def __iter__(self) -> Iterator[TraceEvent]:
        return iter(self.events)

    def __len__(self) -> int:
        return len(self.events)
