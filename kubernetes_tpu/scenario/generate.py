"""Builtin scenario generators — production-shaped workloads as pure
functions ``(params, seed) -> Trace``.

Every generator derives ALL randomness from one ``random.Random(seed)``
and rounds every timestamp to 4 decimals, so the same (params, seed)
produces the same bytes on every machine — the committed golden fixture
under ``benchmarks/config/`` pins this across toolchain drift.

The template pools reuse ``benchmarks/workloads.py`` shapes (same
heterogeneous capacities/labels the existing benches schedule), so a
scenario's pods stress the same filter/score paths as the synthetic
churn they replace — just with correlated arrival times instead of a
uniform drip.
"""

from __future__ import annotations

import math
import random

from kubernetes_tpu.scenario.trace import Trace, TraceEvent, TraceManifest

_ZONES = [f"zone-{i}" for i in range(4)]


def _node_template(cpu: str = "32", mem: str = "128Gi",
                   pods: str = "110") -> dict:
    # same shape make_node(...).obj().to_dict() produces (the driver
    # stamps metadata.name + the hostname label at materialize time)
    return {"kind": "Node", "metadata": {"labels": {}},
            "spec": {},
            "status": {"capacity": {"cpu": cpu, "memory": mem,
                                    "pods": pods},
                       "allocatable": {"cpu": cpu, "memory": mem,
                                       "pods": pods}}}


def _pod_template(rng: random.Random, app: str) -> dict:
    """One heterogeneous pod spec drawn from the workloads.py request
    pool (cpu/mem choices match mixed_heterogeneous)."""
    return {"kind": "Pod",
            "metadata": {"labels": {"app": app}},
            "spec": {"schedulerName": "default-scheduler",
                     "restartPolicy": "Always",
                     "containers": [{
                         "name": "c0",
                         "resources": {"requests": {
                             "cpu": rng.choice(
                                 ["100m", "250m", "500m", "1"]),
                             "memory": rng.choice(
                                 ["128Mi", "512Mi", "1Gi"])}}}]},
            "status": {"phase": "Pending"}}


def _templates(rng: random.Random, n_pod_templates: int = 4) -> dict:
    out = {"node": _node_template()}
    for i in range(n_pod_templates):
        out[f"pod-t{i}"] = _pod_template(rng, app=f"svc-{i}")
    return out


def _pick(rng: random.Random, n_pod_templates: int) -> str:
    return f"pod-t{rng.randrange(n_pod_templates)}"


def _r(t: float) -> float:
    return round(t, 4)


def diurnal_burst(params: dict | None = None, seed: int = 0) -> Trace:
    """Sinusoidal arrival waves + superimposed burst noise: the diurnal
    load curve a production scheduler actually faces. Wave pods arrive
    at the sinusoid's inverse-CDF quantiles (dense at the crest, sparse
    in the trough) with per-pod jitter; each burst dumps a correlated
    clump within ~100ms."""
    p = {"pods": 120, "nodes": 24, "cycles": 2, "period_s": 6.0,
         "bursts": 2, "burst_pods": 24, "templates": 4,
         "p99_slo_s": None, **(params or {})}
    rng = random.Random(seed)
    nt = int(p["templates"])
    templates = _templates(rng, nt)
    duration = float(p["period_s"]) * int(p["cycles"])
    events: list[TraceEvent] = []
    # inverse-CDF over intensity 1 + 0.8*sin: integrate on a fine grid,
    # then place pod i at the time where cumulative mass hits (i+.5)/N
    grid = 2048
    cum = [0.0]
    for g in range(grid):
        t = duration * (g + 0.5) / grid
        lam = 1.0 + 0.8 * math.sin(2 * math.pi * t / float(p["period_s"]))
        cum.append(cum[-1] + lam)
    total = cum[-1]
    n = int(p["pods"])
    for i in range(n):
        target = (i + 0.5) / n * total
        g = next(gi for gi in range(grid) if cum[gi + 1] >= target)
        t = duration * (g + rng.random()) / grid
        cycle = min(int(t // float(p["period_s"])), int(p["cycles"]) - 1)
        events.append(TraceEvent(
            at_s=_r(t), verb="create", kind="Pod", ns="default",
            name=f"dw-{i}", template=_pick(rng, nt),
            phase=f"wave-{cycle}"))
    for b in range(int(p["bursts"])):
        # bursts land near the crest of a cycle picked per-burst
        cycle = rng.randrange(int(p["cycles"]))
        t0 = (cycle + 0.25) * float(p["period_s"]) \
            + rng.uniform(-0.2, 0.2) * float(p["period_s"])
        t0 = min(max(t0, 0.0), duration)
        for j in range(int(p["burst_pods"])):
            events.append(TraceEvent(
                at_s=_r(t0 + rng.random() * 0.1), verb="create",
                kind="Pod", ns="default", name=f"db-{b}-{j}",
                template=_pick(rng, nt), phase=f"burst-{b}"))
    gates = {}
    if p["p99_slo_s"] is not None:
        gates["p99AttemptLatencySeconds"] = float(p["p99_slo_s"])
    manifest = TraceManifest(
        name="diurnal-burst", seed=seed,
        description=(f"{n} wave pods over {int(p['cycles'])} sinusoid "
                     f"cycles + {int(p['bursts'])} correlated bursts of "
                     f"{int(p['burst_pods'])}"),
        fleet=[{"template": "node", "count": int(p["nodes"]),
                "prefix": "sn"}],
        templates=templates, slo_gates=gates)
    return Trace(manifest, events)


def rolling_update(params: dict | None = None, seed: int = 0) -> Trace:
    """Controller-driven rollout: the old ReplicaSet's pods exist from
    t=0, then create+delete streams shaped by maxSurge/maxUnavailable
    walk the fleet to the new generation — the create/delete correlation
    no Poisson churn produces."""
    p = {"replicas": 24, "nodes": 12, "max_surge": 4,
         "max_unavailable": 2, "step_s": 0.4, "templates": 2,
         **(params or {})}
    rng = random.Random(seed)
    nt = int(p["templates"])
    templates = _templates(rng, nt)
    events: list[TraceEvent] = []
    n = int(p["replicas"])
    for i in range(n):
        events.append(TraceEvent(
            at_s=_r(rng.random() * 0.2), verb="create", kind="Pod",
            ns="default", name=f"old-{i}", template=_pick(rng, nt),
            phase="pre"))
    surge, unavail = int(p["max_surge"]), int(p["max_unavailable"])
    created = deleted = 0
    t = 1.0  # old generation gets a beat to bind before the rollout
    step = 0
    while deleted < n:
        # surge phase: bring up new pods (bounded by maxSurge ahead)
        while created < n and created - deleted < surge:
            events.append(TraceEvent(
                at_s=_r(t + rng.random() * 0.05), verb="create",
                kind="Pod", ns="default", name=f"new-{created}",
                template=_pick(rng, nt), phase=f"roll-{step // 4}"))
            created += 1
        # drain phase: take down old pods (bounded by maxUnavailable)
        for _ in range(min(unavail, created - deleted, n - deleted)):
            events.append(TraceEvent(
                at_s=_r(t + 0.05 + rng.random() * 0.05), verb="delete",
                kind="Pod", ns="default", name=f"old-{deleted}",
                phase=f"roll-{step // 4}"))
            deleted += 1
        t += float(p["step_s"])
        step += 1
    manifest = TraceManifest(
        name="rolling-update", seed=seed,
        description=(f"{n}-replica rollout, maxSurge={surge} "
                     f"maxUnavailable={unavail}"),
        fleet=[{"template": "node", "count": int(p["nodes"]),
                "prefix": "sn"}],
        templates=templates)
    return Trace(manifest, events)


def job_waves(params: dict | None = None, seed: int = 0) -> Trace:
    """Batch job storms: waves of short-lived jobs created together and
    deleted together ``lifetime_s`` later. The final wave stays resident
    so a replay still has a 100%-bound gate to hold."""
    p = {"waves": 3, "jobs_per_wave": 16, "nodes": 12,
         "wave_interval_s": 2.0, "lifetime_s": 1.5, "templates": 2,
         **(params or {})}
    rng = random.Random(seed)
    nt = int(p["templates"])
    templates = _templates(rng, nt)
    events: list[TraceEvent] = []
    waves = int(p["waves"])
    for w in range(waves):
        t0 = w * float(p["wave_interval_s"])
        for j in range(int(p["jobs_per_wave"])):
            name = f"job-{w}-{j}"
            events.append(TraceEvent(
                at_s=_r(t0 + rng.random() * 0.15), verb="create",
                kind="Pod", ns="jobs", name=name,
                template=_pick(rng, nt), phase=f"jobwave-{w}"))
            if w < waves - 1:  # final wave stays resident
                events.append(TraceEvent(
                    at_s=_r(t0 + float(p["lifetime_s"])
                            + rng.random() * 0.15),
                    verb="delete", kind="Pod", ns="jobs", name=name,
                    phase=f"jobwave-{w}"))
    manifest = TraceManifest(
        name="job-waves", seed=seed,
        description=(f"{waves} waves x {int(p['jobs_per_wave'])} jobs, "
                     f"lifetime {p['lifetime_s']}s"),
        fleet=[{"template": "node", "count": int(p["nodes"]),
                "prefix": "sn"}],
        templates=templates)
    return Trace(manifest, events)


def tenant_onboarding(params: dict | None = None, seed: int = 0) -> Trace:
    """New tenants land on a LIVE fleet: each onboarding is one burst of
    creates into the tenant's namespace, staggered tenant-by-tenant, on
    top of a small steady background."""
    p = {"tenants": 3, "pods_per_tenant": 12, "background_pods": 8,
         "nodes": 12, "stagger_s": 1.5, "templates": 2,
         **(params or {})}
    rng = random.Random(seed)
    nt = int(p["templates"])
    templates = _templates(rng, nt)
    events: list[TraceEvent] = []
    duration = int(p["tenants"]) * float(p["stagger_s"]) + 1.0
    for i in range(int(p["background_pods"])):
        events.append(TraceEvent(
            at_s=_r(rng.random() * duration), verb="create", kind="Pod",
            ns="default", name=f"bg-{i}", template=_pick(rng, nt),
            phase="background"))
    for ten in range(int(p["tenants"])):
        t0 = 0.5 + ten * float(p["stagger_s"])
        for i in range(int(p["pods_per_tenant"])):
            events.append(TraceEvent(
                at_s=_r(t0 + rng.random() * 0.2), verb="create",
                kind="Pod", ns=f"tenant-{ten}", name=f"tp-{ten}-{i}",
                template=_pick(rng, nt), tenant=f"tenant-{ten}",
                phase=f"onboard-{ten}"))
    manifest = TraceManifest(
        name="tenant-onboarding", seed=seed,
        description=(f"{int(p['tenants'])} tenant onboarding bursts of "
                     f"{int(p['pods_per_tenant'])} pods onto a live "
                     "fleet"),
        fleet=[{"template": "node", "count": int(p["nodes"]),
                "prefix": "sn"}],
        templates=templates)
    return Trace(manifest, events)


def autoscaler_thrash(params: dict | None = None, seed: int = 0) -> Trace:
    """Scale-up/scale-down oscillation: bursts of pending pods big enough
    to overflow the base fleet arrive, bind, then vanish almost entirely a
    beat later — the arrival pattern that whipsaws an autoscaler between
    "add nodes NOW" and "this capacity is provably unneeded" every period.
    A small resident floor keeps utilization non-zero so scale-down is a
    judgment call, not a no-op; ``survivors`` pods of each burst stay
    behind so consecutive swings compound instead of resetting."""
    p = {"swings": 4, "burst_pods": 24, "survivors": 2, "floor_pods": 6,
         "nodes": 6, "period_s": 2.0, "templates": 4, **(params or {})}
    rng = random.Random(seed)
    nt = int(p["templates"])
    templates = _templates(rng, nt)
    events: list[TraceEvent] = []
    for i in range(int(p["floor_pods"])):
        events.append(TraceEvent(
            at_s=_r(rng.random() * 0.2), verb="create", kind="Pod",
            ns="default", name=f"floor-{i}", template=_pick(rng, nt),
            phase="floor"))
    period = float(p["period_s"])
    burst = int(p["burst_pods"])
    survivors = min(int(p["survivors"]), burst)
    for s in range(int(p["swings"])):
        t0 = 0.5 + s * period
        for j in range(burst):
            name = f"thrash-{s}-{j}"
            events.append(TraceEvent(
                at_s=_r(t0 + rng.random() * 0.15), verb="create",
                kind="Pod", ns="default", name=name,
                template=_pick(rng, nt), phase=f"swing-{s}-up"))
            if j >= survivors:
                # the collapse: most of the burst evaporates mid-period,
                # flipping the fleet from overflow to under-utilization
                events.append(TraceEvent(
                    at_s=_r(t0 + 0.5 * period + rng.random() * 0.15),
                    verb="delete", kind="Pod", ns="default", name=name,
                    phase=f"swing-{s}-down"))
    manifest = TraceManifest(
        name="autoscaler-thrash", seed=seed,
        description=(f"{int(p['swings'])} scale-up/down swings of "
                     f"{burst} pods ({survivors} survive each) over a "
                     f"{int(p['floor_pods'])}-pod floor"),
        fleet=[{"template": "node", "count": int(p["nodes"]),
                "prefix": "sn"}],
        templates=templates)
    return Trace(manifest, events)


def smoke(params: dict | None = None, seed: int = 0) -> Trace:
    """The committed golden fixture: a small diurnal-burst trace sized
    for tests and ``BENCH_SCENARIO=builtin:smoke``."""
    p = {"pods": 24, "nodes": 8, "cycles": 2, "period_s": 2.0,
         "bursts": 1, "burst_pods": 8, **(params or {})}
    t = diurnal_burst(p, seed=seed)
    t.manifest.name = "smoke"
    return t


BUILTINS = {
    "diurnal-burst": diurnal_burst,
    "rolling-update": rolling_update,
    "job-waves": job_waves,
    "tenant-onboarding": tenant_onboarding,
    "autoscaler-thrash": autoscaler_thrash,
    "smoke": smoke,
}


def builtin_trace(name: str, seed: int = 0,
                  params: dict | None = None) -> Trace:
    """Resolve a builtin by name — the ``builtin:<name>`` half of
    ``BENCH_SCENARIO`` and the ``ktpu scenario generate`` catalog."""
    fn = BUILTINS.get(name)
    if fn is None:
        raise KeyError(f"unknown builtin scenario {name!r} "
                       f"(catalog: {', '.join(sorted(BUILTINS))})")
    return fn(params, seed=seed)
