"""Scenario driver — the time-warped trace player.

Pushes a trace's events through a real clientset against a connected
apiserver+scheduler stack. Time rides an injected
:class:`~kubernetes_tpu.utils.clock.Clock` (KTL003: a FakeClock test can
replay without sleeping) and a ``speed`` warp factor: 1.0 replays at the
recorded pace, ``N`` compresses it N-fold, and ``0`` dispatches as fast
as the transport accepts. Every event's dispatch skew (how late it ran
vs its warped offset) is stamped into ``scenario_dispatch_skew_seconds``;
every resident pod's create-to-bound latency lands in
``scenario_attempt_latency_seconds`` labeled by trace phase — the
per-phase p99 the scenario SLO gates read.

While running, the driver publishes a ``kubernetes-tpu-scenario-status``
ConfigMap (via the shared ``upsert_configmap``, KTL006) that ``ktpu
status`` renders as the "Scenario:" line.
"""

from __future__ import annotations

import json
import threading

from kubernetes_tpu.metrics.registry import (SCENARIO_ATTEMPT,
                                             SCENARIO_EVENTS,
                                             SCENARIO_SKEW)
from kubernetes_tpu.scenario.trace import Trace, TraceEvent
from kubernetes_tpu.utils.clock import REAL_CLOCK, Clock

SCENARIO_CONFIGMAP = "kubernetes-tpu-scenario-status"

_PLURALS = {"Pod": "pods", "Node": "nodes"}


class ScenarioDriver:
    """One replay of one trace through one clientset."""

    def __init__(self, client, trace: Trace, *,
                 clock: Clock = REAL_CLOCK, speed: float = 1.0,
                 publish: bool = True, status_namespace: str = "default",
                 bind_timeout_s: float = 120.0,
                 poll_interval_s: float = 0.1,
                 publish_every: int = 25,
                 log=lambda *a: None):
        self.client = client
        self.trace = trace
        self.clock = clock
        self.speed = float(speed)
        self.publish = publish
        self.status_namespace = status_namespace
        self.bind_timeout_s = float(bind_timeout_s)
        self.poll_interval_s = float(poll_interval_s)
        self.publish_every = int(publish_every)
        self.log = log
        self._stop = threading.Event()
        self._state = "idle"
        self._phase = ""
        self._dispatched = 0
        self._skew_max = 0.0
        self._bound = 0
        self._resident_total = len(trace.resident_pods())

    # ---- public ----------------------------------------------------------

    def plan(self) -> list[str]:
        """The deterministic dispatch order for this trace — pure data,
        no I/O. Two loads of the same bytes MUST plan identically (the
        bench's determinism gate compares these)."""
        return [f"{e.at_s:.4f} {e.verb} {e.kind} {e.ns}/{e.name}"
                for e in self.trace.events]

    def stop(self) -> None:
        self._stop.set()

    def run(self) -> dict:
        """Dispatch every event at its warped offset, then wait for all
        resident pods to bind. Returns the replay's result block; never
        raises on per-event API errors (they are counted and listed —
        a replayed incident is EXPECTED to hit conflicts)."""
        # process-global registry: this window must not inherit an
        # earlier replay's tail
        SCENARIO_SKEW.reset()
        SCENARIO_ATTEMPT.reset()
        warp = (1.0 / self.speed) if self.speed > 0 else 0.0
        dispatch_order: list[str] = []
        errors: list[str] = []
        dispatch_ts: dict = {}
        pod_phase: dict = {}
        self._state = "dispatching"
        self._publish_status()
        t0 = self.clock.now()
        for i, ev in enumerate(self.trace.events):
            if self._stop.is_set():
                break
            target = t0 + ev.at_s * warp
            delay = target - self.clock.now()
            if delay > 0:
                self._stop.wait(delay)
            ok = self._dispatch(ev)
            now = self.clock.now()
            skew = max(0.0, now - target)
            self._skew_max = max(self._skew_max, skew)
            SCENARIO_SKEW.observe(skew)
            SCENARIO_EVENTS.inc({"verb": ev.verb,
                                 "result": "ok" if ok is True
                                 else "error"})
            if ok is not True:
                errors.append(f"{ev.verb} {ev.key()}: {ok}")
            dispatch_order.append(f"{ev.at_s:.4f} {ev.verb} {ev.kind} "
                                  f"{ev.ns}/{ev.name}")
            if ev.kind == "Pod" and ev.verb == "create":
                dispatch_ts[(ev.ns, ev.name)] = now
                pod_phase[(ev.ns, ev.name)] = ev.phase or "default"
            self._dispatched = i + 1
            phase = ev.phase or "default"
            if phase != self._phase:
                self._phase = phase
                self._publish_status()
            elif (i + 1) % self.publish_every == 0:
                self._publish_status()
        t_dispatched = self.clock.now()
        self._state = "binding"
        self._publish_status()
        bound_at = self._wait_bound(dispatch_ts, pod_phase)
        t_end = self.clock.now()
        resident = self.trace.resident_pods()
        self._bound = len(bound_at)
        completed = (not self._stop.is_set()
                     and len(bound_at) >= len(resident))
        self._state = "done" if completed else "incomplete"
        self._publish_status()

        phases: dict = {}
        for (ns, name), ev in resident.items():
            ph = ev.phase or "default"
            st = phases.setdefault(ph, {"pods": 0, "bound": 0})
            st["pods"] += 1
            if (ns, name) in bound_at:
                st["bound"] += 1
        for ph, st in phases.items():
            n = SCENARIO_ATTEMPT.count({"phase": ph})
            st["p99_attempt_latency_s"] = (
                SCENARIO_ATTEMPT.percentile(0.99, {"phase": ph})
                if n else None)
            st["p50_attempt_latency_s"] = (
                SCENARIO_ATTEMPT.percentile(0.50, {"phase": ph})
                if n else None)
        return {
            "trace": self.trace.manifest.name,
            "seed": self.trace.manifest.seed,
            "events_total": len(self.trace.events),
            "dispatched": self._dispatched,
            "dispatch_order": dispatch_order,
            "errors": errors[:50],
            "error_count": len(errors),
            "speed": self.speed,
            "dispatch_s": round(t_dispatched - t0, 3),
            "wall_s": round(t_end - t0, 3),
            "skew": {"max_s": round(self._skew_max, 4),
                     "p99_s": SCENARIO_SKEW.percentile(0.99),
                     "events": SCENARIO_SKEW.count()},
            "resident": len(resident),
            "bound": len(bound_at),
            "completed": completed,
            "phases": phases,
        }

    # ---- internals -------------------------------------------------------

    def _resource(self, ev: TraceEvent):
        plural = _PLURALS.get(ev.kind)
        if plural is None:
            return None
        if ev.kind == "Node":
            return self.client.nodes()
        return self.client.pods(ev.ns)

    def _dispatch(self, ev: TraceEvent):
        """True on success, else a short error string."""
        res = self._resource(ev)
        if res is None:
            return f"unsupported kind {ev.kind!r}"
        try:
            if ev.verb == "create":
                res.create(self.trace.materialize(ev))
            elif ev.verb == "update":
                res.update(self.trace.materialize(ev))
            elif ev.verb == "delete":
                res.delete(ev.name)
            else:
                return f"unsupported verb {ev.verb!r}"
            return True
        except Exception as e:  # counted + listed, never silent
            return f"{type(e).__name__}: {e}"

    def _wait_bound(self, dispatch_ts: dict, pod_phase: dict) -> dict:
        """Poll the store until every resident pod is bound (or the
        budget runs out); observe create-to-bound latency per pod the
        first poll that sees its binding."""
        resident = self.trace.resident_pods()
        if not resident:
            return {}
        namespaces = sorted({ns for ns, _ in resident})
        deadline = self.clock.now() + self.bind_timeout_s
        bound_at: dict = {}
        while not self._stop.is_set():
            now = self.clock.now()
            for ns in namespaces:
                try:
                    pods = self.client.pods(ns).list()
                except Exception as e:
                    self.log(f"  scenario: list({ns}) failed: {e}")
                    continue
                for p in pods:
                    name = (p.get("metadata") or {}).get("name", "")
                    key = (ns, name)
                    if key not in resident or key in bound_at:
                        continue
                    if (p.get("spec") or {}).get("nodeName"):
                        bound_at[key] = now
                        t_create = dispatch_ts.get(key)
                        if t_create is not None:
                            SCENARIO_ATTEMPT.observe(
                                now - t_create,
                                {"phase": pod_phase.get(key,
                                                        "default")})
            if len(bound_at) != self._bound:
                self._bound = len(bound_at)
                self._publish_status()
            if len(bound_at) >= len(resident) or now >= deadline:
                break
            self._stop.wait(self.poll_interval_s)
        return bound_at

    def _publish_status(self) -> None:
        if not self.publish:
            return
        from kubernetes_tpu.utils.configmap import upsert_configmap
        st = {"trace": self.trace.manifest.name,
              "state": self._state,
              "phase": self._phase,
              "eventsDispatched": self._dispatched,
              "eventsTotal": len(self.trace.events),
              "skewMaxMs": round(self._skew_max * 1000, 1),
              "podsBound": self._bound,
              "podsResident": self._resident_total,
              "speed": self.speed}
        upsert_configmap(self.client, self.status_namespace,
                         SCENARIO_CONFIGMAP,
                         {"scenario": json.dumps(st)},
                         site="scenario_status")
