"""kubernetes_tpu — a TPU-native cluster-scheduling framework.

A ground-up re-design of the kube-scheduler (reference:
``pkg/scheduler/schedule_one.go`` — ``findNodesThatFitPod`` /
``prioritizeNodes``) plus the surrounding control-plane machinery
(store/watch, informers, controllers, node runtime, CLI) where the
per-pod Filter/Score plugin chain is inverted into dense
pods x nodes x resources tensors evaluated in one jitted JAX program,
sharded over a TPU mesh.

Layout:
  api/         core/v1-analog typed objects (Pod, Node, quantities, selectors)
  encode/      cluster objects -> bucketed static-shape tensors (Snapshot)
  ops/         tensor plugin terms: feasibility masks, score terms, topology
  models/      the jitted scheduling step + gang batcher ("flagship model")
  sched/       scheduler framework: queue, cache, profiles, oracle, main loop
  parallel/    device mesh, shardings, collectives
  store/       etcd-analog versioned store + watch + HTTP apiserver
  client/      client-go analog: informers, workqueue, leader election
  controllers/ reconcile loops (deployment, replicaset, job, nodelifecycle, gc)
  kubelet/     hollow node runtime (status, heartbeats)
  proxy/       service -> endpoint rule computation
  cli/         ktpu command-line client
  config/      component config (SchedulerConfiguration), feature gates
  metrics/     prometheus-style registry
"""

__version__ = "0.1.0"
