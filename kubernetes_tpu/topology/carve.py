"""The slice carver — contiguous ICI sub-slice placement as ONE batched
contraction over the resident encoding.

The feasibility grid is DERIVED, not stored: node coordinates ride the
pre-interned ``kubernetes-tpu.io/topology-{x,y,z}`` label columns of
``ClusterTensors`` (encode/snapshot.py), so the scatter into the dense
[X,Y,Z] occupancy grid happens INSIDE the jitted program and node churn
keeps it current through the existing fused-fold patch path — no new
tensor field, no new dispatch on the churn side.

One ``carve_step`` dispatch evaluates, for a requested shape, EVERY
wrap-around torus origin x EVERY axis-order rotation at once:

  - per-node ``free`` (valid, on-grid, schedulable, tenant-visible,
    capacity fits one member, not claimed by an earlier gang this cycle)
    scatters to the free grid;
  - a separable box-sum (``sum_i roll(g, -i, axis)`` per axis — wrap-around
    is free on a torus) turns the grid into per-origin slice-fit counts;
    ``count == a*b*c`` IS the slice-fit score plane;
  - the SAME box-sum over the bound-occupancy grid (existing-pod counts,
    infinity where a cell can never host) is the
    "fewest-evictions-to-free-a-slice" plane — defrag-toward-contiguity
    and slice preemption read it without a second program.

Expressed as large XLA contractions on purpose: the in-repo
``pallas_bench`` measured a hand kernel 120x slower than the fused XLA
form of exactly this kind of pass (see benchmarks/), so there is no
Pallas here.

Host-side selection is deliberately tiny (argmax/argmin over the readback
grids) and shared, ORDER AND ALL, with the numpy twin ``numpy_grids`` —
the bit-parity contract the oracle carver (sched/oracle.py) and the
ParitySentinel carve site build on.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from kubernetes_tpu.encode.snapshot import (
    TENANT_KEY_ID,
    TOPO_X_KEY_ID,
    TOPO_Y_KEY_ID,
    TOPO_Z_KEY_ID,
    ClusterTensors,
)
from kubernetes_tpu.topology.slicing import box_cells, rotations


@dataclass
class CarveResult:
    """Readback of one carve dispatch (device or numpy twin — identical
    layout, identical selection semantics)."""

    fits: np.ndarray       # [R?,X,Y,Z] bool: origin hosts the whole slice
    cost: np.ndarray       # [R?,X,Y,Z] float32: evictions to free it (inf = never)
    node_grid: np.ndarray  # [X,Y,Z] int32 node index, -1 = no node at cell
    free_grid: np.ndarray  # [X,Y,Z] bool
    rots: tuple            # rotation r -> (a, b, c) extents
    dims: tuple            # grid extents (X, Y, Z)
    shape: tuple           # requested shape as labelled


def _box_sum(g, rot):
    """Separable wrap-around box sum: S[o] = sum over the rot-shaped box
    anchored at o. One roll per unit of extent; wrap-around is what
    ``jnp.roll``/``np.roll`` do natively, so the torus costs nothing."""
    roll = jnp.roll if isinstance(g, jax.Array) else np.roll
    for ax, d in enumerate(rot):
        acc = g
        for i in range(1, d):
            acc = acc + roll(g, -i, axis=ax)
        g = acc
    return g


@partial(jax.jit, static_argnames=("dims", "rots"))
def carve_step(ct: ClusterTensors, member_req, pod_tenant, claimed,
               dims: tuple, rots: tuple):
    """-> (fits [R,X,Y,Z] bool, cost [R,X,Y,Z] f32, node_grid [X,Y,Z] i32,
    free_grid [X,Y,Z] bool). Static args: grid extents + the (already
    dims-filtered) rotation tuple — both fixed per installed topology, so
    steady-state carves ride one warm program."""
    X, Y, Z = dims
    N = ct.node_valid.shape[0]
    K = ct.node_labels.shape[1]
    V = ct.label_value_num.shape[0]

    def coord(kid):
        # label-column coordinate: value-id -> numeric parse via the
        # existing label_value_num plane (churn patches already ship it)
        vid = ct.node_labels[:, kid]
        val = ct.label_value_num[jnp.clip(vid, 0, V - 1)]
        ok = (vid >= 0) & ~jnp.isnan(val) & (val >= 0)
        return jnp.where(ok, val, -1.0).astype(jnp.int32), ok

    if K > TOPO_Z_KEY_ID:
        x, okx = coord(TOPO_X_KEY_ID)
        y, oky = coord(TOPO_Y_KEY_ID)
        z, okz = coord(TOPO_Z_KEY_ID)
        on_grid = (okx & oky & okz & (x < X) & (y < Y) & (z < Z)
                   & ct.node_valid)
    else:
        # hand-built tensors predating the topology columns: no grid
        x = y = z = jnp.zeros(N, jnp.int32)
        on_grid = jnp.zeros(N, bool)
    if K > TENANT_KEY_ID:
        visible = ct.node_labels[:, TENANT_KEY_ID] == pod_tenant
    else:
        visible = jnp.ones(N, bool)

    free_cap = jnp.all(member_req[None, :] <= ct.allocatable - ct.requested,
                       axis=-1)
    alone_cap = jnp.all(member_req[None, :] <= ct.allocatable, axis=-1)
    usable = on_grid & visible & ~ct.unschedulable & ~claimed
    free = usable & free_cap
    evictable = usable & alone_cap

    # cell -> node: flat scatter, HIGHEST node index wins a duplicated
    # coordinate (deterministic; the numpy twin iterates ascending so its
    # last write is the same winner). Off-grid rows scatter out of range
    # and drop.
    flat = jnp.where(on_grid, (x * Y + y) * Z + z, X * Y * Z)
    idx = jnp.arange(N, dtype=jnp.int32)
    node_grid = (jnp.full((X * Y * Z,), -1, jnp.int32)
                 .at[flat].max(jnp.where(on_grid, idx, -1), mode="drop")
                 .reshape(X, Y, Z))
    in_t = node_grid >= 0
    gi = jnp.clip(node_grid, 0)
    free_grid = jnp.where(in_t, free[gi], False)

    # bound-occupancy plane: existing pods per node (epod slots are the
    # encoder's bound set; pending/pad slots are invalid and weigh 0)
    pods_on = jnp.zeros(N, jnp.float32).at[
        jnp.clip(ct.epod_node, 0, N - 1)].add(
        jnp.where(ct.epod_valid, 1.0, 0.0))
    cell_cost = jnp.where(
        jnp.where(in_t, evictable[gi], False),
        jnp.where(free_grid, 0.0, pods_on[gi]),
        jnp.inf)

    fits, costs = [], []
    for rot in rots:
        want = rot[0] * rot[1] * rot[2]
        fits.append(_box_sum(free_grid.astype(jnp.int32), rot) == want)
        costs.append(_box_sum(cell_cost, rot))
    return (jnp.stack(fits), jnp.stack(costs), node_grid,
            free_grid)


def carve_device(ct: ClusterTensors, member_req, pod_tenant: int, claimed,
                 dims: tuple, shape: tuple) -> Optional[CarveResult]:
    """Run one carve dispatch and read the score planes back. None when no
    rotation of ``shape`` fits ``dims`` at all (the shape can NEVER be
    carved on this torus — a static verdict, no device needed)."""
    rots = rotations(shape, dims)
    if not rots:
        return None
    # ktpu-lint: disable=KTL005 -- group-path carve: one batched readback of the tiny score planes per gang, same contract as gang_schedule's readback
    fits, cost, node_grid, free_grid = jax.device_get(carve_step(
        ct, jnp.asarray(member_req), jnp.int32(pod_tenant),
        jnp.asarray(claimed), dims=dims, rots=rots))
    return CarveResult(fits=np.asarray(fits), cost=np.asarray(cost),
                       node_grid=np.asarray(node_grid),
                       free_grid=np.asarray(free_grid),
                       rots=rots, dims=dims, shape=shape)


def numpy_grids(coords: list, free: list, evictable: list, n_pods: list,
                dims: tuple, shape: tuple) -> Optional[CarveResult]:
    """The carver's numpy twin over per-node host verdicts: ``coords[i]``
    is node i's (x, y, z) or None, ``free``/``evictable``/``n_pods`` its
    host-judged cell state. Same max-wins scatter, same roll-based box
    sums, same rotation order — bit-equal planes to ``carve_step`` by
    construction, asserted by the parity tests and the sentinel."""
    rots = rotations(shape, dims)
    if not rots:
        return None
    X, Y, Z = dims
    node_grid = np.full(dims, -1, np.int32)
    for i, c in enumerate(coords):
        if c is None or not all(0 <= v < d for v, d in zip(c, dims)):
            continue
        node_grid[c] = i  # ascending i: last write == max-wins
    in_t = node_grid >= 0
    gi = np.clip(node_grid, 0, None)
    free_grid = np.where(in_t, np.asarray(free, bool)[gi], False)
    evict_grid = np.where(in_t, np.asarray(evictable, bool)[gi], False)
    cell_cost = np.where(
        evict_grid,
        np.where(free_grid, 0.0, np.asarray(n_pods, np.float32)[gi]),
        np.inf).astype(np.float32)
    fits = np.stack([
        _box_sum(free_grid.astype(np.int32), rot) == rot[0] * rot[1] * rot[2]
        for rot in rots])
    cost = np.stack([_box_sum(cell_cost, rot) for rot in rots])
    return CarveResult(fits=fits, cost=cost, node_grid=node_grid,
                       free_grid=free_grid, rots=rots, dims=dims,
                       shape=shape)


# ---- host-side selection (shared by device and twin paths) ----------------

def select_assignment(res: Optional[CarveResult]
                      ) -> Optional[list[int]]:
    """First-fit origin in flat (rotation, x, y, z) order -> the member ->
    node-index assignment (C-order box cells, slicing.box_cells). None
    when no origin hosts the slice."""
    if res is None or res.fits.size == 0:
        return None
    flat = res.fits.reshape(-1)
    i = int(np.argmax(flat))  # argmax over bool = FIRST True
    if not flat[i]:
        return None
    r, ox, oy, oz = np.unravel_index(i, res.fits.shape)
    return [int(res.node_grid[c])
            for c in box_cells((int(ox), int(oy), int(oz)),
                               res.rots[r], res.dims)]


def select_eviction(res: Optional[CarveResult]
                    ) -> Optional[tuple[list[int], list[tuple], float]]:
    """Cheapest contiguous victim set: the finite-minimum origin of the
    eviction plane (first minimum in flat order) -> (node indices of the
    slice's cells, the cells themselves, total eviction cost). None when
    no origin can EVER host the slice (an unusable cell in every box)."""
    if res is None or res.cost.size == 0:
        return None
    flat = res.cost.reshape(-1)
    i = int(np.argmin(flat))  # first minimum in flat order
    if not np.isfinite(flat[i]):
        return None
    r, ox, oy, oz = np.unravel_index(i, res.cost.shape)
    cells = box_cells((int(ox), int(oy), int(oz)), res.rots[r], res.dims)
    nodes = [int(res.node_grid[c]) for c in cells]
    return nodes, cells, float(flat[i])


def _covered_grid(res: CarveResult) -> np.ndarray:
    """[X,Y,Z] bool: cell belongs to SOME carveable placement of the shape
    (any rotation, any fitting origin)."""
    covered = np.zeros(res.dims, bool)
    for r, rot in enumerate(res.rots):
        f = res.fits[r]
        for cell in box_cells((0, 0, 0), rot, res.dims):
            covered |= np.roll(f, cell, axis=(0, 1, 2))
    return covered


def covered_nodes(res: Optional[CarveResult], n_nodes: int) -> list[bool]:
    """Per-node verdict "this node sits inside some carveable placement" —
    the oracle explainer's SliceCarve filter plane (a node outside every
    placement can never host a member of the requested slice as things
    stand)."""
    out = [False] * n_nodes
    if res is None:
        return out
    covered = _covered_grid(res)
    for cell in np.argwhere(covered):
        ni = int(res.node_grid[tuple(cell)])
        if 0 <= ni < n_nodes:
            out[ni] = True
    return out


def coverage_stats(res: Optional[CarveResult]) -> dict:
    """Status-surface numbers for one shape: carveable origin count and
    fragmentation % — the share of free cells that sit in NO carveable
    placement of the shape (100% = plenty of free nodes, none of them
    composable into a slice; 0% = every free cell is part of some fit)."""
    if res is None:
        return {"origins": 0, "fragmentationPct": None}
    covered = _covered_grid(res)
    n_free = int(res.free_grid.sum())
    frag = (100.0 * (1.0 - int((covered & res.free_grid).sum()) / n_free)
            if n_free else 0.0)
    return {"origins": int(res.fits.sum()),
            "fragmentationPct": round(float(frag), 1)}
