"""Slice-shape vocabulary — the host half of topology-aware carving.

A TPU fleet's gangs do not want "N feasible nodes"; they want a CONTIGUOUS
sub-slice of the ICI torus (2x2x1, 2x2x4, ...) so ring collectives never
leave the wrap-around mesh. This module owns the shape vocabulary every
other layer speaks:

  - nodes advertise their torus coordinate via the
    ``kubernetes-tpu.io/topology-{x,y,z}`` labels (pre-interned in
    encode/snapshot.py, so the coordinate planes ride the label COLUMNS of
    the resident encoding and churn patches update them with no new
    dispatch);
  - gangs request a shape via ``kubernetes-tpu.io/slice-shape: "2x2x4"``
    (or a slice-shaped ResourceClaim — sched/dra.py routes those here);
  - ``rotations`` enumerates the distinct axis-order orientations a shape
    can land in, filtered to those that fit the grid without a
    wrap-around cell counting twice;
  - ``is_contiguous_slice`` is the audit-side truth predicate (torus
    box under some rotation + wrap-around), shared by the
    ``slice_contiguity`` invariant and the bench gates.

Everything here is deliberately numpy/stdlib-only: the device carver
(topology/carve.py) and its numpy oracle twin both import THIS vocabulary,
which is what keeps their bit-parity honest.
"""

from __future__ import annotations

from itertools import permutations
from typing import Optional

# Label a gang (or claim) requests its slice shape with. The gang identity
# label is owned by descheduler/strategies.py; re-declared here (same
# convention as audit/invariants.py) to avoid a low-level package importing
# the descheduler.
SLICE_SHAPE_LABEL = "kubernetes-tpu.io/slice-shape"
GANG_LABEL = "kubernetes-tpu.io/gang"  # descheduler/strategies.py owner

# DRA attribute names a ResourceSlice's devices use to publish the SAME
# coordinates node labels carry (sched/dra.py reads these).
TOPO_ATTRS = ("topology-x", "topology-y", "topology-z")


def parse_shape(s: Optional[str]) -> Optional[tuple[int, int, int]]:
    """``"2x2x4"`` -> (2, 2, 4); None/empty/malformed -> None (a pod with
    a malformed shape label schedules as a NORMAL pod — the label is a
    request, not a trap; the invariant only judges parseable shapes)."""
    if not s:
        return None
    parts = str(s).lower().split("x")
    if len(parts) != 3:
        return None
    try:
        dims = tuple(int(p) for p in parts)
    except ValueError:
        return None
    if any(d <= 0 for d in dims):
        return None
    return dims  # type: ignore[return-value]


def shape_str(shape: tuple[int, int, int]) -> str:
    return "x".join(str(d) for d in shape)


def shape_of_labels(labels: Optional[dict]) -> Optional[tuple[int, int, int]]:
    """The ONE way to read an object's requested slice shape from labels
    (mirrors encode/snapshot.tenant_label_of for the tenant plane)."""
    return parse_shape((labels or {}).get(SLICE_SHAPE_LABEL))


def rotations(shape: tuple[int, int, int],
              dims: tuple[int, int, int]) -> tuple[tuple[int, int, int], ...]:
    """Distinct axis-order orientations of ``shape`` that fit ``dims``.

    Sorted for determinism (the carver's first-fit selection order is
    (rotation, x, y, z), so this order is part of the bit-parity
    contract). An orientation with any extent LARGER than the grid axis is
    dropped: with wrap-around, extent > axis would count a torus cell
    twice and "fit" a slice onto fewer physical nodes than it needs
    (extent == axis is fine — the box covers the whole ring exactly
    once)."""
    return tuple(sorted(
        r for r in set(permutations(shape))
        if all(e <= d for e, d in zip(r, dims))))


def coords_of_labels(labels: Optional[dict]
                     ) -> Optional[tuple[int, int, int]]:
    """A node's ICI-torus coordinate from its topology labels, or None
    when any axis label is absent/non-integer (the node is off-grid and
    never hosts a slice member)."""
    labels = labels or {}
    out = []
    for axis in ("x", "y", "z"):
        v = labels.get(f"kubernetes-tpu.io/topology-{axis}")
        if v is None:
            return None
        try:
            out.append(int(v))
        except (TypeError, ValueError):
            return None
    if any(c < 0 for c in out):
        return None
    return tuple(out)  # type: ignore[return-value]


def topology_labels(x: int, y: int, z: int) -> dict[str, str]:
    """The label stamp for a node at (x, y, z) — test/bench helper kept
    next to the vocabulary so fixtures can't drift from the reader."""
    return {"kubernetes-tpu.io/topology-x": str(x),
            "kubernetes-tpu.io/topology-y": str(y),
            "kubernetes-tpu.io/topology-z": str(z)}


def grid_dims(coords: list[tuple[int, int, int]]
              ) -> Optional[tuple[int, int, int]]:
    """Dense grid extent covering every known coordinate: (max+1) per
    axis. None when no node carries coordinates (topology disabled)."""
    if not coords:
        return None
    return (max(c[0] for c in coords) + 1,
            max(c[1] for c in coords) + 1,
            max(c[2] for c in coords) + 1)


def box_cells(origin: tuple[int, int, int], rot: tuple[int, int, int],
              dims: tuple[int, int, int]) -> list[tuple[int, int, int]]:
    """The torus cells of a ``rot``-shaped box at ``origin`` (wrap-around),
    in C order — member m of a gang sits on ``box_cells(...)[m]``. The C
    order is part of the parity contract between the device carver, the
    numpy oracle and the audit invariant."""
    a, b, c = rot
    X, Y, Z = dims
    return [((origin[0] + i) % X, (origin[1] + j) % Y, (origin[2] + k) % Z)
            for i in range(a) for j in range(b) for k in range(c)]


def is_contiguous_slice(coords: list[tuple[int, int, int]],
                        shape: tuple[int, int, int],
                        dims: tuple[int, int, int]) -> bool:
    """Audit-side truth: do ``coords`` form ONE contiguous torus box of
    ``shape`` under some rotation + wrap-around? Distinctness is required
    (two members on one node is never a slice)."""
    want = len(coords)
    if want != shape[0] * shape[1] * shape[2]:
        return False
    cs = set(coords)
    if len(cs) != want:
        return False
    c0 = next(iter(cs))
    for rot in rotations(shape, dims):
        # c0 must sit SOMEWHERE in the box, so the only viable anchors are
        # (c0 - offset) mod dims for each in-box offset — O(|box|) anchors,
        # not O(X*Y*Z)
        for i in range(rot[0]):
            for j in range(rot[1]):
                for k in range(rot[2]):
                    anchor = ((c0[0] - i) % dims[0],
                              (c0[1] - j) % dims[1],
                              (c0[2] - k) % dims[2])
                    if cs == set(box_cells(anchor, rot, dims)):
                        return True
    return False
