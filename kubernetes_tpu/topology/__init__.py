"""Topology-aware slice carving: contiguous ICI sub-slice scheduling.

``slicing`` is the host vocabulary (shapes, rotations, coordinates,
contiguity truth); ``carve`` is the device-batched carver and its numpy
twin. sched/scheduler.py drives the carve inside ``_schedule_group``;
sched/oracle.py hosts the oracle carver the parity machinery judges
against.
"""

from kubernetes_tpu.topology.slicing import (  # noqa: F401
    GANG_LABEL,
    SLICE_SHAPE_LABEL,
    TOPO_ATTRS,
    box_cells,
    coords_of_labels,
    grid_dims,
    is_contiguous_slice,
    parse_shape,
    rotations,
    shape_of_labels,
    shape_str,
    topology_labels,
)
from kubernetes_tpu.topology.carve import (  # noqa: F401
    CarveResult,
    carve_device,
    carve_step,
    coverage_stats,
    covered_nodes,
    numpy_grids,
    select_assignment,
    select_eviction,
)
