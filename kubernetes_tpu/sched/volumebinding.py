"""Volume scheduling — PVC/PV topology compiled to node-selector constraints.

Reference semantics, plugin by plugin:
  VolumeBinding      framework/plugins/volumebinding/volume_binding.go
                     (+ FindPodVolumes in volume/scheduling/scheduler_binder.go):
                     bound PVs constrain the pod to nodes matching the PV's
                     nodeAffinity; unbound PVCs need a matching unbound PV
                     whose affinity matches, or dynamic provisioning.
  VolumeZone         framework/plugins/volumezone/volume_zone.go: a PV's
                     zone/region labels must match the node's.
  VolumeRestrictions framework/plugins/volumerestrictions/: ReadWriteOncePod
                     claims exclude every other pod; single-attach volumes
                     conflict per node.
  NodeVolumeLimits   framework/plugins/nodevolumelimits/csi.go: count of
                     attachable volumes on the node vs its reported limit.

The TPU-first trick: every constraint above is *node-selector-shaped*, so the
compiler below emits per-PVC **groups of NodeSelectorTerms** — within a group
OR (any candidate PV works), across groups AND (every PVC must be satisfied) —
and the jitted filter evaluates them with the same eval_term_set kernel that
NodeAffinity uses (ops/filters.volume_mask). No per-node Go loop survives.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional

from kubernetes_tpu.api.resource import canonical
from kubernetes_tpu.api.types import (
    OP_EXISTS,
    OP_IN,
    NodeSelectorTerm,
    Pod,
    Requirement,
)

ZONE_LABELS = ("topology.kubernetes.io/zone", "topology.kubernetes.io/region",
               "failure-domain.beta.kubernetes.io/zone",
               "failure-domain.beta.kubernetes.io/region")
SELECTED_NODE_ANNOTATION = "volume.kubernetes.io/selected-node"
WAIT_FOR_FIRST_CONSUMER = "WaitForFirstConsumer"

# a term that matches every node: metadata.name always exists
MATCH_ALL_TERM = NodeSelectorTerm(match_fields=[
    Requirement("metadata.name", OP_EXISTS)])


@dataclass
class VolumeCatalog:
    """Indexed PVC/PV/StorageClass state (the informer caches' view)."""

    pvcs: dict[tuple[str, str], dict] = field(default_factory=dict)  # (ns,name)
    pvs: dict[str, dict] = field(default_factory=dict)               # name
    storage_classes: dict[str, dict] = field(default_factory=dict)   # name

    @classmethod
    def from_lists(cls, pvcs=(), pvs=(), storage_classes=()) -> "VolumeCatalog":
        return cls(
            pvcs={((p.get("metadata") or {}).get("namespace", "default"),
                   (p.get("metadata") or {}).get("name", "")): p for p in pvcs},
            pvs={(p.get("metadata") or {}).get("name", ""): p for p in pvs},
            storage_classes={(s.get("metadata") or {}).get("name", ""): s
                             for s in storage_classes},
        )

    def empty(self) -> bool:
        return not self.pvcs and not self.pvs


@dataclass
class PodVolumeInfo:
    """Compiled volume constraints for one pod."""

    # One group per PVC: OR over the group's terms, AND across groups.
    # A group with zero terms is unsatisfiable (pod stays pending).
    groups: list[list[NodeSelectorTerm]] = field(default_factory=list)
    rwo_pv_names: list[str] = field(default_factory=list)  # node-exclusive PVs
    attach_count: int = 0
    # PVC names that still need binding once a node is chosen (Reserve/PreBind)
    claims_to_bind: list[str] = field(default_factory=list)


def _pv_terms(pv: dict) -> list[NodeSelectorTerm]:
    """A PV's reachable-nodes constraint: spec.nodeAffinity.required terms
    AND-folded with its zone/region labels (VolumeZone)."""
    req = (((pv.get("spec") or {}).get("nodeAffinity") or {})
           .get("required") or {})
    terms = [NodeSelectorTerm.from_dict(t)
             for t in req.get("nodeSelectorTerms") or []]
    zone_reqs = []
    for lbl in ZONE_LABELS:
        v = ((pv.get("metadata") or {}).get("labels") or {}).get(lbl)
        if v is not None:
            # VolumeZone: comma-separated value set -> In
            zone_reqs.append(Requirement(lbl, OP_IN, sorted(v.split("__")
                                                            if "__" in v
                                                            else v.split(","))))
    if not terms:
        terms = [MATCH_ALL_TERM] if not zone_reqs else [NodeSelectorTerm()]
    if zone_reqs:
        terms = [NodeSelectorTerm(
            match_expressions=list(t.match_expressions) + zone_reqs,
            match_fields=list(t.match_fields)) for t in terms]
    return terms


def _pv_capacity(pv: dict) -> int:
    cap = ((pv.get("spec") or {}).get("capacity") or {}).get("storage", 0)
    return canonical("storage", cap)


def _pvc_request(pvc: dict) -> int:
    req = ((((pvc.get("spec") or {}).get("resources") or {})
            .get("requests")) or {}).get("storage", 0)
    return canonical("storage", req)


def _access_modes(obj: dict) -> set[str]:
    return set((obj.get("spec") or {}).get("accessModes") or [])


def _pv_available(pv: dict, pvc_key: tuple[str, str]) -> bool:
    """Unbound, or already reserved for exactly this claim."""
    ref = (pv.get("spec") or {}).get("claimRef")
    if not ref:
        return True
    return (ref.get("namespace", "default"), ref.get("name", "")) == pvc_key


def find_matching_pvs(pvc: dict, catalog: VolumeCatalog) -> list[dict]:
    """FindMatchingVolume (pkg/volume/persistentvolume/util.go): capacity,
    access modes, storage class; smallest-first preference is applied by the
    binder, not the filter."""
    pvc_key = ((pvc.get("metadata") or {}).get("namespace", "default"),
               (pvc.get("metadata") or {}).get("name", ""))
    want_modes = _access_modes(pvc)
    want_cap = _pvc_request(pvc)
    sc = (pvc.get("spec") or {}).get("storageClassName", "") or ""
    out = []
    for pv in catalog.pvs.values():
        if (pv.get("status") or {}).get("phase") in ("Released", "Failed"):
            continue
        if not _pv_available(pv, pvc_key):
            continue
        if ((pv.get("spec") or {}).get("storageClassName", "") or "") != sc:
            continue
        if want_modes - _access_modes(pv):
            continue
        if _pv_capacity(pv) < want_cap:
            continue
        out.append(pv)
    return sorted(out, key=_pv_capacity)  # smallest fitting first


def _is_provisionable(pvc: dict, catalog: VolumeCatalog) -> bool:
    sc_name = (pvc.get("spec") or {}).get("storageClassName", "") or ""
    sc = catalog.storage_classes.get(sc_name)
    return bool(sc and sc.get("provisioner"))


def _pvc_bound_pv(pvc: dict) -> str:
    return (pvc.get("spec") or {}).get("volumeName", "") or ""


def _node_exclusive(obj: dict) -> bool:
    """RWO/RWOP volumes attach to one node at a time (the conflict the
    VolumeRestrictions filter guards)."""
    modes = _access_modes(obj)
    return bool(modes & {"ReadWriteOnce", "ReadWriteOncePod"})


def compile_pod_volumes(pod: Pod, catalog: Optional[VolumeCatalog],
                        in_use_rwop: Optional[set[str]] = None) -> PodVolumeInfo:
    """-> PodVolumeInfo; upstream's FindPodVolumes decomposed into
    selector-term groups. ``in_use_rwop`` = PV names claimed ReadWriteOncePod
    by other live pods (conflict = unschedulable anywhere)."""
    info = PodVolumeInfo()
    if catalog is None:
        return info
    ns = pod.metadata.namespace
    for claim in pod.pvc_names():
        pvc = catalog.pvcs.get((ns, claim))
        if pvc is None:
            info.groups.append([])  # missing PVC: unschedulable (wait)
            continue
        bound = _pvc_bound_pv(pvc)
        if bound:
            pv = catalog.pvs.get(bound)
            if pv is None:
                info.groups.append([])
                continue
            if "ReadWriteOncePod" in _access_modes(pvc) and \
                    in_use_rwop and bound in in_use_rwop:
                info.groups.append([])  # claim already in use by another pod
                continue
            info.groups.append(_pv_terms(pv))
            info.attach_count += 1
            if _node_exclusive(pvc) or _node_exclusive(pv):
                info.rwo_pv_names.append(bound)
            continue
        # unbound PVC
        candidates = find_matching_pvs(pvc, catalog)
        if candidates:
            terms = [t for pv in candidates for t in _pv_terms(pv)]
            info.groups.append(terms)
            info.claims_to_bind.append(claim)
            info.attach_count += 1
            if _node_exclusive(pvc):
                # whichever PV binds is exclusive, but its identity is
                # node-dependent; conflicts materialize post-bind
                pass
            continue
        if _is_provisionable(pvc, catalog):
            sc = catalog.storage_classes.get(
                (pvc.get("spec") or {}).get("storageClassName", "") or "")
            info.groups.append([MATCH_ALL_TERM])
            info.claims_to_bind.append(claim)
            info.attach_count += 1
            continue
        info.groups.append([])  # nothing matches, nothing provisions: wait
    return info


def cluster_volume_state(bound_pods: list[Pod], catalog: Optional[VolumeCatalog]
                         ) -> tuple[dict[str, list[str]], dict[str, int], set[str]]:
    """-> (rwo PVs in use per node, attach counts per node, RWOP PVs in use).

    Feeds ClusterTensors: the node side of VolumeRestrictions + NodeVolumeLimits.
    """
    per_node_rwo: dict[str, list[str]] = {}
    per_node_attach: dict[str, int] = {}
    rwop_in_use: set[str] = set()
    if catalog is None:
        return per_node_rwo, per_node_attach, rwop_in_use
    for p in bound_pods:
        node = p.spec.node_name
        if not node:
            continue
        for claim in p.pvc_names():
            pvc = catalog.pvcs.get((p.metadata.namespace, claim))
            if pvc is None:
                continue
            bound = _pvc_bound_pv(pvc)
            if not bound:
                continue
            pv = catalog.pvs.get(bound, {})
            per_node_attach[node] = per_node_attach.get(node, 0) + 1
            if _node_exclusive(pvc) or _node_exclusive(pv):
                per_node_rwo.setdefault(node, []).append(bound)
            if "ReadWriteOncePod" in _access_modes(pvc):
                rwop_in_use.add(bound)
    return per_node_rwo, per_node_attach, rwop_in_use


def node_attach_limit(node_allocatable: dict[str, Any]) -> int:
    """NodeVolumeLimits: sum of attachable-volumes-* allocatable entries
    (csi.go reads CSINode; kubelet reports them as node allocatable)."""
    total = 0
    found = False
    for k, v in node_allocatable.items():
        if k.startswith("attachable-volumes-"):
            total += int(canonical("pods", v))
            found = True
    return total if found else -1  # -1 = unlimited


class VolumeBinder:
    """Reserve/PreBind: bind unbound PVCs once a node is chosen.

    Reference: volume_binding.go Reserve (AssumePodVolumes) + PreBind
    (BindPodVolumes). Static PVs get claimRef/volumeName set; provisionable
    claims get the selected-node annotation for an external provisioner
    (pkg/controller/volume/persistentvolume/pv_controller.go analog lives in
    controllers/pvprovisioner.py).
    """

    def __init__(self, client):
        self.client = client

    def bind_pod_volumes(self, pod: Pod, node: "Any", catalog: VolumeCatalog,
                         node_labels: dict[str, str], node_name: str) -> bool:
        ns = pod.metadata.namespace
        ok = True
        for claim in pod.pvc_names():
            pvc = catalog.pvcs.get((ns, claim))
            if pvc is None or _pvc_bound_pv(pvc):
                continue
            chosen = None
            for pv in find_matching_pvs(pvc, catalog):
                if self._pv_matches_node(pv, node_labels, node_name):
                    chosen = pv
                    break
            try:
                if chosen is not None:
                    self._bind_static(pvc, chosen)
                elif _is_provisionable(pvc, catalog):
                    self._annotate_selected_node(pvc, node_name)
                else:
                    ok = False
            except Exception:  # ktpu-lint: disable=KTL002 -- provision-plugin failure = bind verdict False; the scheduler requeues the pod with backoff
                ok = False
        return ok

    @staticmethod
    def _pv_matches_node(pv: dict, node_labels: dict[str, str],
                         node_name: str) -> bool:
        from kubernetes_tpu.api.selectors import (
            node_fields,
            node_selector_matches,
        )
        terms = _pv_terms(pv)
        return node_selector_matches(terms, node_labels, node_fields(node_name))

    def _bind_static(self, pvc: dict, pv: dict) -> None:
        md = pvc["metadata"]
        pv = dict(pv)
        pv["spec"] = {**(pv.get("spec") or {}),
                      "claimRef": {"kind": "PersistentVolumeClaim",
                                   "namespace": md.get("namespace", "default"),
                                   "name": md["name"], "uid": md.get("uid", "")}}
        pv["status"] = {**(pv.get("status") or {}), "phase": "Bound"}
        self.client.resource("persistentvolumes", None).update(pv)
        pvc = dict(pvc)
        pvc["spec"] = {**(pvc.get("spec") or {}),
                       "volumeName": pv["metadata"]["name"]}
        pvc["status"] = {**(pvc.get("status") or {}), "phase": "Bound"}
        self.client.resource("persistentvolumeclaims",
                             md.get("namespace", "default")).update(pvc)

    def _annotate_selected_node(self, pvc: dict, node_name: str) -> None:
        pvc = dict(pvc)
        md = dict(pvc.get("metadata") or {})
        ann = dict(md.get("annotations") or {})
        if ann.get(SELECTED_NODE_ANNOTATION) == node_name:
            return
        ann[SELECTED_NODE_ANNOTATION] = node_name
        md["annotations"] = ann
        pvc["metadata"] = md
        self.client.resource("persistentvolumeclaims",
                             md.get("namespace", "default")).update(pvc)
