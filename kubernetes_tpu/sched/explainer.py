"""Scheduling explainer — per-pod decision provenance off the hot path.

The batched schedulers (gang step, fused drain) reduce every per-(filter,
pod, node) verdict to one winner index; an unschedulable pod used to get
the generic "no node satisfied the pod's scheduling constraints this
cycle". This recovers what upstream's ``findNodesThatFitPod`` would have
said, WITHOUT adding a dispatch to the drain cycle:

- the scheduling thread hands each cycle's unschedulable pods (plus the
  typed cluster views the cycle judged against) to :class:`SchedulingExplainer`
  via ``submit`` — a capture + queue put, nothing more;
- a dedicated daemon thread (the ``audit/sentinel.py`` pattern) re-runs the
  STATIC filter stack in per-filter-output mode: one batched
  ``models/explain.explain_step`` dispatch over only the failed pods on a
  PRIVATE encoder (no cache-lock contention), or the numpy oracle when the
  device layer is degraded/broken;
- verdicts become (1) upstream-style ``FailedScheduling`` events
  ("0/N nodes are available: 3 Insufficient resources, ..."), (2) the
  ``scheduler-explanations`` ConfigMap ``ktpu why <pod>`` reads (published
  through a runner-supplied callback), and (3) the
  ``scheduler_unschedulable_reasons_total{filter}`` counter.

Out-of-tree tensor plugins and extender vetoes are outside the static
stack: pods from profiles that carry them still get the in-tree breakdown
(a superset explanation can overcount feasible nodes, never invent a
reject), and the explanation records the mode it was computed in.
"""

from __future__ import annotations

import logging
import queue as queue_mod
import threading
import time
from collections import OrderedDict
from typing import Callable, Optional

from kubernetes_tpu.metrics.registry import (
    EXPLAIN_SAMPLES,
    LOOP_ERRORS,
    UNSCHEDULABLE_REASONS,
)

_LOG = logging.getLogger(__name__)

# per-pod re-explanation throttle: a pod failing every backoff cycle gets
# one fresh verdict per window, not one per cycle (events aggregate the
# identical message anyway)
REEXPLAIN_INTERVAL_S = 2.0

# pods explained per batched dispatch (failed pods beyond this chunk go in
# further chunks); encode_pods pow2-buckets each chunk's width itself, so
# repeat cycles reuse the compiled explain program per bucket
MAX_EXPLAIN_BATCH = 256


class SchedulingExplainer:
    """Capture on the scheduling thread, judge + publish on a daemon
    thread. ``recorder_ref``/``publisher_ref`` are callables because the
    runner wires the real EventRecorder and ConfigMap publisher after the
    Scheduler (and this explainer) are constructed."""

    def __init__(self, cfg, recorder_ref: Callable[[], object],
                 max_backlog: int = 8, max_entries: int = 1024):
        self.cfg = cfg
        self._recorder_ref = recorder_ref
        # publisher(dict) -> None: writes the scheduler-explanations
        # ConfigMap (None = library embedder, explanations stay in-memory)
        self.publisher: Optional[Callable[[dict], None]] = None
        self._max_backlog = max_backlog
        self._max_entries = max_entries
        self._q: "queue_mod.Queue" = queue_mod.Queue()
        self._thread: Optional[threading.Thread] = None
        self._spawn_lock = threading.Lock()
        self._lock = threading.Lock()
        # pod key -> explanation dict (bounded, oldest evicted)
        self._explanations: "OrderedDict[str, dict]" = OrderedDict()
        self._last_explained: dict[str, float] = {}
        # private encoder: explanation encodes must never contend with the
        # drain cycle's encode lock (lazily built on the checker thread)
        self._encoder = None
        self.samples = 0
        self.pods_explained = 0
        self.errors = 0
        self.skipped = 0

    # ---- scheduling-thread half -----------------------------------------

    def submit(self, cache, profile, level: str, pods: list) -> bool:
        """Capture one cycle's unschedulable pods + the typed views the
        cycle judged against. Returns True when the explainer OWNS the
        FailedScheduling events for these pods (the caller then skips the
        generic event); False = backlog full / nothing to do, caller keeps
        the old behavior."""
        now = time.time()
        fresh = [p for p in pods
                 if now - self._last_explained.get(p.key, 0.0)
                 >= REEXPLAIN_INTERVAL_S]
        if not fresh:
            # every pod was explained moments ago; its event/ConfigMap
            # entry is still fresh — recording another identical generic
            # event would only be noise
            return True
        if self._q.qsize() >= self._max_backlog:
            self.skipped += 1
            return False
        for p in fresh:
            self._last_explained[p.key] = now
        if len(self._last_explained) > 4 * self._max_entries:
            cutoff = now - 10 * REEXPLAIN_INTERVAL_S
            self._last_explained = {
                k: t for k, t in self._last_explained.items() if t > cutoff}
        self.samples += 1
        self._ensure_thread()
        self._q.put({"ts": now, "level": level,
                     "profile": profile.scheduler_name if profile else "",
                     "pods": list(fresh),
                     "nodes": cache.list_nodes(),
                     "bound": cache.bound_pods(include_assumed=True),
                     "ns_labels": cache.namespace_labels()})
        return True

    def submit_direct(self, pod, message: str, filters: dict,
                      n_nodes: int, profile: str = "") -> bool:
        """A READY-MADE verdict from the scheduling thread — the carve
        path's "0/N origins can host a 2x2x4 slice" message, which no
        per-node judge can reconstruct (the free nodes individually pass;
        it's their composition into a contiguous box that failed).
        Recorded + published on the checker thread so ``ktpu why`` sees
        it; the EVENT stays with the caller (the scheduler already
        emitted the same message)."""
        now = time.time()  # ktpu-lint: disable=KTL003 -- same wall-clock re-explain throttle as submit() above (baselined); entries carry wall ts for ktpu why
        if now - self._last_explained.get(pod.key, 0.0) < REEXPLAIN_INTERVAL_S:
            return True
        if self._q.qsize() >= self._max_backlog:
            self.skipped += 1
            return False
        self._last_explained[pod.key] = now
        self.samples += 1
        self._ensure_thread()
        self._q.put({"direct": True, "key": pod.key,
                     "entry": {"message": message,
                               "filters": dict(filters),
                               "nodes": n_nodes, "feasibleNow": 0,
                               "unjudged": 0, "mode": "carve", "ts": now,
                               "profile": profile}})
        return True

    # ---- results surface -------------------------------------------------

    def explanations(self) -> dict[str, dict]:
        with self._lock:
            return dict(self._explanations)

    def explain_of(self, key: str) -> Optional[dict]:
        with self._lock:
            return self._explanations.get(key)

    def stats(self) -> dict:
        return {"samples": self.samples,
                "podsExplained": self.pods_explained,
                "errors": self.errors, "skipped": self.skipped,
                "entries": len(self._explanations)}

    def drain(self, timeout: float = 10.0) -> None:
        """Block until every submitted capture's verdict landed (tests)."""
        deadline = time.time() + timeout
        while self._q.unfinished_tasks and time.time() < deadline:
            time.sleep(0.01)

    def close(self) -> None:
        t = self._thread
        if t is not None and t.is_alive():
            self._q.put(None)
            self._thread = None

    # ---- checker thread --------------------------------------------------

    def _ensure_thread(self) -> None:
        with self._spawn_lock:
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._loop, daemon=True, name="sched-explainer")
                self._thread.start()

    def _loop(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                self._q.task_done()
                return
            try:
                if item.get("direct"):
                    self._record_direct(item)
                else:
                    self._explain(item)
            except Exception:
                # a broken explanation is counted and logged, never raised
                # into silence — and never into the scheduling loop either
                self.errors += 1
                LOOP_ERRORS.inc({"site": "explainer"})
                _LOG.exception("explanation failed (pods get no verdict "
                               "this cycle)")
            finally:
                self._q.task_done()

    def _profile(self, name: str):
        return self.cfg.profile_for(name)

    def _record_direct(self, item: dict) -> None:
        """Store + publish one submit_direct verdict (checker thread)."""
        entry = item["entry"]
        hist = entry.get("filters") or {}
        if hist:
            dominant = max(hist.items(), key=lambda kv: kv[1])[0]
            UNSCHEDULABLE_REASONS.inc({"filter": dominant})
        EXPLAIN_SAMPLES.inc({"mode": entry.get("mode", "carve")})
        self.pods_explained += 1
        with self._lock:
            self._explanations.pop(item["key"], None)
            self._explanations[item["key"]] = entry
            while len(self._explanations) > self._max_entries:
                self._explanations.popitem(last=False)
            snap = dict(self._explanations)
        if self.publisher is not None:
            try:
                self.publisher(snap)
            except Exception:
                LOOP_ERRORS.inc({"site": "explainer_publish"})
                _LOG.warning("explanations publish failed", exc_info=True)

    @staticmethod
    def _slice_shape(pod):
        """Label-based shape detection only: the capture carries no DRA
        catalog, and a claim-routed slice pod still explains usefully
        through the generic judges."""
        from kubernetes_tpu.topology.slicing import shape_of_labels
        return shape_of_labels(pod.metadata.labels)

    def _explain(self, item: dict) -> None:
        from kubernetes_tpu.models.explain import failed_scheduling_message
        from kubernetes_tpu.utils.tracing import TRACER
        pods, nodes = item["pods"], item["nodes"]
        profile = self._profile(item["profile"])
        views = (profile.apply_added_affinity(pods)
                 if profile is not None and profile.added_affinity else pods)
        mode = "tensor"
        with TRACER.span("explain/judge", pods=len(pods),
                         nodes=len(nodes)):
            try:
                if item["level"] == "oracle":
                    raise RuntimeError("device degraded; oracle explain")
                if any(self._slice_shape(v) is not None for v in views):
                    # slice-shaped pods: only the oracle judge carries the
                    # SliceCarve pseudo-filter (the carver's coverage
                    # plane) — the tensor stack has no such mask
                    raise RuntimeError("slice-shaped pod; oracle explain")
                per_pod = self._judge_tensor(item, views, profile)
            except Exception:
                _LOG.debug("tensor explain failed; falling back to the "
                           "oracle judge", exc_info=True)
                mode = "oracle"
                per_pod = self._judge_oracle(item, views)
        # per-pod: (histogram, feasible_now, unjudged). The tensor program
        # evaluates EVERY filter (disabled ones pass), so its first-fail
        # verdicts honor the profile natively; the oracle short-circuits,
        # so a rejection via a filter the profile disables hides any later
        # check — count those nodes as unjudged rather than blame a filter
        # the profile never ran (or worse, claim feasibility).
        per_pod = [(h, f, 0) for h, f in per_pod]
        if (mode == "oracle" and profile is not None
                and profile.enabled_filters is not None):
            # SliceCarve is not a disableable plugin — a profile's filter
            # allowlist must not demote its verdicts to "unjudged"
            enabled = set(profile.enabled_filters) | {"SliceCarve"}
            per_pod = [
                ({f: c for f, c in hist.items() if f in enabled}, feasible,
                 sum(c for f, c in hist.items() if f not in enabled))
                for hist, feasible, _u in per_pod]
        ts = item["ts"]
        recorder = self._recorder_ref()
        out: dict[str, dict] = {}
        for pod, (hist, feasible_now, unjudged) in zip(pods, per_pod):
            msg = failed_scheduling_message(len(nodes), hist, feasible_now,
                                            unjudged)
            if recorder is not None:
                recorder.event(pod, "Warning", "FailedScheduling", msg)
            if hist:
                dominant = max(hist.items(), key=lambda kv: kv[1])[0]
                UNSCHEDULABLE_REASONS.inc({"filter": dominant})
            EXPLAIN_SAMPLES.inc({"mode": mode})
            out[pod.key] = {"message": msg, "filters": hist,
                            "nodes": len(nodes),
                            "feasibleNow": feasible_now,
                            "unjudged": unjudged,
                            "mode": mode, "ts": ts,
                            "profile": item["profile"]}
        self.pods_explained += len(out)
        with self._lock:
            for k, v in out.items():
                self._explanations.pop(k, None)
                self._explanations[k] = v
            while len(self._explanations) > self._max_entries:
                self._explanations.popitem(last=False)
            snap = dict(self._explanations)
        if self.publisher is not None:
            with TRACER.span("explain/publish", entries=len(snap)):
                try:
                    self.publisher(snap)
                except Exception:
                    LOOP_ERRORS.inc({"site": "explainer_publish"})
                    _LOG.warning("explanations publish failed",
                                 exc_info=True)

    def _judge_tensor(self, item: dict, views: list, profile) -> list:
        """One batched per-filter-output dispatch over only the failed
        pods (chunked at the pow2 bucket) on the PRIVATE encoder.
        -> [(histogram, feasible_now)] per pod."""
        import jax
        import numpy as np
        from kubernetes_tpu.encode.snapshot import SnapshotEncoder
        from kubernetes_tpu.models.explain import (explain_step, first_fail,
                                                   reject_histogram)
        from kubernetes_tpu.utils.tracing import TRACER
        if self._encoder is None:
            self._encoder = SnapshotEncoder()
        enc = self._encoder
        enc.set_namespaces(item["ns_labels"])
        with TRACER.span("explain/encode", pods=len(views)):
            ct, meta = enc.encode_cluster(item["nodes"], item["bound"],
                                          pending_pods=views)
        enabled = (None if profile is None
                   or profile.enabled_filters is None
                   else tuple(sorted(profile.enabled_filters)))
        n_nodes = len(item["nodes"])
        out = []
        for i in range(0, len(views), MAX_EXPLAIN_BATCH):
            chunk = views[i:i + MAX_EXPLAIN_BATCH]
            pb = enc.encode_pods(chunk, meta, cache_rows=False)
            with TRACER.span("explain/dispatch", pods=len(chunk)):
                # ktpu-lint: disable=KTL005 -- background explainer thread, off the scheduling cycle by design (ExplainAB gates its overhead <= 5%)
                verdicts, valid = jax.device_get(
                    explain_step(ct, pb, topo_keys=meta.topo_keys,
                                 enabled=enabled))
            ff = first_fail(np.asarray(verdicts),
                            np.asarray(valid))[:len(chunk), :n_nodes]
            for row in ff:
                out.append((reject_histogram(row), int((row == -1).sum())))
        return out

    def _judge_oracle(self, item: dict, views: list) -> list:
        """Numpy-oracle fallback (degraded mode, device failure): the
        documented CPU path — same first-fail verdicts, serially."""
        from kubernetes_tpu.models.explain import REASON_TO_FILTER
        from kubernetes_tpu.sched.oracle import OracleScheduler
        orc = OracleScheduler(item["nodes"], item["bound"],
                              namespace_labels=item["ns_labels"])
        # arm the per-node SliceCarve gate (opt-in on the oracle): nodes
        # outside every carveable placement of a pod's requested shape
        # report SLICE_UNAVAILABLE instead of a misleading per-node pass
        orc.slice_explain = True
        out = []
        for pod in views:
            mask, reasons = orc.feasible(pod)
            hist: dict[str, int] = {}
            for reason in reasons.values():
                f = REASON_TO_FILTER.get(reason, reason)
                hist[f] = hist.get(f, 0) + 1
            out.append((hist, int(sum(mask))))
        return out

    # ---- on-demand score breakdown (scheduled pods) ----------------------

    def score_breakdown(self, nodes: list, bound: list, pod,
                        namespace_labels=None) -> Optional[dict]:
        """Why a SCHEDULED pod landed where it did: per-node combined
        scores from the oracle's score pipeline over the feasible set, with
        the top nodes listed. On-demand only (operator/library call) — the
        hot path never computes this."""
        import dataclasses
        from kubernetes_tpu.sched.oracle import OracleScheduler
        profile = self._profile(pod.spec.scheduler_name)
        orc = OracleScheduler(
            nodes, bound,
            weights=profile.weights() if profile is not None else None,
            namespace_labels=namespace_labels)
        view = pod
        if profile is not None and profile.added_affinity:
            view = profile.apply_added_affinity([pod])[0]
        # judge the pod as it looked AT SCHEDULING time: the nodeName its
        # binding wrote would pin the NodeName filter to one node
        view = dataclasses.replace(
            view, spec=dataclasses.replace(view.spec, node_name=""))
        mask, _reasons = orc.feasible(view)
        if not any(mask):
            return None
        scores = orc.score(view, mask)
        ranked = sorted(
            ((n.metadata.name, float(s))
             for n, s, ok in zip(nodes, scores, mask) if ok),
            key=lambda kv: -kv[1])
        return {"feasible": int(sum(mask)), "top": ranked[:5],
                "chosen": pod.spec.node_name or None}
