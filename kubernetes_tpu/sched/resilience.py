"""Self-healing primitives for the connected loop: circuit breaker + watchdog.

Reference shape: the kubelet's runtime-health circuit (``kubelet.go``
runtimeState + the PLEG relist health check) and controller-runtime's
healthz-driven restarts — a component that depends on an unreliable
substrate (here: the device/XLA layer and its own threads) must degrade to
a slower-but-correct path and recover automatically, never hang or die.

``DeviceCircuitBreaker`` tracks consecutive device-program failures and
walks an ordered ladder of degradation levels (mesh -> single-device ->
pure-numpy oracle). After a cooldown it half-opens: exactly one cycle
probes the next-better level; a probe success restores it, a probe
failure restarts the cooldown — and either way the cycle's pods still
schedule (the caller falls back within the same cycle).

``ThreadWatchdog`` monitors registered threads via liveness + heartbeat:
a dead thread restarts immediately, a stalled one (heartbeat older than
``stall_s`` while the target reports work pending) is restarted through
its owner's restart callback. Both paths taint the device-resident drain
context — a thread that died mid-dispatch leaves the resident encoding
unaccountable.
"""

from __future__ import annotations

import logging
import threading
from typing import Callable, Optional

from kubernetes_tpu.metrics.registry import (
    BREAKER_TRIPS,
    DEGRADED_MODE,
    WATCHDOG_RESTARTS,
)
from kubernetes_tpu.utils.clock import Clock, REAL_CLOCK

_LOG = logging.getLogger(__name__)


class DeviceCircuitBreaker:
    """Consecutive-failure breaker over an ordered ladder of levels.

    ``levels`` runs best -> worst, e.g. ``("mesh", "single", "oracle")``.
    Level 0 is healthy; each trip moves one level down. The last level is
    assumed to always work (the oracle is pure numpy)."""

    def __init__(self, levels=("mesh", "single", "oracle"),
                 threshold: int = 3, cooldown_s: float = 30.0,
                 clock: Optional[Clock] = None):
        self.levels = list(levels)
        self.threshold = max(1, int(threshold))
        self.cooldown_s = float(cooldown_s)
        self.clock = clock or REAL_CLOCK
        self._lock = threading.Lock()
        self._idx = 0
        self._fails = 0
        self._tripped_at: Optional[float] = None
        self._last_fail_at: Optional[float] = None
        self._probing = False
        self.trips = 0
        self.restores = 0
        # why each trip happened: "device" (program raised) vs "parity"
        # (the sentinel proved the program returned a WRONG answer). A
        # miscompile that yields garbage without raising is invisible to
        # fail(); trip_now is the sentinel's entry for it.
        self.trip_reasons: dict[str, int] = {}
        self.last_trip_reason: Optional[str] = None
        DEGRADED_MODE.set(0)

    # ---- state -----------------------------------------------------------

    @property
    def index(self) -> int:
        return self._idx

    @property
    def mode(self) -> str:
        return self.levels[self._idx]

    def reset_levels(self, levels) -> None:
        """Operator action (e.g. an explicit mesh install) resets the
        ladder and forgives history — the substrate changed."""
        with self._lock:
            self.levels = list(levels)
            self._idx = 0
            self._fails = 0
            self._tripped_at = None
            self._probing = False
            DEGRADED_MODE.set(0)

    # ---- per-cycle protocol ---------------------------------------------

    def attempt_level(self) -> str:
        """Level to attempt THIS cycle. Normally the current mode; when
        degraded and the cooldown has elapsed, the next-better level (the
        half-open probe). The probe keeps being offered until a device
        outcome lands — a cycle that happens to run no device program
        (empty pop, parked batch) must not consume the recovery window —
        and a probe FAILURE re-arms the cooldown in fail()."""
        with self._lock:
            if (self._idx > 0 and self._tripped_at is not None
                    and self.clock.now() - self._tripped_at
                    >= self.cooldown_s):
                self._probing = True
                return self.levels[self._idx - 1]
            return self.levels[self._idx]

    def succeed(self, level: str,
                dispatched_at: Optional[float] = None) -> None:
        """``dispatched_at``: when the succeeding work was DISPATCHED.
        A pipelined drain can land after newer dispatches already failed;
        such a stale success says nothing about the device NOW, so it
        must neither reset the consecutive-failure count nor pass a
        half-open probe."""
        with self._lock:
            if (dispatched_at is not None
                    and self._last_fail_at is not None
                    and dispatched_at < self._last_fail_at):
                return
            self._fails = 0
            try:
                li = self.levels.index(level)
            except ValueError:
                return
            if self._probing and li == self._idx - 1:
                # half-open probe passed: restore one level
                self._idx = li
                self.restores += 1
                self._probing = False
                self._tripped_at = (self.clock.now() if self._idx > 0
                                    else None)
                _LOG.warning("device circuit breaker: recovered to %r "
                             "(restores=%d)", self.mode, self.restores)
            DEGRADED_MODE.set(self._idx)

    def fail(self, level: str, reason: str = "device") -> str:
        """Record a device failure at ``level``; returns the (possibly
        newly degraded) mode."""
        with self._lock:
            self._last_fail_at = self.clock.now()
            try:
                li = self.levels.index(level)
            except ValueError:
                return self.mode
            if self._probing and li < self._idx:
                # failed probe: stay degraded, restart the cooldown
                self._probing = False
                self._tripped_at = self.clock.now()
                _LOG.warning("device circuit breaker: probe of %r failed; "
                             "staying %r", level, self.mode)
                return self.mode
            self._fails += 1
            if (self._fails >= self.threshold
                    and self._idx < len(self.levels) - 1):
                self._trip_locked(reason)
                _LOG.warning(
                    "device circuit breaker: %d consecutive device "
                    "failures -> degrading to %r (trips=%d)",
                    self.threshold, self.mode, self.trips)
            DEGRADED_MODE.set(self._idx)
            return self.mode

    def _trip_locked(self, reason: str) -> None:
        self._idx += 1
        self.trips += 1
        self._fails = 0
        self._tripped_at = self.clock.now()
        self.trip_reasons[reason] = self.trip_reasons.get(reason, 0) + 1
        self.last_trip_reason = reason
        BREAKER_TRIPS.inc({"reason": reason})

    def trip_now(self, level: str, reason: str = "parity") -> str:
        """Degrade one level IMMEDIATELY (no consecutive-failure count).
        The parity sentinel's entry: a device program that returned a
        provably WRONG answer is a miscompile, not a transient fault —
        waiting for ``threshold`` more wrong answers would bind pods onto
        overcommitted nodes in the meantime. Stale attributions — work
        dispatched at a level the breaker has since degraded past OR
        restored past (the verdict's level is no longer the active one)
        — are ignored: degrading the CURRENT level over an answer from a
        different one would punish a level nobody refuted. A wrong answer
        from a half-open probe re-arms the cooldown like any failed
        probe. Returns the resulting mode."""
        with self._lock:
            self._last_fail_at = self.clock.now()
            try:
                li = self.levels.index(level)
            except ValueError:
                return self.mode
            if self._probing and li < self._idx:
                self._probing = False
                self._tripped_at = self.clock.now()
                _LOG.warning("device circuit breaker: probe of %r returned "
                             "a wrong answer (%s); staying %r",
                             level, reason, self.mode)
                return self.mode
            if li != self._idx:
                return self.mode  # stale: that level is not active now
            if self._idx < len(self.levels) - 1:
                self._trip_locked(reason)
                _LOG.error(
                    "device circuit breaker: %s divergence at level %r -> "
                    "degrading to %r NOW (trips=%d)",
                    reason, level, self.mode, self.trips)
            DEGRADED_MODE.set(self._idx)
            return self.mode


class _Target:
    def __init__(self, name, is_alive, restart, busy):
        self.name = name
        self.is_alive = is_alive
        self.restart = restart
        self.busy = busy
        self.last_beat: Optional[float] = None
        self.restarting = False


class ThreadWatchdog:
    """Liveness + heartbeat monitor over registered threads."""

    def __init__(self, interval_s: float = 2.0, stall_s: float = 120.0,
                 clock: Optional[Clock] = None):
        self.interval_s = float(interval_s)
        self.stall_s = float(stall_s)
        self.clock = clock or REAL_CLOCK
        self._lock = threading.Lock()
        self._targets: dict[str, _Target] = {}  # guarded by: self._lock
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.restarts = 0  # guarded by: self._lock

    def register(self, name: str, is_alive: Callable[[], bool],
                 restart: Callable[[], "Optional[bool]"],
                 busy: Callable[[], bool] = lambda: True) -> None:
        """``is_alive``: False = thread is dead and should exist.
        ``busy``: stall detection only applies while True (an idle thread
        parked on a queue has no heartbeat to give). ``restart`` may
        return False to report that it only intervened (signaled a
        stalled thread, skipped a lost-leadership revive) without
        actually restarting — such sweeps are not counted as restarts."""
        with self._lock:
            t = _Target(name, is_alive, restart, busy)
            t.last_beat = self.clock.now()
            self._targets[name] = t

    def beat(self, name: str) -> None:
        t = self._targets.get(name)  # ktpu-lint: disable=KTL001 -- hot-path GIL-atomic read (resolver/loop threads beat per cycle); a raced registration misses at most one beat
        if t is not None:
            t.last_beat = self.clock.now()

    def check_once(self) -> list[str]:
        """One sweep; returns the names restarted (tests drive this
        directly instead of sleeping through intervals)."""
        restarted = []
        with self._lock:
            targets = list(self._targets.values())
        now = self.clock.now()
        for t in targets:
            try:
                dead = not t.is_alive()
                stalled = (not dead and t.busy()
                           and t.last_beat is not None
                           and now - t.last_beat > self.stall_s)
                if not (dead or stalled) or t.restarting:
                    continue
                t.restarting = True
                try:
                    _LOG.warning("watchdog: thread %r %s; intervening",
                                 t.name, "dead" if dead else "stalled")
                    did = t.restart()
                    if did is not False:
                        with self._lock:
                            self.restarts += 1
                        WATCHDOG_RESTARTS.inc({"thread": t.name})
                        restarted.append(t.name)
                    # reset the beat either way so a signaled-but-alive
                    # stall doesn't hot-loop the intervention every sweep
                    t.last_beat = self.clock.now()
                finally:
                    t.restarting = False
            except Exception:
                _LOG.exception("watchdog: restart of %r failed", t.name)
        return restarted

    def start(self) -> "ThreadWatchdog":
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()

        def loop():
            while not self._stop.wait(self.interval_s):
                self.check_once()
        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="sched-watchdog")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
