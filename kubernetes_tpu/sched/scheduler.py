"""Scheduler main loop — pop batch -> snapshot -> gang step -> assume/bind.

Reference shape: ``pkg/scheduler/scheduler.go`` (Scheduler.Run) +
``schedule_one.go`` (scheduleOne / schedulingCycle / bindingCycle), inverted
for batching: instead of ``wait.Until(ScheduleOne)`` popping one pod, each
iteration drains up to batch_size pods from the queue, runs ONE device gang
step for the whole batch, then assumes + binds asynchronously. Binding
overlaps the next batch's scheduling cycle exactly like the reference's
``go bindingCycle`` — failures roll back via Cache.forget.

Profiles: pods are grouped by spec.schedulerName; unknown names are ignored
(the reference leaves such pods to whatever scheduler owns them).
"""

from __future__ import annotations

import logging
import queue as queue_mod
import threading
import time
from typing import Callable, Optional

from kubernetes_tpu.api.types import Pod
from kubernetes_tpu.config.features import DEFAULT_FEATURE_GATE
from kubernetes_tpu.config.types import SchedulerConfiguration
from kubernetes_tpu.metrics.registry import (
    ATTEMPT_DURATION,
    BATCH_DURATION,
    GANG_ROUNDS,
    QUEUE_DEPTH,
    SCHEDULE_ATTEMPTS,
)
from kubernetes_tpu.models.gang import gang_schedule
from kubernetes_tpu.sched.cache import SchedulerCache
from kubernetes_tpu.sched import preemption as preemption_mod
from kubernetes_tpu.sched.queue import SchedulingQueue
from kubernetes_tpu.utils import sanity
from kubernetes_tpu.utils.events import NullRecorder

_LOG = logging.getLogger(__name__)

# binder(pod, node_name) -> bool success. The client layer supplies the real
# POST pods/<p>/binding; tests pass a lambda.
Binder = Callable[[Pod, str], bool]


class Scheduler:
    def __init__(self, cfg: SchedulerConfiguration, cache: SchedulerCache,
                 queue: SchedulingQueue, binder: Binder,
                 feature_gate=DEFAULT_FEATURE_GATE,
                 preemptor: Optional[Callable] = None,
                 registry=None):
        self.cfg = cfg
        self.cache = cache
        self.queue = queue
        self.binder = binder
        self.features = feature_gate
        self.preemptor = preemptor if preemptor is not None else self._default_preempt
        # Binding pool: a fixed set of long-lived workers with persistent
        # (per-thread keep-alive) API connections. The reference spawns a
        # goroutine per bindingCycle but funnels the POSTs through client-go's
        # shared rate-limited transport; a thread+connection per pod here
        # would pay TCP setup/teardown per binding and melt under load.
        self._bind_q: "queue_mod.Queue[tuple[Pod, str]]" = queue_mod.Queue()
        self._bind_workers: list[threading.Thread] = []
        self._bind_inflight = 0
        self._bind_cv = threading.Condition()
        # preemption nominees awaiting re-schedule: key -> (node, prio, pod, ts).
        # Their freed capacity is reserved against lower-priority pods until
        # they bind (schedule_one.go nominatedNodeName handling). The TTL
        # backstops pods deleted while nominated.
        self._nominated: dict[str, tuple] = {}
        self._nominated_ttl = 300.0
        # PDBs for preemption victim selection; the runner wires this to its
        # poddisruptionbudgets informer
        self.pdb_lister: Callable[[], list] = lambda: []
        # scheduler extenders (extender.go HTTPExtender analog)
        from kubernetes_tpu.sched.extender import HTTPExtender, extender_binder
        self._extenders = [HTTPExtender(c) for c in (cfg.extenders or [])]
        self._extender_bind = (extender_binder(self._extenders)
                               if self._extenders else None)
        # event recording (record.EventRecorder analog); the runner wires
        # a real recorder, library users keep the no-op default
        self.recorder = NullRecorder()
        # out-of-tree plugin registry (framework.Registry analog). Profiles
        # referencing unregistered names fail fast here, like upstream's
        # config validation — register plugins before constructing.
        from kubernetes_tpu.sched.framework import Registry
        self.registry = registry if registry is not None else Registry()
        known = {p.name for p in self.registry.tensor_plugins()} \
            | {p.name for p in self.registry.lifecycle_plugins()}
        for prof in cfg.profiles:
            unknown = set(prof.out_of_tree or ()) - known
            if unknown:
                raise ValueError(
                    f"profile {prof.scheduler_name!r} references "
                    f"unregistered out-of-tree plugins: {sorted(unknown)}")

    # ---- one batch iteration --------------------------------------------

    def run_once(self, wait: float = 0.5) -> int:
        """Schedule one batch. Returns number of pods bound (or assumed)."""
        batch = self.queue.pop_batch(self.cfg.batch_size, wait=wait)
        if not batch:
            return 0
        stats = self.queue.stats()
        for q, v in stats.items():
            QUEUE_DEPTH.set(v, {"queue": q})
        # Slot headroom = everything still pending (this batch + queued):
        # the snapshot reserves that many existing-pod slots so the whole
        # drain binds via incremental patches with stable tensor shapes.
        headroom = len(batch) + sum(stats.values())

        by_profile: dict[str, list[tuple[Pod, int]]] = {}
        for pod, attempts in batch:
            by_profile.setdefault(pod.spec.scheduler_name, []).append((pod, attempts))

        n_bound = 0
        for sched_name, items in by_profile.items():
            profile = self.cfg.profile_for(sched_name)
            if profile is None:
                # Not ours. The informer layer normally filters these out; if
                # one slips through, park it rather than losing it.
                for pod, attempts in items:
                    self.queue.park_unschedulable(pod, attempts)
                continue
            n_bound += self._schedule_group(profile, items, headroom)
        return n_bound

    def _schedule_group(self, profile, items, slot_headroom: int = 0) -> int:
        from kubernetes_tpu.utils.tracing import TRACER
        t0 = time.time()
        pods = [p for p, _ in items]
        with TRACER.span("scheduler/snapshot", pods=len(pods)):
            nodes, ct, meta = self.cache.snapshot(pending_pods=pods,
                                                  slot_headroom=slot_headroom)
        if not nodes:
            for pod, attempts in items:
                self.queue.add_unschedulable(pod, attempts + 1)
                SCHEDULE_ATTEMPTS.inc({"result": "unschedulable"})
            return 0
        batch_keys = {p.key for p in pods}
        now = time.time()
        self._nominated = {
            k: e for k, e in self._nominated.items()
            if now - e[3] < self._nominated_ttl and not self.cache.is_bound(k)}
        entries = [(n, prio, p) for k, (n, prio, p, _ts)
                   in self._nominated.items() if k not in batch_keys]
        if entries:
            # nominees OUTSIDE this batch hold their reservation tensor-side;
            # nominees inside it are protected by the gang rank order instead
            ct = self.cache.overlay_nominated(ct, meta, entries)
        with TRACER.span("scheduler/encode_pods", pods=len(pods)):
            pb = self.cache.encode_pods(pods, meta)
        ext_mask = ext_scores = None
        ext_errors: set = set()
        if self._extenders:
            import numpy as np
            from kubernetes_tpu.sched.extender import run_extenders
            with TRACER.span("scheduler/extenders", pods=len(pods)):
                m, s, ext_errors = run_extenders(self._extenders, pods, nodes)
            Pb, Nb = pb.pod_valid.shape[0], ct.node_valid.shape[0]
            if m is not None:  # pad to bucket dims; padding is neutral
                ext_mask = np.ones((Pb, Nb), bool)
                ext_mask[:m.shape[0], :m.shape[1]] = m
            if s is not None:
                ext_scores = np.zeros((Pb, Nb), np.float32)
                ext_scores[:s.shape[0], :s.shape[1]] = s
            if ext_errors:
                # extender transport failure = attempt ERROR: exclude from
                # the gang batch and requeue with backoff — never feed it to
                # preemption as if the cluster had no room
                valid = np.asarray(pb.pod_valid).copy()
                for i in ext_errors:
                    valid[i] = False
                pb = pb.replace(pod_valid=valid)
        serial = not self.features.enabled("TPUBatchScheduling")
        oot = (None if profile.out_of_tree is None
               else set(profile.out_of_tree))
        plugins = self.registry.tensor_plugins(oot)
        with BATCH_DURATION.time(), TRACER.span(
                "scheduler/gang_schedule", pods=len(pods), nodes=len(nodes)):
            assignment, rounds = gang_schedule(
                ct, pb, seed=self.cfg.seed, fit_strategy=profile.fit_strategy,
                topo_keys=meta.topo_keys, serial=serial,
                max_rounds=self.cfg.max_gang_rounds,
                weights=profile.weights(),
                enabled_filters=profile.enabled_filters,
                ext_mask=ext_mask, ext_scores=ext_scores, plugins=plugins)
        GANG_ROUNDS.observe(rounds)
        if sanity.check_enabled():
            for problem in sanity.check_assignment(assignment, len(nodes)):
                _LOG.error("KTPU_CHECK: %s (batch of %d)", problem, len(pods))

        n_bound = n_err = n_unsched = 0
        dt = time.time() - t0
        for i, ((pod, attempts), a) in enumerate(
                zip(items, assignment[:len(items)])):
            if i in ext_errors:
                self.queue.add_unschedulable(pod, attempts + 1)
                n_err += 1
                continue
            if a >= 0:
                node_name = meta.node_names[int(a)]
                self._nominated.pop(pod.key, None)
                self.cache.assume(pod, node_name)
                self._bind_async(pod, node_name)
                n_bound += 1
            else:
                self._handle_failure(pod, attempts)
                n_unsched += 1
        # every pod in the batch shares one cycle's wall time; record the
        # whole batch with batched lock acquisitions instead of 2 per pod
        for result, n in (("scheduled", n_bound), ("error", n_err),
                          ("unschedulable", n_unsched)):
            if n:
                SCHEDULE_ATTEMPTS.inc({"result": result}, by=n)
                ATTEMPT_DURATION.observe(dt, {"result": result}, n=n)
        return n_bound

    # ---- failure path: PostFilter / preemption ---------------------------

    def _handle_failure(self, pod: Pod, attempts: int):
        # (metrics for the unschedulable result are batched by the caller)
        if self.cache.is_bound(pod.key):
            # Bound by another party while in-flight (its own bound copy may
            # even be why the gang step couldn't place it). Requeueing would
            # cycle it through backoffQ forever — no future event clears it.
            # No FailedScheduling event either: the pod IS scheduled.
            return
        self.recorder.event(pod, "Warning", "FailedScheduling",
                            "no node satisfied the pod's scheduling "
                            "constraints this cycle")
        nominated = None
        if pod.spec.priority > 0 and self.features.enabled("PreemptionSimulation"):
            nominated = self.preemptor(pod)
        if nominated:
            # Victims were evicted: retry immediately (no backoff) so the
            # freed capacity isn't stolen by lower-priority arrivals; until
            # the pod binds, the reservation also shields the capacity from
            # lower-priority pods in other batches (fit_mask nominated terms).
            pod.status.nominated_node_name = nominated
            self._nominated[pod.key] = (nominated, pod.spec.priority, pod,
                                        time.time())
            self.queue.add(pod)
        else:
            self.queue.add_unschedulable(pod, attempts + 1)
            if self.cache.is_bound(pod.key):  # bound event raced the requeue
                self.queue.delete(pod)

    def _default_preempt(self, pod: Pod) -> Optional[str]:
        nodes, _, _ = self.cache.snapshot()
        bound = self.cache.bound_pods(include_assumed=True)
        res = preemption_mod.find_candidate(nodes, bound, pod,
                                            pdbs=self.pdb_lister(),
                                            dra=self.cache.dra_catalog)
        if res is None:
            return None
        for v in res.victims:
            self._evict(v)
        return res.node_name

    def _evict(self, victim: Pod):
        """Delete the victim via the binder-side client (overridden by the
        connected scheduler); cache removal happens via the watch event."""
        self.cache.remove_pod(victim.key)

    # ---- binding cycle (async, overlaps next batch) ----------------------

    def _bind_async(self, pod: Pod, node_name: str):
        with self._bind_cv:
            self._bind_inflight += 1
            if (len(self._bind_workers) < max(1, self.cfg.bind_workers)
                    and len(self._bind_workers) < self._bind_inflight):
                t = threading.Thread(target=self._bind_worker, daemon=True,
                                     name=f"binder-{len(self._bind_workers)}")
                t.start()
                self._bind_workers.append(t)
        self._bind_q.put((pod, node_name))

    def _bind_worker(self):
        while True:
            pod, node_name = self._bind_q.get()
            try:
                self._bind_one(pod, node_name)
            except Exception:
                _LOG.exception("binding %s -> %s", pod.key, node_name)
            finally:
                with self._bind_cv:
                    self._bind_inflight -= 1
                    if self._bind_inflight == 0:
                        self._bind_cv.notify_all()

    def _bind_one(self, pod: Pod, node_name: str):
        from kubernetes_tpu.sched import framework as fw
        # lifecycle hooks honor the pod's profile opt-in like tensor plugins
        profile = self.cfg.profile_for(pod.spec.scheduler_name)
        oot = (None if profile is None or profile.out_of_tree is None
               else set(profile.out_of_tree))
        lifecycle = self.registry.lifecycle_plugins(oot)
        rollback: list = []
        try:
            # Permit -> PreBind -> Bind (framework extension-point order);
            # plugins that allowed/prepared join the unreserve rollback set
            ok, permitted = fw.run_permit(lifecycle, pod, node_name)
            rollback.extend(permitted)
            if ok:
                ok, prebound = fw.run_pre_bind(lifecycle, pod, node_name)
                rollback.extend(p for p in prebound if p not in rollback)
            if ok:
                delegated = None
                if self._extender_bind is not None:
                    # an interested extender with a bindVerb owns the binding
                    delegated = self._extender_bind(pod, node_name)
                ok = (self.binder(pod, node_name) if delegated is None
                      else delegated)
        except Exception:
            ok = False
        if ok:
            fw.run_post_bind(lifecycle, pod, node_name)
            self.recorder.event(pod, "Normal", "Scheduled",
                                f"Successfully assigned {pod.key} to {node_name}")
        else:
            fw.run_unreserve(rollback, pod, node_name)
        if ok:
            self.cache.finish_binding(pod.key)
        else:
            self.cache.forget(pod.key)
            # 409 ordering: if another party bound this pod while it was
            # in-flight, the informer's MODIFIED(nodeName) event (and its
            # queue.delete) may have already fired — requeueing now would
            # retry-409 forever with no further event to clear it. Mirrors
            # the reference's handleSchedulingFailure assigned-pod check.
            if not self.cache.is_bound(pod.key):
                self.queue.add_unschedulable(pod, 1)
                if self.cache.is_bound(pod.key):  # event raced the requeue
                    self.queue.delete(pod)
            SCHEDULE_ATTEMPTS.inc({"result": "error"})

    def wait_for_bindings(self, timeout: float = 5.0):
        deadline = time.time() + timeout
        with self._bind_cv:
            while self._bind_inflight > 0:
                remaining = deadline - time.time()
                if remaining <= 0 or not self._bind_cv.wait(remaining):
                    break

    # ---- loop ------------------------------------------------------------

    def run(self, stop: threading.Event):
        """wait.UntilWithContext(sched.ScheduleOne, 0) analog."""
        while not stop.is_set() and not self.queue.closed:
            self.run_once()
