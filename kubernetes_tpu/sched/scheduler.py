"""Scheduler main loop — pop batch -> snapshot -> gang step -> assume/bind.

Reference shape: ``pkg/scheduler/scheduler.go`` (Scheduler.Run) +
``schedule_one.go`` (scheduleOne / schedulingCycle / bindingCycle), inverted
for batching: instead of ``wait.Until(ScheduleOne)`` popping one pod, each
iteration drains up to batch_size pods from the queue, runs ONE device gang
step for the whole batch, then assumes + binds asynchronously. Binding
overlaps the next batch's scheduling cycle exactly like the reference's
``go bindingCycle`` — failures roll back via Cache.forget.

Profiles: pods are grouped by spec.schedulerName; unknown names are ignored
(the reference leaves such pods to whatever scheduler owns them).
"""

from __future__ import annotations

import dataclasses
import logging
import queue as queue_mod
import threading
import time
from collections import deque
from typing import Callable, Optional

from kubernetes_tpu.api.types import Pod
from kubernetes_tpu.chaos.hooks import chaos_point
from kubernetes_tpu.config.features import DEFAULT_FEATURE_GATE
from kubernetes_tpu.config.types import SchedulerConfiguration
from kubernetes_tpu.metrics.registry import (
    ATTEMPT_DURATION,
    BATCH_DURATION,
    DRAIN_SHARD_MS,
    GANG_ROUNDS,
    LOOP_ERRORS,
    MESH_DEVICES,
    PIPELINE_DEPTH,
    PIPELINE_INFLIGHT,
    QUEUE_DEPTH,
    RESOLVE_BYTES,
    SCHEDULE_ATTEMPTS,
)
from kubernetes_tpu.models.gang import gang_schedule
from kubernetes_tpu.sched.cache import SchedulerCache
from kubernetes_tpu.sched import preemption as preemption_mod
from kubernetes_tpu.sched.queue import SchedulingQueue
from kubernetes_tpu.sched.resilience import DeviceCircuitBreaker
from kubernetes_tpu.utils import sanity
from kubernetes_tpu.utils.events import NullRecorder
from kubernetes_tpu.utils.tracing import FLIGHT

_LOG = logging.getLogger(__name__)

# binder(pod, node_name) -> bool success. The client layer supplies the real
# POST pods/<p>/binding; tests pass a lambda.
Binder = Callable[[Pod, str], bool]

# Resident nominee-reservation bucket in the drain context (encode/patch.py):
# preemption storms patch reservations device-side instead of dropping the
# context. Static — part of the compiled drain shapes.
import os as _os
DRAIN_NOM_BUCKET = int(_os.environ.get("KTPU_DRAIN_NOM_BUCKET", "128"))

# Bounded resolve wait: how long the scheduling thread waits on the
# resolver's Event before degrading to an inline device fetch — a dead or
# stalled resolver must never hang the loop.
RESOLVE_WAIT_S = float(_os.environ.get("KTPU_RESOLVE_TIMEOUT", "30"))


class Scheduler:
    def __init__(self, cfg: SchedulerConfiguration, cache: SchedulerCache,
                 queue: SchedulingQueue, binder: Binder,
                 feature_gate=DEFAULT_FEATURE_GATE,
                 preemptor: Optional[Callable] = None,
                 registry=None, bulk_binder: Optional[Callable] = None):
        self.cfg = cfg
        self.cache = cache
        self.queue = queue
        self.binder = binder
        # bulk_binder(pairs: [(Pod, node_name)]) -> [bool]: one API call
        # binding a whole gang batch (POST pods/-/binding). Pods needing
        # per-pod ceremony (lifecycle hooks, DRA claims, volume binding,
        # extender-delegated binds) still go through ``binder``.
        self._bulk_binder = bulk_binder
        self.features = feature_gate
        self._custom_preemptor = preemptor is not None
        self.preemptor = preemptor if preemptor is not None else self._default_preempt
        # Binding pool: a fixed set of long-lived workers with persistent
        # (per-thread keep-alive) API connections. The reference spawns a
        # goroutine per bindingCycle but funnels the POSTs through client-go's
        # shared rate-limited transport; a thread+connection per pod here
        # would pay TCP setup/teardown per binding and melt under load.
        self._bind_q: "queue_mod.Queue[tuple[Pod, str]]" = queue_mod.Queue()
        self._bind_workers: list[threading.Thread] = []
        self._bind_inflight = 0
        self._bind_cv = threading.Condition()
        # device-resident drain context (see _schedule_drain): HBM replica
        # of the cluster encoding, valid while the only pending cache deltas
        # are assumes this loop folded on device
        self._drain_ctx = None
        # ---- device mesh (multi-chip scheduling) -------------------------
        # cfg.meshShape / KTPU_MESH arm a ("pods","nodes") mesh: the drain's
        # cluster encoding device_puts SHARDED (node axis split), pod stacks
        # split on "pods", and the jitted programs lower to GSPMD
        # collectives. _mesh_epoch bumps on every reshape; the drain context
        # records the epoch it was staged under, so a reshape forces a
        # rebuild instead of patching arrays whose layout no longer matches.
        self._mesh = None
        self._mesh_epoch = 0
        # operator-configured mesh (what the breaker restores to after a
        # degrade window; _install_mesh toggles the ACTIVE mesh without
        # touching this)
        self._configured_mesh = None
        # device circuit breaker: consecutive device-program failures walk
        # mesh -> single-device -> pure-numpy oracle, with half-open
        # recovery (sched/resilience.py). Levels gain "mesh" in set_mesh.
        self.breaker = DeviceCircuitBreaker(
            levels=("single", "oracle"), threshold=cfg.breaker_threshold,
            cooldown_s=cfg.breaker_cooldown_s)
        self._attempt_level = self.breaker.mode
        # device-parity sentinel (audit/sentinel.py): every Kth drain/wave
        # dispatch is re-judged against the numpy oracle off this thread;
        # a refuted answer trips the breaker with reason "parity" — the
        # runtime guard for the GSPMD-miscompile class the startup canaries
        # can't cover. breaker_ref is a callable because tests swap
        # self.breaker wholesale.
        parity_every = cfg.parity_sample_every
        env_parity = _os.environ.get("KTPU_PARITY_EVERY")
        if env_parity is not None:
            try:
                parity_every = max(0, int(env_parity))
            except ValueError:
                _LOG.warning("ignoring invalid KTPU_PARITY_EVERY=%r",
                             env_parity)
        self.sentinel = None
        if parity_every > 0:
            from kubernetes_tpu.audit.sentinel import ParitySentinel
            self.sentinel = ParitySentinel(lambda: self.breaker,
                                           every=parity_every)
        # decision-provenance explainer (sched/explainer.py): re-runs the
        # static filter stack in per-filter-output mode over unschedulable
        # pods on its own thread — upstream-style FailedScheduling
        # messages, ktpu why, and unschedulable-reason metrics with zero
        # dispatches added to the drain cycle. recorder_ref is a callable
        # because the runner swaps self.recorder after construction.
        explain_on = cfg.explainer_enabled
        env_explain = _os.environ.get("KTPU_EXPLAIN")
        if env_explain is not None:
            explain_on = env_explain != "0"
        self.explainer = None
        if explain_on:
            from kubernetes_tpu.sched.explainer import SchedulingExplainer
            self.explainer = SchedulingExplainer(cfg,
                                                 lambda: self.recorder)
        # watchdog heartbeats (the runner wires these to its watchdog;
        # library embedders keep the no-ops)
        self.heartbeat: Callable[[], None] = lambda: None
        self.resolver_heartbeat: Callable[[], None] = lambda: None
        mesh_shape = cfg.mesh_shape
        env_mesh = _os.environ.get("KTPU_MESH")
        if env_mesh is not None:
            from kubernetes_tpu.config.types import ValidationError, validate
            from kubernetes_tpu.parallel.mesh import parse_mesh_shape
            try:
                env_shape = parse_mesh_shape(env_mesh)
                # same rules the YAML path enforces (pow2 axes, pods axis
                # divides batchSize) — the env knob must not smuggle in a
                # shape validate() would have rejected at construction
                validate(dataclasses.replace(cfg, mesh_shape=env_shape))
                mesh_shape = env_shape
            except (ValidationError, ValueError) as e:
                _LOG.warning("ignoring invalid KTPU_MESH=%r: %s",
                             env_mesh, e)
        if mesh_shape is not None and mesh_shape[0] * mesh_shape[1] > 1:
            from kubernetes_tpu.parallel.mesh import mesh_from_shape
            try:
                self.set_mesh(mesh_from_shape(mesh_shape))
            except Exception:
                # fewer devices than configured (or no backend yet): run
                # single-device rather than refuse to schedule — the mesh is
                # a throughput knob, not a correctness requirement
                _LOG.warning("mesh shape %s unavailable; running "
                             "single-device", mesh_shape, exc_info=True)
        MESH_DEVICES.set(self._mesh.devices.size if self._mesh else 1)
        # Fused fold: churn patches ride the drain dispatch as the resident
        # program's third input instead of a separate apply_ctx_patch
        # dispatch (and fold-safe churn skips the pipeline drain). The env
        # knob exists so a bench A/B can flip modes without config surgery.
        self._fused_fold = cfg.fused_fold
        env_fused = _os.environ.get("KTPU_FUSED_FOLD")
        if env_fused is not None:
            self._fused_fold = env_fused != "0"
        # Pre-sharded double-buffered batch staging (sched/staging.py):
        # dispatch-time stage_drain_batch becomes a buffer swap. The cache
        # owns the arena (it owns the mesh staging helpers); the env knob
        # KTPU_STAGE_ARENA=0 wins over config for bench A/Bs.
        self.cache.configure_staging(cfg.staging_arena)
        # context lifecycle counters (benchmarks report these: a healthy
        # churn run shows folds/patches >> rebuilds; "folds" are churn
        # deltas fused into a drain dispatch, "patches" are separate
        # apply_ctx_patch dispatches — steady-state fused churn keeps
        # patches at 0)
        self.ctx_stats = {"patches": 0, "folds": 0, "rebuilds": 0,
                          "unfit": 0, "reasons": {}}
        # per-drain-cycle debug trail (pop size, t_pop, t_dispatch,
        # t_resolve) when KTPU_CYCLE_LOG=1
        self.cycle_log: list = [] if _os.environ.get(
            "KTPU_CYCLE_LOG") else None
        # Multi-deep software pipeline: in-flight drains awaiting resolution,
        # oldest first (the device executes them in dispatch order). Bounded
        # by cfg.pipeline_depth — dispatch of drain k+1..k+N overlaps the
        # host-side resolve of drain k (schedule_one.go's async bindingCycle
        # overlapping the next scheduling cycle, generalized to N drains).
        self._pending: "deque[dict]" = deque()
        # Dedicated resolver thread: device_get of each drain's results runs
        # here the moment the device finishes, NOT on the scheduling thread —
        # which means the scheduler never parks inside the device tunnel
        # while informer bursts hold the GIL (the resolve_wait variance of
        # BENCH_r05). The scheduling thread waits on a plain Event instead.
        # serializes (queue, thread) swaps between the scheduling thread's
        # lazy spawn, the watchdog's restart_resolver, and close()
        self._resolver_swap_lock = threading.Lock()
        self._resolver_q: Optional["queue_mod.Queue"] = None  # guarded by: self._resolver_swap_lock
        self._resolver_thread: Optional[threading.Thread] = None  # guarded by: self._resolver_swap_lock
        self._use_resolver = _os.environ.get(
            "KTPU_RESOLVER_THREAD", "1") != "0"
        # Fleet mode (sched/fleet.py FleetRunner sets this): pops are split
        # into TENANT-HOMOGENEOUS drain chunks so every tenant's pods sit at
        # batch positions 0..n of their own chunk — the structural property
        # that makes fleet-batched placements bit-equal to independent
        # per-tenant runs (same seed, same tie-break salts).
        self.fleet_mode = False
        # fragment pops parked while the device is busy (see run_once)
        self._staged: list = []
        self._staged_once = False   # a parked fragment merges at most once
        self._last_pop_full = False  # burst heuristic: arrivals are hot
        # ---- topology slice carving (topology/) --------------------------
        # Carve plans for slice gangs that could NOT be placed this cycle:
        # gang id -> {"res": CarveResult, "members": [...], "nodes": [...],
        # "shape": ..., "dims": ...}. Written by _carve_slices and consumed
        # by _handle_failures within the SAME _run_batch call — scheduling
        # thread only, cleared each cycle.
        self._carve_plans: dict[str, dict] = {}
        self._carve_lock = threading.Lock()
        # shapes seen on slice gangs + carve outcome counters — read by the
        # runner's status thread (topology_status)
        self._carve_shapes_seen: set = set()  # guarded by: self._carve_lock
        self._carve_stats = {"carved": 0, "failed": 0, "slicePreempts": 0}  # guarded by: self._carve_lock
        # preemption nominees awaiting re-schedule: key -> (node, prio, pod, ts).
        # Their freed capacity is reserved against lower-priority pods until
        # they bind (schedule_one.go nominatedNodeName handling). The TTL
        # backstops pods deleted while nominated.
        self._nominated: dict[str, tuple] = {}
        self._nominated_ttl = 300.0
        # API-visible nominations set by OTHER components (the descheduler's
        # gang defrag writes status.nominatedNodeName after draining nodes
        # for a gang). Staged under a lock by the informer thread and folded
        # into _nominated on the scheduling thread each cycle — _nominated
        # itself is single-thread state.
        self._nominated_staged: dict[str, Optional[tuple]] = {}
        self._nominated_staged_lock = threading.Lock()
        # keys whose _nominated entry came from the API: only those may be
        # cleared by an API-side removal (tombstone) — the scheduler's own
        # preemption nominations are in-memory only and must survive
        # unrelated MODIFIED events that naturally carry no nominatedNodeName
        self._nominated_external: set[str] = set()
        # PDBs for preemption victim selection; the runner wires this to its
        # poddisruptionbudgets informer
        self.pdb_lister: Callable[[], list] = lambda: []
        # scheduler extenders (extender.go HTTPExtender analog)
        from kubernetes_tpu.sched.extender import HTTPExtender, extender_binder
        self._extenders = [HTTPExtender(c) for c in (cfg.extenders or [])]
        self._extender_bind = (extender_binder(self._extenders)
                               if self._extenders else None)
        # event recording (record.EventRecorder analog); the runner wires
        # a real recorder, library users keep the no-op default
        self.recorder = NullRecorder()
        # out-of-tree plugin registry (framework.Registry analog). Profiles
        # referencing unregistered names fail fast here, like upstream's
        # config validation — register plugins before constructing.
        from kubernetes_tpu.sched.framework import Registry
        self.registry = registry if registry is not None else Registry()
        known = {p.name for p in self.registry.tensor_plugins()} \
            | {p.name for p in self.registry.lifecycle_plugins()}
        for prof in cfg.profiles:
            unknown = set(prof.out_of_tree or ()) - known
            if unknown:
                raise ValueError(
                    f"profile {prof.scheduler_name!r} references "
                    f"unregistered out-of-tree plugins: {sorted(unknown)}")

    # ---- device mesh -----------------------------------------------------

    def set_mesh(self, mesh) -> None:
        """Install (or drop, with ``None``) the scheduling mesh — the
        OPERATOR-facing entry. Also records the mesh as the configured
        layout the circuit breaker restores to, and resets the breaker's
        degradation ladder (an explicit reshape means the substrate
        changed; old trip history is moot)."""
        self._configured_mesh = mesh
        self._install_mesh(mesh)
        self.breaker.reset_levels(
            ("mesh", "single", "oracle") if mesh is not None
            else ("single", "oracle"))

    def _install_mesh(self, mesh) -> None:
        """Activate a mesh (or drop to single-device). Bumps the mesh
        epoch so a resident drain context staged under the OLD layout
        rebuilds at its next dispatch — patching sharded arrays with a
        stale-layout patch would be silently wrong, never just slow. The
        breaker's degrade/restore path uses this directly so a temporary
        single-device window never forgets the configured mesh."""
        self._mesh = mesh
        self._mesh_epoch += 1
        self.cache.set_mesh(mesh)
        MESH_DEVICES.set(mesh.devices.size if mesh is not None else 1)

    def _mesh_scope(self):
        """Context manager activating the mesh for a jitted dispatch (a
        no-op scope when single-device)."""
        if self._mesh is None:
            import contextlib
            return contextlib.nullcontext()
        return self._mesh

    @property
    def _winners_sharding(self):
        if self._mesh is None:
            return None
        from kubernetes_tpu.parallel.mesh import replicated
        return replicated(self._mesh)

    def _stage_batch(self, pb_stack, ticket, n_pods: int):
        """Dispatch-time batch staging with honest attribution: the whole
        operation is ``scheduler/stage_batch`` (the span r06 pinned the
        sharded regression on) and the arena redeem within it is
        ``scheduler/stage_swap`` — in steady state the swap IS the whole
        cost, and a fallback's inline device_put shows up as stage_batch
        time exceeding stage_swap. EVERY drain staging site goes through
        here (warm_drain included) so bench attribution can never miss a
        transfer again."""
        from kubernetes_tpu.utils.tracing import TRACER
        with TRACER.span("scheduler/stage_batch", pods=n_pods):
            if ticket is not None:
                with TRACER.span("scheduler/stage_swap", pods=n_pods):
                    staged = self.cache.stage_redeem(ticket)
                if staged is not None:
                    return staged
            return self.cache.stage_drain_batch(pb_stack)

    def _stage_fill(self, fill: int):
        """Device-resident fill scalar for a fresh context: the steady
        state donates the previous drain's new_fill through, and staging
        the rebuild-time int as the SAME strong-int32 device scalar keeps
        one compiled drain variant (and zero implicit transfers) from the
        first post-rebuild dispatch on."""
        import jax
        import numpy as np
        if self._mesh is None:
            return jax.device_put(np.int32(fill))
        return jax.device_put(np.int32(fill), self._winners_sharding)

    # ---- external nominations -------------------------------------------

    def nominate_external(self, pod: Pod, node_name: str) -> None:
        """Register a nominatedNodeName another component wrote to the API
        (schedule_one.go honors these the same way it honors its own
        preemption nominations). The reservation shields the node's
        capacity from lower-priority pods until the nominee binds — without
        it, a descheduler gang-defrag race is lost to whichever replacement
        pod reaches the activeQ first. Safe to call from the informer
        thread; entries fold into _nominated on the scheduling thread.
        An empty ``node_name`` stages a CLEAR: the API removed the field
        (e.g. the descheduler aborted a half-executed gang set), so the
        reservation must not pin capacity for the rest of its TTL. Clears
        only touch API-origin entries — the scheduler's own preemption
        nominations are in-memory only and must survive unrelated MODIFIED
        events that naturally carry no nominatedNodeName."""
        with self._nominated_staged_lock:
            if node_name:
                self._nominated_staged[pod.key] = (
                    node_name, pod.spec.priority, pod, time.time())
            else:
                self._nominated_staged[pod.key] = None

    def _fold_staged_nominations(self) -> None:
        if not self._nominated_staged:
            return
        with self._nominated_staged_lock:
            staged, self._nominated_staged = self._nominated_staged, {}
        # entries pruned since registration (bound / TTL) drop out of the
        # external set too, keeping it bounded by live nominations
        self._nominated_external &= set(self._nominated)
        for k, e in staged.items():
            if e is None:
                if k in self._nominated_external:
                    self._nominated.pop(k, None)
                    self._nominated_external.discard(k)
            elif not self.cache.is_bound(k):
                self._nominated[k] = e
                self._nominated_external.add(k)

    # ---- dispatch pipeline ----------------------------------------------

    @property
    def _pending_drain(self) -> Optional[dict]:
        """Oldest in-flight drain, or None when the pipeline is empty.
        Read-only compat view (tests poll it); the pipeline itself is
        ``self._pending``."""
        return self._pending[0] if self._pending else None

    @staticmethod
    def _drain_ready(pend: dict) -> bool:
        ev = pend.get("done")
        if ev is not None:
            return ev.is_set()
        try:
            return pend["assignments"].is_ready()
        except Exception:
            # a handle that can't even answer is_ready is broken: route it
            # to resolve NOW, where the failure is handled and counted
            LOOP_ERRORS.inc({"site": "drain_ready"})
            return True

    def _resolve_ready(self) -> int:
        """Land every in-flight drain whose results are already on the host
        (no blocking) — finished work must not sit behind a pop or a deeper
        pipeline. Returns pods bound."""
        n = 0
        while self._pending and self._drain_ready(self._pending[0]):
            n += self._resolve_one()
        return n

    def _submit_resolve(self, pend: dict) -> None:
        """Hand the drain's device handles to the resolver thread: it blocks
        in device_get (GIL released in the runtime) and publishes numpy
        results + sets ``pend['done']``. KTPU_RESOLVER_THREAD=0 disables the
        thread; _resolve_one then fetches inline as before."""
        if not self._use_resolver:
            return
        pend["done"] = threading.Event()
        self._ensure_resolver().put(pend)

    def _ensure_resolver(self) -> "queue_mod.Queue":
        """Resolver queue, (re)spawning the thread if dead — the resolver
        self-heals on thread death; a STALLED one is the watchdog's job
        (restart_resolver). Serialized with restart_resolver: the watchdog
        swaps the queue/thread pair from its own thread, and a dispatch
        racing the swap must never see a half-installed pair."""
        with self._resolver_swap_lock:
            if (self._resolver_thread is None
                    or not self._resolver_thread.is_alive()):
                self._spawn_resolver_locked()
            return self._resolver_q

    def _spawn_resolver_locked(self) -> None:
        """Install a fresh (queue, thread) pair and MIGRATE the old
        queue's drains — a dead thread's queued pends would otherwise
        never get their done Event set, and each would stall a resolve
        for the full bounded wait. Queue installed before the thread
        becomes visible: a concurrent reader can never observe (alive
        thread, no queue)."""
        old_q = self._resolver_q
        new_q = queue_mod.Queue()
        t = threading.Thread(
            target=self._resolver_loop, args=(new_q,),
            daemon=True, name="drain-resolver")
        self._resolver_q = new_q
        self._resolver_thread = t
        t.start()
        if old_q is not None:
            try:
                while True:
                    it = old_q.get_nowait()
                    if it is not None:
                        new_q.put(it)
            except queue_mod.Empty:
                pass
            old_q.put(None)  # poison, should the old thread still wake

    def restart_resolver(self) -> None:
        """Watchdog restart path: swap in a fresh resolver thread and move
        the old queue's drains over. A merely-stalled old thread drains to
        its poison pill when it wakes; the pend it held in flight resolves
        late or falls to _resolve_one's bounded-wait inline fetch. The
        resident ctx is NOT touched here — resolver death loses no device
        state, only a fetch."""
        with self._resolver_swap_lock:
            self._spawn_resolver_locked()

    def _resolver_loop(self, q: "queue_mod.Queue") -> None:
        import jax
        while True:
            pend = q.get()
            if pend is None:  # poison pill from close()/restart
                return
            try:
                self.resolver_heartbeat()
                chaos_point("resolver")
                pend["resolved"] = jax.device_get(
                    (pend["assignments"], pend["rounds"]))
            except Exception:
                # surface on the scheduling thread: _resolve_one retries the
                # fetch inline and handles the real error
                LOOP_ERRORS.inc({"site": "resolver"})
                _LOG.exception("drain resolver device_get failed")
            finally:
                pend["done"].set()

    # ---- one batch iteration --------------------------------------------

    def run_once(self, wait: float = 0.5) -> int:
        """Schedule one pop's worth of pods. Returns pods bound (or assumed).

        A pop can yield up to ``batch_size * max_drain_batches`` pods; a deep
        backlog takes the fused drain path (one device program for many
        batches, models/gang.py gang_drain) while shallow pops run the
        single-batch program."""
        self._fold_staged_nominations()
        # land finished drains' bindings as soon as the device is done
        # (don't let finished results sit behind a blocking pop)
        n_early = self._resolve_ready()
        cap = self.cfg.batch_size * max(1, self.cfg.max_drain_batches)
        batch = self.queue.pop_batch(
            max(1, cap - len(self._staged)),
            wait=0.05 if self._pending else wait)
        if self._staged:
            batch = self._staged + batch
            self._staged = []
        if not batch:
            return n_early + self._resolve_pending()
        try:
            return n_early + self._run_batch(batch, cap)
        except BaseException:
            # mid-cycle failure with the popped batch in hand: the pods
            # are in no queue and no watch event will re-deliver them —
            # requeue before the exception escapes to run()'s self-healing
            # (or kills the thread for the watchdog). Without this, an
            # absorbed failure would silently strand the whole pop.
            self._rescue_batch(batch)
            raise

    def _rescue_batch(self, batch) -> None:
        self._staged = []  # a fragment staged THIS cycle is part of batch
        rescued = 0
        for pod, attempts in batch:
            if not self.cache.is_assumed_or_bound(pod.key):
                self.queue.add_unschedulable(pod, attempts + 1)
                rescued += 1
        if rescued:
            _LOG.warning("mid-cycle failure: requeued %d popped pods",
                         rescued)

    def _run_batch(self, batch, cap: int) -> int:
        """The body of one cycle once a batch is in hand (split out so
        run_once can rescue the batch on ANY failure)."""
        if (len(batch) < self.cfg.batch_size and not self._staged_once
                and (self._pending or self._last_pop_full)):
            # A fragment pop while the device is busy or right after a
            # full-size pop — typically the middle of a creation burst,
            # when the informer thread is decoding thousands of watch
            # events and any host work crawls (single-core GIL). Park it
            # once, settle the OLDEST in-flight drain (device-bound anyway),
            # and let the fragment merge with the arrivals that land
            # meanwhile: tiny mid-burst drains were the connected p99
            # tail.
            self._staged = batch
            self._staged_once = True
            return self._resolve_one()
        self._staged_once = False
        self._last_pop_full = len(batch) >= cap
        self._carve_plans.clear()  # plans never outlive their cycle
        stats = self.queue.stats()
        for q, v in stats.items():
            QUEUE_DEPTH.set(v, {"queue": q})
        # Slot headroom = everything still pending (this batch + queued):
        # the snapshot reserves that many existing-pod slots so the whole
        # drain binds via incremental patches with stable tensor shapes.
        headroom = len(batch) + sum(stats.values())

        by_profile: dict[str, list[tuple[Pod, int]]] = {}
        for pod, attempts in batch:
            by_profile.setdefault(pod.spec.scheduler_name, []).append((pod, attempts))

        n_bound = n_landed = 0
        serial = not self.features.enabled("TPUBatchScheduling")
        # degrade-don't-die routing: the breaker picks the level this cycle
        # attempts — the current degraded mode, or one better when the
        # half-open window opened (the probe). "mesh"/"single" still run
        # the tensor programs (mesh installed or dropped to match);
        # "oracle" bypasses the device entirely.
        level = self.breaker.attempt_level()
        self._attempt_level = level
        if level != "oracle":
            want = self._configured_mesh if level == "mesh" else None
            if want is not self._mesh:
                _LOG.warning("degraded-mode transition: running %s "
                             "(breaker mode %r)",
                             "under the configured mesh" if want is not None
                             else "single-device", self.breaker.mode)
                self._install_mesh(want)
        elif self._pending:
            # oracle mode dispatches nothing new; in-flight drains from
            # before the degrade must not linger (bounded waits inside)
            n_landed += self._resolve_pending()
        for sched_name, items in by_profile.items():
            profile = self.cfg.profile_for(sched_name)
            if profile is None:
                # Not ours. The informer layer normally filters these out; if
                # one slips through, park it rather than losing it.
                for pod, attempts in items:
                    self.queue.park_unschedulable(pod, attempts)
                continue
            if level == "oracle":
                n_bound += self._schedule_oracle(profile, items)
                continue
            # slice-shaped gangs never ride the drain path: the carve is a
            # group-path stage (_schedule_group), and a resident drain would
            # place members as independent pods — feasible but not
            # contiguous. Split them out and route them per gang.
            slice_items = [it for it in items
                           if self._slice_shape_of(it[0]) is not None]
            if slice_items:
                items = [it for it in items
                         if self._slice_shape_of(it[0]) is None]
                for chunk in self._slice_chunks(slice_items):
                    n_bound += self._schedule_group(profile, chunk, headroom)
            if not items:
                continue
            if ((len(items) > self.cfg.batch_size
                    or self._drain_ctx is not None)
                    and not serial and not self._extenders):
                n_bound += self._schedule_drain(profile, items, headroom)
            else:
                for chunk in self._tenant_chunks(items, self.cfg.batch_size):
                    n_bound += self._schedule_group(profile, chunk, headroom)
        return n_landed + n_bound

    def _tenant_chunks(self, items: list, P: int) -> list[list]:
        """Split a popped batch into device chunks of up to ``P`` pods.
        Single-tenant (the default): plain consecutive slices, unchanged.
        Fleet mode: chunks are TENANT-HOMOGENEOUS — each tenant's pods,
        in pop (priority) order, fill their own chunks from position 0,
        so the per-position tie-break salt and the per-chunk balance
        guard see exactly what a standalone run of that tenant would.
        The chunk count is bounded by max_drain_batches (one compiled
        drain width): surplus partial chunks merge into mixed chunks,
        which stay CORRECT (the tenant gate isolates them) but waive
        bit-parity — only full per-tenant blocks claim it."""
        if not self.fleet_mode:
            return [items[i:i + P] for i in range(0, len(items), P)]
        from kubernetes_tpu.encode.snapshot import tenant_label_of
        groups: dict[str, list] = {}
        order: list[str] = []
        for it in items:
            t = tenant_label_of(it[0].metadata.labels) or ""
            if t not in groups:
                groups[t] = []
                order.append(t)
            groups[t].append(it)
        if len(order) <= 1:
            return [items[i:i + P] for i in range(0, len(items), P)]
        chunks: list[list] = []
        for t in order:
            g = groups[t]
            chunks += [g[i:i + P] for i in range(0, len(g), P)]
        cap = max(max(1, self.cfg.max_drain_batches), -(-len(items) // P))
        # Bound the compiled batch axis by merging ADJACENT chunks — the
        # flattened pod order (and with it the pop's cross-tenant priority
        # order inside the sequential batch scan) is preserved exactly;
        # a size-sorted merge would let a larger low-priority chunk fold
        # its wins into contested capacity ahead of an earlier
        # higher-priority one.
        while len(chunks) > cap:
            best_i = None
            best = P + 1
            for i in range(len(chunks) - 1):
                comb = len(chunks[i]) + len(chunks[i + 1])
                if comb <= P and comb < best:
                    best, best_i = comb, i
            if best_i is None:
                break  # nothing merges within P: accept the extra width
            chunks[best_i] = chunks[best_i] + chunks[best_i + 1]
            del chunks[best_i + 1]
        return chunks

    # ---- topology slice carving (topology/) ------------------------------

    def _slice_shape_of(self, pod: Pod) -> Optional[tuple]:
        """The pod's requested slice shape: the slice-shape label, else a
        slice-shaped ResourceClaim (sched/dra.py). None = not a slice pod
        (malformed shapes schedule as normal pods by design)."""
        from kubernetes_tpu.topology.slicing import shape_of_labels
        s = shape_of_labels(pod.metadata.labels)
        if s is None and getattr(self.cache, "dra_catalog", None) is not None:
            s = self.cache.dra_catalog.pod_slice_shape(pod)
        return s

    def _slice_chunks(self, items: list) -> list[list]:
        """Group slice pods into device chunks: members of one gang stay
        together (the carve is per-gang), chunks are tenant-homogeneous
        (same property _tenant_chunks guarantees in fleet mode), and whole
        gangs pack greedily up to batch_size — an oversize gang still rides
        ONE chunk (the pod bucket grows; contiguity over bucket reuse)."""
        from kubernetes_tpu.encode.snapshot import tenant_label_of
        from kubernetes_tpu.topology.slicing import GANG_LABEL
        gangs: dict[tuple, list] = {}
        order: list[tuple] = []
        for it in items:
            pod = it[0]
            t = tenant_label_of(pod.metadata.labels) or ""
            g = (pod.metadata.labels or {}).get(GANG_LABEL) or f"pod:{pod.key}"
            key = (t, g)
            if key not in gangs:
                gangs[key] = []
                order.append(key)
            gangs[key].append(it)
        chunks: list[list] = []
        cur: list = []
        cur_tenant = None
        P = self.cfg.batch_size
        for key in order:
            g = gangs[key]
            if cur and (cur_tenant != key[0] or len(cur) + len(g) > P):
                chunks.append(cur)
                cur = []
            cur = cur + g
            cur_tenant = key[0]
        if cur:
            chunks.append(cur)
        return chunks

    def _carve_slices(self, items, nodes, ct, meta, pb, ext_mask):
        """Carve contiguous sub-slices for the batch's slice gangs and pin
        members to their cells.

        One ``carve_step`` dispatch per gang over the SAME snapshot tensors
        gang_schedule is about to run on; earlier gangs' cells are claimed
        against later ones. Returns ``(ext_mask, gang_of, gang_nodes)``:
        winners get a one-hot ext_mask row pinning member -> cell node (the
        gang program's atomicity/tenant machinery is untouched — the carve
        only narrows candidates); a failed carve writes all-False rows so
        the members fail through the NORMAL failure path, where the stashed
        plan (_carve_plans) drives slice preemption and the explain event.
        """
        import numpy as np
        from kubernetes_tpu.encode.snapshot import TENANT_KEY_ID
        from kubernetes_tpu.topology import carve as carve_mod
        from kubernetes_tpu.topology.slicing import (GANG_LABEL,
                                                     coords_of_labels,
                                                     grid_dims, shape_str)
        pods = [p for p, _ in items]
        groups: dict[str, list[int]] = {}
        shapes: dict[str, tuple] = {}
        for i, pod in enumerate(pods):
            shape = self._slice_shape_of(pod)
            if shape is None:
                continue
            g = (pod.metadata.labels or {}).get(GANG_LABEL) or f"pod:{pod.key}"
            groups.setdefault(g, []).append(i)
            shapes[g] = shape
        if not groups:
            return ext_mask, {}, {}
        dims = grid_dims([c for c in (coords_of_labels(n.metadata.labels)
                                      for n in nodes) if c is not None])
        Pb, Nb = pb.pod_valid.shape[0], ct.node_valid.shape[0]
        if ext_mask is None:
            ext_mask = np.ones((Pb, Nb), bool)
        pod_labels = np.asarray(pb.pod_labels)
        requests = np.asarray(pb.requests)
        claimed = np.zeros(Nb, bool)
        gang_of: dict[int, str] = {}
        gang_nodes: dict[str, dict[int, int]] = {}
        for g in sorted(groups):
            # member order is sorted by pod key — the SAME order the oracle
            # carver uses, so member m <-> C-order box cell m on both sides
            # (part of the bit-parity contract)
            idxs = sorted(groups[g], key=lambda i: pods[i].key)
            shape = shapes[g]
            want = shape[0] * shape[1] * shape[2]
            res = None
            asg = None
            if len(idxs) == want and dims is not None:
                # conservative homogeneous view of the gang: every cell must
                # fit the elementwise-MAX member request (the oracle carver
                # mirrors this)
                member_req = requests[idxs].max(axis=0)
                tenant = (int(pod_labels[idxs[0], TENANT_KEY_ID])
                          if pod_labels.shape[1] > TENANT_KEY_ID else -1)
                res = carve_mod.carve_device(ct, member_req, tenant,
                                             claimed, dims, shape)
                asg = carve_mod.select_assignment(res)
            with self._carve_lock:
                self._carve_shapes_seen.add(shape_str(shape))
                self._carve_stats["carved" if asg is not None
                                  else "failed"] += 1
            for i in idxs:
                gang_of[i] = g
            if asg is None:
                for i in idxs:
                    ext_mask[i, :] = False
                self._carve_plans[g] = {
                    "res": res, "dims": dims, "shape": shape, "nodes": nodes,
                    "members": [pods[i] for i in idxs]}  # cell order
                continue
            gang_nodes[g] = {}
            for m, i in enumerate(idxs):
                ni = asg[m]
                row = np.zeros(Nb, bool)
                row[ni] = True
                ext_mask[i] &= row  # AND keeps an extender's veto binding
                claimed[ni] = True
                gang_nodes[g][i] = ni
        return ext_mask, gang_of, gang_nodes

    def _carve_gang_of(self, pod: Pod) -> Optional[str]:
        """Gang id of a slice pod whose carve FAILED this cycle (a plan is
        stashed), else None."""
        from kubernetes_tpu.topology.slicing import GANG_LABEL
        if not self._carve_plans or self._slice_shape_of(pod) is None:
            return None
        g = (pod.metadata.labels or {}).get(GANG_LABEL) or f"pod:{pod.key}"
        return g if g in self._carve_plans else None

    @staticmethod
    def _slice_fail_message(plan: dict) -> str:
        """The slice flavor of failed_scheduling_message: "0/N origins can
        host a 2x2x4 slice: <why>" with N = candidate origins actually
        evaluated (rotations x torus cells)."""
        from kubernetes_tpu.topology import carve as carve_mod
        from kubernetes_tpu.topology.slicing import shape_str
        res = plan["res"]
        shape = shape_str(plan["shape"])
        want = plan["shape"][0] * plan["shape"][1] * plan["shape"][2]
        if len(plan["members"]) != want:
            return (f"0/0 origins can host a {shape} slice: gang has "
                    f"{len(plan['members'])} member(s), the shape needs "
                    f"{want}")
        if plan["dims"] is None:
            return (f"0/0 origins can host a {shape} slice: no node "
                    "carries kubernetes-tpu.io/topology-{x,y,z} labels")
        if res is None:
            return (f"0/0 origins can host a {shape} slice: no rotation "
                    f"of the shape fits the {shape_str(plan['dims'])} grid")
        sel = carve_mod.select_eviction(res)
        hint = (f"freeing the cheapest origin costs {int(sel[2])} "
                "eviction(s)" if sel is not None
                else "no origin can ever host it")
        return (f"0/{res.fits.size} origins can host a {shape} slice: "
                f"{int(res.free_grid.sum())} free cell(s) on the "
                f"{shape_str(res.dims)} torus are too fragmented; {hint}")

    def _slice_preempt_gang(self, gang: str, members: list,
                            preempt_on: bool) -> None:
        """Slice preemption: a blocked slice nominates the CHEAPEST
        CONTIGUOUS victim set — the finite-minimum origin of the carve's
        eviction plane — instead of asking the per-pod wave for N unrelated
        nodes. Victims are chosen per occupied cell with the full
        preemption machinery (PDBs, priorities, graceful victim ordering:
        sched/preemption.find_candidate restricted to that cell's node);
        free cells need no victims; any cell without a legal victim set
        abandons the whole wave — a half-freed slice helps nobody."""
        from kubernetes_tpu.topology import carve as carve_mod
        plan = self._carve_plans.pop(gang, None)
        nominations: Optional[dict] = None
        if (plan is not None and preempt_on
                and any(p.spec.priority > 0 for p, _a in members)
                and len(plan["members"]) == len(members)):
            sel = carve_mod.select_eviction(plan["res"])
            if sel is not None:
                node_idxs, cells, _cost = sel
                nodes = plan["nodes"]
                cell_members = plan["members"]  # cell order
                free_grid = plan["res"].free_grid
                bound_left = self.cache.bound_pods(include_assumed=True)
                victims: list = []
                ok = True
                for m, (ni, cell) in enumerate(zip(node_idxs, cells)):
                    if free_grid[cell]:
                        continue  # free cell: nothing to evict
                    found = preemption_mod.find_candidate(
                        [nodes[ni]], bound_left,
                        self._preempt_view(cell_members[m]),
                        pdbs=self.pdb_lister(),
                        dra=self.cache.dra_catalog)
                    if found is None:
                        ok = False
                        break
                    gone = {v.key for v in found.victims}
                    bound_left = [p for p in bound_left
                                  if p.key not in gone]
                    victims.extend(found.victims)
                if ok:
                    # ONE eviction for the whole contiguous set — evict
                    # nothing unless every cell cleared
                    lead = max((p for p, _a in members),
                               key=lambda p: p.spec.priority)
                    if self._evict_victims(lead, victims):
                        with self._carve_lock:
                            self._carve_stats["slicePreempts"] += 1
                        nominations = {
                            cell_members[m].key:
                                nodes[ni].metadata.name
                            for m, ni in enumerate(node_idxs)}
        for pod, attempts in members:
            self._after_preempt(
                pod, attempts,
                None if nominations is None
                else nominations.get(pod.key))

    def topology_status(self) -> Optional[dict]:
        """Topology block for the status ConfigMap (``ktpu status`` renders
        it as the "Topology:" line): grid extent, per-requested-shape
        carveable-origin counts + fragmentation %, and carve counters.
        Host-side numpy over the cache's lists — a status surface, not the
        carve itself, so "free" here is the defrag notion (a schedulable
        node with ZERO bound pods). None when no node carries coordinates.
        """
        from kubernetes_tpu.topology import carve as carve_mod
        from kubernetes_tpu.topology.slicing import (coords_of_labels,
                                                     grid_dims, parse_shape,
                                                     shape_str)
        nodes = self.cache.list_nodes()
        coords = [coords_of_labels(n.metadata.labels) for n in nodes]
        dims = grid_dims([c for c in coords if c is not None])
        if dims is None:
            return None
        with self._carve_lock:
            shapes = sorted(self._carve_shapes_seen)
            stats = dict(self._carve_stats)
        per_node: dict[str, int] = {}
        for p in self.cache.bound_pods(include_assumed=True):
            if p.spec.node_name:
                per_node[p.spec.node_name] = (
                    per_node.get(p.spec.node_name, 0) + 1)
        free, evictable, n_pods = [], [], []
        for n in nodes:
            b = per_node.get(n.metadata.name, 0)
            sched = not n.spec.unschedulable
            free.append(sched and b == 0)
            evictable.append(sched)
            n_pods.append(b)
        out_shapes: dict[str, dict] = {}
        for s in shapes:
            res = carve_mod.numpy_grids(coords, free, evictable, n_pods,
                                        dims, parse_shape(s))
            out_shapes[s] = carve_mod.coverage_stats(res)
        return {"grid": shape_str(dims),
                "nodes": sum(1 for c in coords if c is not None),
                "freeCells": int(sum(free)),
                "shapes": out_shapes,
                "carves": stats}

    def _schedule_group(self, profile, items, slot_headroom: int = 0) -> int:
        from kubernetes_tpu.utils.tracing import TRACER
        t0 = time.time()
        pods = [p for p, _ in items]
        with TRACER.span("scheduler/snapshot", pods=len(pods)):
            nodes, ct, meta = self.cache.snapshot(pending_pods=pods,
                                                  slot_headroom=slot_headroom)
        if not nodes:
            for pod, attempts in items:
                self.queue.add_unschedulable(pod, attempts + 1)
                SCHEDULE_ATTEMPTS.inc({"result": "unschedulable"})
            return 0
        batch_keys = {p.key for p in pods}
        now = time.time()
        self._nominated = {
            k: e for k, e in self._nominated.items()
            if now - e[3] < self._nominated_ttl and not self.cache.is_bound(k)}
        entries = [(n, prio, p) for k, (n, prio, p, _ts)
                   in self._nominated.items() if k not in batch_keys]
        # nominations the snapshot is about to reserve resource-accurately
        # (overlay below); only arrivals AFTER this point need the coarse
        # assume-time re-check
        overlaid_noms = set(self._nominated)
        if entries:
            # nominees OUTSIDE this batch hold their reservation tensor-side;
            # nominees inside it are protected by the gang rank order instead
            # pin the reservation bucket: nominee counts vary per cycle
            # and every new M is a fresh gang compile mid-storm
            ct = self.cache.overlay_nominated(ct, meta, entries,
                                              min_m=DRAIN_NOM_BUCKET)
        with TRACER.span("scheduler/encode_pods", pods=len(pods)):
            # placement-time view: the profile's addedAffinity folds into
            # the encoded terms; assume/bind/requeue keep the ORIGINAL pod.
            # min_p pins the batch bucket to ONE compiled width: failure
            # re-pops arrive in ragged sizes (1..batch) and per-size
            # buckets each recompile the gang program
            pb = self.cache.encode_pods(
                profile.apply_added_affinity(pods), meta,
                min_p=self.cfg.batch_size,
                cache_rows=not profile.added_affinity)
        ext_mask = ext_scores = None
        ext_errors: set = set()
        if self._extenders:
            import numpy as np
            from kubernetes_tpu.sched.extender import run_extenders
            with TRACER.span("scheduler/extenders", pods=len(pods)):
                m, s, ext_errors = run_extenders(self._extenders, pods, nodes)
            Pb, Nb = pb.pod_valid.shape[0], ct.node_valid.shape[0]
            if m is not None:  # pad to bucket dims; padding is neutral
                ext_mask = np.ones((Pb, Nb), bool)
                ext_mask[:m.shape[0], :m.shape[1]] = m
            if s is not None:
                ext_scores = np.zeros((Pb, Nb), np.float32)
                ext_scores[:s.shape[0], :s.shape[1]] = s
            if ext_errors:
                # extender transport failure = attempt ERROR: exclude from
                # the gang batch and requeue with backoff — never feed it to
                # preemption as if the cluster had no room
                valid = np.asarray(pb.pod_valid).copy()
                for i in ext_errors:
                    valid[i] = False
                pb = pb.replace(pod_valid=valid)
        gang_of: dict[int, str] = {}
        gang_nodes: dict[str, dict[int, int]] = {}
        if any(self._slice_shape_of(p) is not None for p in pods):
            with TRACER.span("scheduler/carve", pods=len(pods)):
                ext_mask, gang_of, gang_nodes = self._carve_slices(
                    items, nodes, ct, meta, pb, ext_mask)
            if gang_nodes and self.sentinel is not None and not entries:
                # parity sampling only when the snapshot had no nominee
                # overlay (the host replay can't see overlay reservations)
                self.sentinel.maybe_submit_carve(
                    nodes, self.cache.bound_pods(include_assumed=True),
                    {g: {pods[i].key: meta.node_names[ni]
                         for i, ni in picks.items()}
                     for g, picks in gang_nodes.items()},
                    [pods[i] for i in sorted(gang_of)],
                    dra=self.cache.dra_catalog,
                    level=self._attempt_level)
        serial = not self.features.enabled("TPUBatchScheduling")
        oot = (None if profile.out_of_tree is None
               else set(profile.out_of_tree))
        plugins = self.registry.tensor_plugins(oot)
        with BATCH_DURATION.time(), TRACER.span(
                "scheduler/gang_schedule", pods=len(pods),
                nodes=len(nodes)) as sp_gang:
            try:
                assignment, rounds = gang_schedule(
                    ct, pb, seed=self.cfg.seed,
                    fit_strategy=profile.fit_strategy,
                    topo_keys=meta.topo_keys, serial=serial,
                    max_rounds=self.cfg.max_gang_rounds,
                    weights=profile.weights(),
                    enabled_filters=profile.enabled_filters,
                    ext_mask=ext_mask, ext_scores=ext_scores,
                    plugins=plugins, mesh=self._mesh)
            except Exception:
                # device program failed (compile/runtime/transport): feed
                # the breaker and schedule THIS batch with the pure-numpy
                # oracle — degraded, never dropped
                LOOP_ERRORS.inc({"site": "device_gang"})
                _LOG.warning("gang program failed at level %r; scheduling "
                             "the batch with the host oracle",
                             self._attempt_level, exc_info=True)
                self.breaker.fail(self._attempt_level)
                return self._schedule_oracle(profile, items)
        self.breaker.succeed(self._attempt_level)
        GANG_ROUNDS.observe(rounds)
        if sanity.check_enabled():
            for problem in sanity.check_assignment(assignment, len(nodes)):
                _LOG.error("KTPU_CHECK: %s (batch of %d)", problem, len(pods))

        # Nominations that arrived while this cycle's snapshot was in
        # flight (the descheduler writes status.nominatedNodeName right
        # before evicting): the snapshot could not reserve them, so winners
        # re-check against them before the assume. ONLY the mid-cycle
        # arrivals — nominations the snapshot already overlaid were
        # reserved resource-accurately, and a node-level deny for those
        # would lock out pods that provably fit beside the nominee.
        # Losing a node to a fresh reservation costs one backoff; binding
        # over it costs the reservation its meaning.
        self._fold_staged_nominations()
        reserved: dict[str, int] = {}
        for k, (n, prio, _p, _ts) in self._nominated.items():
            if k not in batch_keys and k not in overlaid_noms:
                reserved[n] = max(prio, reserved.get(n, prio))

        # slice gangs bind all-or-nothing: the carve pinned each member to
        # its cell, so ANY member the program (or the reservation shield
        # below) refuses fails the WHOLE gang this cycle — no partial
        # assume ever reaches the cache
        gang_ok: dict[str, bool] = {}
        for i, g in gang_of.items():
            pod = items[i][0]
            a = int(assignment[i]) if i < len(items) else -1
            ok = a >= 0 and gang_nodes.get(g, {}).get(i) == a
            if ok:
                rp = reserved.get(meta.node_names[a])
                ok = rp is None or rp < pod.spec.priority
            gang_ok[g] = gang_ok.get(g, True) and ok

        n_bound = n_err = n_unsched = 0
        to_bind: list[tuple[Pod, str]] = []
        failures: list[tuple[Pod, int]] = []
        dt = time.time() - t0
        for i, ((pod, attempts), a) in enumerate(
                zip(items, assignment[:len(items)])):
            if i in ext_errors:
                self.queue.add_unschedulable(pod, attempts + 1)
                n_err += 1
                continue
            g = gang_of.get(i)
            if g is not None and not gang_ok.get(g, False):
                failures.append((pod, attempts))
                n_unsched += 1
                continue
            if a >= 0:
                node_name = meta.node_names[int(a)]
                rp = reserved.get(node_name)
                # >=: equal-priority nominees shield too, matching the
                # device-side fit_mask (prio_s >= pb.priority) and upstream's
                # RunFilterPluginsWithNominatedPods — default-priority gangs
                # (0) must still beat their victims' replacements (also 0)
                if rp is not None and rp >= pod.spec.priority:
                    failures.append((pod, attempts))
                    n_unsched += 1
                    continue
                self._nominated.pop(pod.key, None)
                self.cache.assume(pod, node_name)
                to_bind.append((pod, node_name))
                n_bound += 1
            else:
                failures.append((pod, attempts))
                n_unsched += 1
        if FLIGHT.enabled:
            for pod, _a in items:
                FLIGHT.record(pod.key, "dispatch", span=sp_gang)
            for pod, _n in to_bind:
                FLIGHT.record(pod.key, "resolve", span=sp_gang)
        self._handle_failures(failures)
        self._bind_async_batch(to_bind, profile)
        # every pod in the batch shares one cycle's wall time; record the
        # whole batch with batched lock acquisitions instead of 2 per pod
        for result, n in (("scheduled", n_bound), ("error", n_err),
                          ("unschedulable", n_unsched)):
            if n:
                SCHEDULE_ATTEMPTS.inc({"result": result}, by=n)
                ATTEMPT_DURATION.observe(dt, {"result": result}, n=n)
        return n_bound

    def _schedule_drain(self, profile, items, slot_headroom: int = 0) -> int:
        """Deep-backlog path: fuse the whole pop into ONE device program over
        a DEVICE-RESIDENT cluster encoding.

        Per-batch dispatches cost ~100ms each on remote-attached TPUs and
        re-uploading the multi-MB cluster encoding per drain dominated the
        connected path, so the steady state here is: cluster tensors live in
        HBM (``_drain_ctx``), each drain ships only the new pod batches,
        and ``drain_step`` folds what it commits into free existing-pod
        slots on device (models/gang.py). Foreign changes — node churn, pod
        deletes, rebinds, preemption nominees — are replayed from the
        cache's delta log as DEVICE-SIDE PATCHES (encode/patch.py +
        apply_ctx_patch) before the next dispatch; the context rebuilds
        from a host snapshot only when a delta doesn't fit the resident
        buckets (new resource kind / topology key, bucket overflow,
        port/volume-owning pods)."""
        import numpy as np
        import jax
        from kubernetes_tpu.models.gang import (
            apply_ctx_patch, batch_shapes, build_drain_context, drain_step,
            drain_widths_fit, pad_batch_to, unify_batches)
        from kubernetes_tpu.utils.tracing import TRACER
        t0 = time.time()
        self._cyc_marks = []  # fresh debug trail per cycle (KTPU_CYCLE_LOG)
        pods = [p for p, _ in items]
        batch_keys = {p.key for p in pods}
        now = time.time()
        self._nominated = {
            k: e for k, e in self._nominated.items()
            if now - e[3] < self._nominated_ttl and not self.cache.is_bound(k)}
        # desired resident reservation set: nominees NOT in this pop (a
        # nominee scheduling itself must not be blocked by its own hold)
        nom_target = {k: (n, prio, p) for k, (n, prio, p, _ts)
                      in self._nominated.items() if k not in batch_keys}

        ctx = self._drain_ctx
        use_ctx = False
        fused_patch = None  # churn deltas riding THIS dispatch (fused fold)
        n_prev = 0
        if (ctx is not None
                and ctx.get("mesh_epoch") != self._mesh_epoch):
            # mesh reshape since this context was staged: its arrays carry
            # the OLD layout, and a patch compiled against them would apply
            # shard-inconsistently. Epoch mismatch always rebuilds.
            self._ctx_reason("mesh_reshape")
            n_prev += self._resolve_pending()
            self._drain_ctx = ctx = None
        if ctx is not None and ctx["profile"] == profile.scheduler_name:
            cs = ctx["cs"]
            known = set(ctx["meta"].resources)
            fits = (not cs.tainted
                    and ctx["fill_bound"] + len(pods) <= cs.top
                    and not any(r not in known for p in pods
                                for r in p.resource_requests()))
            if not fits:
                self._ctx_reason("tainted" if cs.tainted else "capacity")
            else:
                entries = self.cache.deltas_since(ctx["seq"])
                nom_dirty = (set(nom_target) != set(cs.nom_applied)
                             or any(cs.nom_applied[k][1:] != (n, prio)
                                    for k, (n, prio, _p)
                                    in nom_target.items()
                                    if k in cs.nom_applied))
                from kubernetes_tpu.encode.patch import entries_all_folded
                if entries is None:
                    self._ctx_reason("log_window")
                elif not nom_dirty and entries_all_folded(cs, entries):
                    # Every entry is an assume of a placement this context
                    # already folded device-side (our own resolves): advance
                    # the cursor and dispatch WITHOUT draining the pipeline.
                    # This is the steady-state gate of the multi-deep
                    # pipeline — the old code compiled a no-op patch here,
                    # which forced resolve-before-dispatch every cycle and
                    # quietly serialized the "async" drain loop.
                    if entries:
                        ctx["seq"] = entries[-1][0] + 1
                    use_ctx = True
                else:
                    # Foreign churn / nominee change. Fused-fold mode
                    # compiles the patch against the LIVE patch state and
                    # ships it as the drain dispatch's third input — the
                    # pipeline drains first only when a delta actually
                    # depends on an in-flight drain's unmirrored folds
                    # (encode/patch.py entries_fold_safe: a pod an
                    # in-flight drain is scheduling, or a node delete
                    # whose retire accounting can't see in-flight folds).
                    # Legacy mode (fusedFold off) resolves everything and
                    # dispatches a separate apply_ctx_patch, as before.
                    from kubernetes_tpu.encode.patch import entries_fold_safe
                    if self._pending and not (
                            self._fused_fold and entries_fold_safe(
                                cs, entries,
                                {p.key for pend in self._pending
                                 for c in pend["chunks"] for p, _ in c})):
                        if self.cycle_log is not None:
                            self._cyc_marks.append(("resolve_prev_start",
                                                    round(time.time() - t0,
                                                          3)))
                        n_prev += self._resolve_pending()
                        if self.cycle_log is not None:
                            self._cyc_marks.append(
                                ("resolve_prev_end",
                                 round(time.time() - t0, 3)))
                        entries = self.cache.deltas_since(ctx["seq"])
                    if entries is not None:
                        new_seq = (entries[-1][0] + 1 if entries
                                   else ctx["seq"])
                        # host-side half of the on-device fold: delta log ->
                        # static-shape scatter arrays. fold_floor pins the
                        # patch allocator above the DISPATCH-side fill
                        # reservation so a patch compiled with drains still
                        # in flight can never hand out a slot an unresolved
                        # fold will take.
                        with TRACER.span("scheduler/fold_deltas",
                                         deltas=len(entries)):
                            patch = self.cache.compile_ctx_patch(
                                ctx["meta"], cs, entries, nom_target,
                                DRAIN_NOM_BUCKET,
                                fold_floor=ctx["fill_bound"])
                        # the patch may have moved the slot cursor: the
                        # fold region this dispatch will write must still
                        # clear every patched slot (re-check AFTER compile;
                        # on failure the context — and the mutated patch
                        # state with it — is discarded and rebuilt)
                        if (patch is not None
                                and ctx["fill_bound"] + len(pods)
                                <= cs.top):
                            shadow = ctx.get("shadow")
                            if shadow is not None:
                                # mirror the requested/allocatable writes
                                # host-side BEFORE the host arrays are
                                # staged away: the preemption wave then
                                # reads totals without a device round-trip.
                                # Pending winner folds flush FIRST — on
                                # device they happened before this patch,
                                # and a reset row must zero them too
                                # (ResidentShadow.apply_patch contract).
                                shadow.catch_up(
                                    lambda p: self.cache.request_vector(
                                        p, cs.resources))
                                shadow.apply_patch(patch)
                            if self._fused_fold:
                                # the scatter rides THIS dispatch as
                                # drain_step's third input — zero separate
                                # device round trips for churn
                                fused_patch = patch
                                self.ctx_stats["folds"] += 1
                            else:
                                with TRACER.span("scheduler/ctx_patch_apply"), \
                                        self._mesh_scope():
                                    # sharded context: the scatter program
                                    # runs under the mesh — the tiny patch
                                    # arrays ship via one explicit
                                    # replicated put, the donated sharded
                                    # buffers keep their layout
                                    # (epoch-checked above, out-shardings
                                    # pinned inside the program)
                                    ctx["ct"] = apply_ctx_patch(
                                        ctx["ct"],
                                        self.cache.stage_patch(patch),
                                        mesh=self._mesh)
                                self.ctx_stats["patches"] += 1
                            ctx["seq"] = new_seq
                            use_ctx = True
                        elif patch is None:
                            self.ctx_stats["unfit"] += 1
                            self._ctx_reason("patch_unfit")
                        else:
                            self._ctx_reason("capacity")
        if use_ctx:
            nodes, meta = ctx["nodes"], ctx["meta"]
        else:
            # the in-flight drain's placements must land in the cache before
            # a host snapshot, or the re-encode double-books their capacity
            n_prev += self._resolve_pending()
            self._drain_ctx = None
            with TRACER.span("scheduler/snapshot", pods=len(pods)):
                nodes, ct, meta = self.cache.snapshot(
                    pending_pods=pods, slot_headroom=slot_headroom)
            seq0 = self.cache.last_snapshot_seq()
            if not nodes:
                for pod, attempts in items:
                    self.queue.add_unschedulable(pod, attempts + 1)
                    SCHEDULE_ATTEMPTS.inc({"result": "unschedulable"})
                return n_prev

        P = self.cfg.batch_size
        if self.cycle_log is not None:
            self._cyc_marks.append(("encode_start",
                                    round(time.time() - t0, 3)))
        chunks = self._tenant_chunks(items, P)
        with TRACER.span("scheduler/encode_pods", pods=len(pods)) as sp_enc:
            pbs = [self.cache.encode_pods(
                profile.apply_added_affinity([p for p, _ in c]),
                meta, min_p=P,
                cache_rows=not profile.added_affinity) for c in chunks]
        if FLIGHT.enabled:
            for pod, _a in items:
                FLIGHT.record(pod.key, "drain_fill", span=sp_enc)
        # pad to the fixed drain width with all-invalid batches (their pods
        # propose nothing; the scan converges them in one dead round)
        B = max(1, self.cfg.max_drain_batches)
        while len(pbs) < B:
            pad = pbs[-1]
            pbs.append(pad.replace(
                pod_valid=np.zeros_like(np.asarray(pad.pod_valid))))
        pb_stack = jax.tree_util.tree_map(
            lambda *xs: np.stack(xs), *unify_batches(pbs))

        if not use_ctx:
            from kubernetes_tpu.encode.patch import fork_meta
            built = build_drain_context(ct, pbs,
                                        nom_bucket=DRAIN_NOM_BUCKET,
                                        mesh=self._mesh)
            cs = self.cache.patch_state_fork()
            if built is None or cs is None:
                # base slots not packed (host patches left holes): run the
                # host per-batch path this cycle
                self._drain_ctx = None
                return n_prev + sum(
                    self._schedule_group(profile, c, slot_headroom)
                    for c in chunks)
            ct_dev, e0, fill = built
            from kubernetes_tpu.encode.patch import sync_resident_widths
            from kubernetes_tpu.sched.staging import ResidentShadow
            sync_resident_widths(cs, ct_dev)
            self.ctx_stats["rebuilds"] += 1
            ctx = {"ct": ct_dev, "e0": e0,
                   "fill_dev": self._stage_fill(fill),
                   "fill_bound": fill, "meta": fork_meta(meta),
                   "nodes": nodes, "cs": cs, "seq": seq0,
                   "pb_shape": batch_shapes(pb_stack),
                   "profile": profile.scheduler_name,
                   # host mirror of the resident [N,R] totals, cut from
                   # the SAME host encoding the context staged — the
                   # preemption wave reads it instead of a device_get
                   "shadow": ResidentShadow(ct.allocatable, ct.requested),
                   "mesh_epoch": self._mesh_epoch}
            meta = ctx["meta"]
            if nom_target:
                patch = self.cache.compile_ctx_patch(
                    meta, cs, [], nom_target, DRAIN_NOM_BUCKET)
                if patch is None:
                    # reservation set exceeds the resident bucket: keep
                    # semantics via the per-batch overlay path this cycle
                    return n_prev + sum(
                        self._schedule_group(profile, c, slot_headroom)
                        for c in chunks)
                ctx["shadow"].apply_patch(patch)
                with self._mesh_scope():
                    ctx["ct"] = apply_ctx_patch(
                        ctx["ct"], self.cache.stage_patch(patch),
                        mesh=self._mesh)
            self._drain_ctx = ctx
        else:
            # pin the batch to the context's compiled shapes: pop-dependent
            # bucket widths would otherwise recompile the drain mid-stream
            padded = pad_batch_to(pb_stack, ctx["pb_shape"])
            if padded is None or not drain_widths_fit(ctx["ct"], padded):
                # wider than anything compiled so far: rebuild the context
                self._ctx_reason("batch_shape")
                n_prev += self._resolve_pending()
                self._drain_ctx = None
                return n_prev + self._schedule_drain(profile, items,
                                                     slot_headroom)
            pb_stack = padded

        # hand the FINAL stacked batch to the staging arena now: the
        # background stager uploads it pre-sharded while this thread
        # finishes the cycle's remaining host work and the previous drain
        # still executes — the dispatch below then swaps buffers
        stage_ticket = self.cache.stage_submit(pb_stack)
        oot = (None if profile.out_of_tree is None
               else set(profile.out_of_tree))
        plugins = self.registry.tensor_plugins(oot)
        # parity sentinel: on sampled dispatches capture the host views the
        # resident encoding mirrors (consistent here — the ctx's log cursor
        # was settled on this thread moments ago; anything newer is carried
        # as the exempt set). Winners of still-in-flight drains resolve
        # before this one, so their placements are collected at resolve.
        parity_cap = None
        if self.sentinel is not None and not self._extenders:
            parity_cap = self.sentinel.maybe_capture_drain(
                self.cache, profile, self._attempt_level, ctx["seq"])
            if parity_cap is not None:
                parity_cap["prior"] = list(self._pending)
        # ---- dispatch (async): the device crunches this drain while the
        # host resolves the PREVIOUS one — assume/bind/requeue and the next
        # pop's decode all overlap device execution (software pipelining;
        # jax dispatch is asynchronous, only device_get blocks)
        if self.cycle_log is not None:
            self._cyc_marks.append(("dispatch_start",
                                    round(time.time() - t0, 3)))
        # staging is its OWN span (scheduler/stage_batch, with the arena
        # redeem nested as scheduler/stage_swap): MULTICHIP_r06's sharded
        # gang_dispatch growth (381ms -> 1641ms) was the per-dispatch
        # device_put hiding inside the dispatch span — the arena moves the
        # upload to the background stager, so steady state pays a swap
        pb_staged = self._stage_batch(pb_stack, stage_ticket, len(pods))
        if fused_patch is not None:
            # the churn scatter's ~KB arrays ship via one explicit
            # replicated put: the fused dispatch below then takes ONLY
            # device-resident inputs (the transfer-guard invariant)
            fused_patch = self.cache.stage_patch(fused_patch)
        with TRACER.span("scheduler/gang_dispatch",
                         pods=len(pods), nodes=len(nodes),
                         depth=len(self._pending) + 1) as sp_disp, \
                self._mesh_scope():
            # mesh on: the batch stack ships pre-sharded on "pods" (the
            # context's cluster arrays are already resident split on
            # "nodes"), and the winners view is pinned replicated so the
            # resolve fetch stays O(P). fused_patch (churn deltas) is the
            # third input of the resident program — the scatter applies
            # in front of the scan, inside this same dispatch.
            try:
                assignments, rounds, new_ct, new_fill = drain_step(
                    ctx["ct"], pb_staged,
                    ctx["fill_dev"], fused_patch, e0=ctx["e0"],
                    seed=self.cfg.seed, fit_strategy=profile.fit_strategy,
                    topo_keys=meta.topo_keys,
                    weights=tuple(sorted(profile.weights().items())),
                    enabled_filters=tuple(
                        sorted(profile.enabled_filters or ())),
                    max_rounds=self.cfg.max_gang_rounds, plugins=plugins,
                    winners_sharding=self._winners_sharding,
                    mesh=self._mesh)
            except Exception:
                # dispatch failed (compile error, dead tunnel, chaos):
                # the resident context's device state is unaccountable —
                # drop it, land whatever is still in flight, and schedule
                # this pop on the per-batch path (which itself degrades to
                # the oracle if the device stays broken)
                LOOP_ERRORS.inc({"site": "device_drain"})
                _LOG.warning("drain dispatch failed at level %r; falling "
                             "back to the per-batch path",
                             self._attempt_level, exc_info=True)
                self.breaker.fail(self._attempt_level)
                self._drain_ctx = None
                n_prev += self._resolve_pending()
                return n_prev + sum(
                    self._schedule_group(profile, c, slot_headroom)
                    for c in chunks)
        ctx["ct"] = new_ct
        ctx["fill_dev"] = new_fill
        ctx["fill_bound"] += len(pods)
        pend = {
            "assignments": assignments, "rounds": rounds,
            "chunks": chunks, "ctx": ctx,
            "meta": meta, "n_nodes": len(nodes), "profile": profile,
            "t0": t0,
            # breaker attribution: the level THIS drain was dispatched at
            # (resolve may happen cycles later, at a different level) and
            # the dispatch time on the BREAKER's clock (a stale success
            # must not mask newer failures)
            "level": self._attempt_level,
            "dispatched_at": self.breaker.clock.now(),
            # nominations the dispatched program already respects (resident
            # reservation slots); resolve re-checks winners only against
            # nominations that arrive AFTER this point
            "nom_keys": set(nom_target),
        }
        if parity_cap is not None:
            pend["parity"] = parity_cap
        if FLIGHT.enabled:
            for pod, _a in items:
                FLIGHT.record(pod.key, "dispatch", span=sp_disp)
        if self.cycle_log is not None:
            marks = dict(self._cyc_marks)
            marks["done"] = round(time.time() - t0, 3)
            pend["cyc"] = (len(pods), t0, marks)
        self._submit_resolve(pend)
        self._pending.append(pend)
        PIPELINE_DEPTH.observe(len(self._pending))
        PIPELINE_INFLIGHT.set(len(self._pending))
        # land whatever already finished, then enforce the depth bound: the
        # oldest drain resolves (blocking) only once MORE than
        # cfg.pipeline_depth drains are in flight — its assume/bind work
        # overlaps the younger drains' device execution (depth 1 reproduces
        # the old one-deep pipeline exactly)
        n_prev += self._resolve_ready()
        while len(self._pending) > max(1, self.cfg.pipeline_depth):
            n_prev += self._resolve_one()
        return n_prev

    def _ctx_reason(self, why: str):
        r = self.ctx_stats["reasons"]
        r[why] = r.get(why, 0) + 1

    def _resolve_pending(self) -> int:
        """Drain the WHOLE dispatch pipeline: block on every in-flight
        drain's results, oldest first, and apply them host-side. Returns
        pods bound. (Patch compiles and context rebuilds call this — their
        bookkeeping needs every fold recorded.)"""
        n = 0
        while self._pending:
            n += self._resolve_one()
        return n

    def _resolve_one(self) -> int:
        """Block on the OLDEST in-flight drain's results and apply them
        host-side: assume + bulk-bind the placements, requeue the failures,
        and record the device folds in the context's patch state (the fold
        packs committed pods into base slots [fill, fill+n) in flattened
        batch order — mirrored here so later churn patches can address
        them). Returns pods bound."""
        if not self._pending:
            return 0
        pend = self._pending.popleft()
        PIPELINE_INFLIGHT.set(len(self._pending))
        if self.cycle_log is not None and "cyc" in pend:
            n, tp, marks = pend["cyc"]
            marks["resolve_at"] = round(time.time() - tp, 3)
            self.cycle_log.append((n, round(tp, 3), marks))
        import jax
        import numpy as np
        from kubernetes_tpu.utils.tracing import TRACER
        t_wait = time.time()
        fetch_failed = False
        with BATCH_DURATION.time(), TRACER.span(
                "scheduler/resolve_wait",
                depth=len(self._pending) + 1) as sp_res:
            # fill_bound is maintained purely by the dispatch-side
            # reservation arithmetic (adjusted below); the device fill stays
            # resident as ctx["fill_dev"] and is never fetched
            done = pend.get("done")
            res = None
            if done is not None:
                # resolver thread owns the device fetch; this thread parks
                # on a plain Event — BOUNDED: a dead or stalled resolver
                # degrades to an inline fetch instead of hanging the loop
                deadline = time.time() + RESOLVE_WAIT_S
                while not done.wait(0.25):
                    t = self._resolver_thread  # ktpu-lint: disable=KTL001 -- lock-free liveness peek: a stale handle costs one redundant 0.25s wait round, never a wrong resolve
                    dead = t is not None and not t.is_alive()
                    if dead or time.time() > deadline:
                        LOOP_ERRORS.inc({"site": "resolver_wait"})
                        _LOG.warning(
                            "drain resolver %s; fetching inline",
                            "died" if dead
                            else f"silent for {RESOLVE_WAIT_S:.0f}s")
                        break
                res = pend.pop("resolved", None)
            if res is None:  # resolver off/stalled or its fetch failed
                try:
                    chaos_point("resolve")
                    res = jax.device_get(
                        (pend["assignments"], pend["rounds"]))
                except Exception:
                    fetch_failed = True
                    LOOP_ERRORS.inc({"site": "drain_resolve"})
                    _LOG.exception("drain results unrecoverable; "
                                   "requeueing the drain's pods")
            if not fetch_failed:
                assignments, rounds = res
        if fetch_failed:
            # the drain's winners are lost: requeue every pod (the cache
            # never assumed them), release the fold reservation, and taint
            # the resident context — the device-side fold state is unknown
            self.breaker.fail(pend.get("level", self._attempt_level))
            ctx = pend["ctx"]
            pend_count = sum(len(c) for c in pend["chunks"])
            if self._drain_ctx is ctx:
                ctx["cs"].tainted = True
                ctx["fill_bound"] -= pend_count
            for chunk in pend["chunks"]:
                for pod, attempts in chunk:
                    if not self.cache.is_bound(pod.key):
                        self.queue.add_unschedulable(pod, attempts + 1)
            SCHEDULE_ATTEMPTS.inc({"result": "error"}, by=pend_count)
            return 0
        # results landed: the device executed this drain end to end — the
        # breaker's success signal for the fused path (dispatch alone is
        # async and proves nothing). Attributed to the level and time the
        # drain was DISPATCHED at, not this cycle's.
        self.breaker.succeed(pend.get("level", self._attempt_level),
                             dispatched_at=pend.get("dispatched_at"))
        wait_ms = round((time.time() - t_wait) * 1000.0, 3)
        RESOLVE_BYTES.set(np.asarray(assignments).nbytes
                          + np.asarray(rounds).nbytes)
        # the drain is ONE SPMD program — every shard runs it lock-step, so
        # there is exactly one honest wall time (per-shard labels would
        # duplicate it N ways and leave stale series after a reshape);
        # stragglers surface in collective time, which this number includes
        DRAIN_SHARD_MS.set(wait_ms)
        ctx, meta, profile = pend["ctx"], pend["meta"], pend["profile"]
        active = self._drain_ctx is ctx
        pend_count = sum(len(c) for c in pend["chunks"])
        GANG_ROUNDS.observe(int(np.sum(rounds)))
        # nominations that arrived while this drain was on the device (the
        # descheduler writes them right before evicting): the dispatched
        # program could not reserve them, so winners re-check here — same
        # contract as _schedule_group's assume-time re-check
        self._fold_staged_nominations()
        fresh: dict[str, int] = {}
        if self._nominated:
            known = pend.get("nom_keys", set())
            drain_keys = {pod.key for chunk in pend["chunks"]
                          for pod, _ in chunk}
            for k, (n, prio, _p, _ts) in self._nominated.items():
                if k not in known and k not in drain_keys:
                    fresh[n] = max(prio, fresh.get(n, prio))
        lost_races = 0
        to_bind: list[tuple[Pod, str]] = []
        bound_rows: list[int] = []  # node index per to_bind entry
        failures: list[tuple[Pod, int]] = []
        with TRACER.span("scheduler/apply"):
            for b, chunk in enumerate(pend["chunks"]):
                assignment = assignments[b]
                if sanity.check_enabled():
                    for problem in sanity.check_assignment(
                            assignment, pend["n_nodes"]):
                        _LOG.error("KTPU_CHECK: %s (drain chunk %d)",
                                   problem, b)
                node_names = meta.node_names
                for (pod, attempts), a in zip(chunk,
                                              assignment[:len(chunk)]):
                    if a >= 0:
                        node_name = node_names[int(a)]
                        rp = fresh.get(node_name)
                        if rp is not None and rp >= pod.spec.priority:
                            failures.append((pod, attempts))
                            lost_races += 1
                            continue
                        to_bind.append((pod, node_name))
                        bound_rows.append(int(a))
                    else:
                        failures.append((pod, attempts))
            if lost_races and active:
                # the device fold already committed the rejected winners
                # into the resident encoding: it is now approximate —
                # rebuild at next dispatch (rare; only when a nomination
                # raced an in-flight drain)
                ctx["cs"].tainted = True
            if to_bind:
                # one lock pass for the whole drain's winners; failures are
                # handled AFTER so their preemption dry-runs see every winner
                self.cache.assume_many(to_bind)
                nominated = self._nominated
                if active:
                    # mirror the device fold: winners occupy base slots
                    # [fill_host, fill_host+n) in this exact order. slot_req
                    # stores the Pod itself — the request vector is computed
                    # lazily only if the pod is later deleted/rebound.
                    cs = ctx["cs"]
                    fill = cs.fill_host
                    for (pod, node), row in zip(to_bind, bound_rows):
                        cs.slot_of[pod.key] = fill
                        cs.slot_node[pod.key] = row
                        cs.slot_req[pod.key] = pod
                        cs.row_pods[row] = cs.row_pods.get(row, 0) + 1
                        cs.folded[pod.key] = node
                        fill += 1
                        if pod.spec.volumes or pod.host_ports():
                            # the fold cannot reproduce this pod's node-side
                            # port/volume state: the resident encoding is
                            # now approximate — rebuild at next dispatch
                            cs.tainted = True
                    cs.fill_host = fill
                    shadow = ctx.get("shadow")
                    if shadow is not None:
                        # record the winners' (pod, row) pairs; their
                        # request vectors fold into the host totals mirror
                        # lazily, only when a preemption wave reads them
                        shadow.fold_winners(
                            [(pod, row) for (pod, _n), row
                             in zip(to_bind, bound_rows)])
                for pod, _node in to_bind:
                    if nominated:
                        nominated.pop(pod.key, None)
        # every resolved drain records its winners: a later sampled drain's
        # parity check needs the placements of the drains that were in
        # flight when it dispatched (the device fold already counted them)
        pend["winners"] = list(to_bind)
        cap = pend.get("parity")
        if cap is not None and self.sentinel is not None:
            prior = [w for pp in cap.pop("prior", ())
                     for w in pp.get("winners", ())]
            self.sentinel.submit_drain(cap, list(to_bind), prior)
        n_bound = len(to_bind)
        n_unsched = len(failures)
        if FLIGHT.enabled:
            for pod, _n in to_bind:
                FLIGHT.record(pod.key, "resolve", span=sp_res)
            for pod, _a in failures:
                FLIGHT.record(pod.key, "resolve", span=sp_res)
        self._handle_failures(failures)
        # fill_bound is ADJUSTED, never overwritten: drains dispatched after
        # this one already reserved their own += len(pods) on top, so only
        # this drain's unused reservation (pend_count - n_bound) is released
        if active and self._drain_ctx is ctx:
            ctx["fill_bound"] -= (pend_count - n_bound)
        self._bind_async_batch(to_bind, profile)
        dt = time.time() - pend["t0"]
        for result, n in (("scheduled", n_bound),
                          ("unschedulable", n_unsched)):
            if n:
                SCHEDULE_ATTEMPTS.inc({"result": result}, by=n)
                ATTEMPT_DURATION.observe(dt, {"result": result}, n=n)
        return n_bound

    def warm_drain(self, sample_pods: list, slot_headroom: int) -> bool:
        """Pre-compile the fused drain and pre-stage the device-resident
        cluster context at the shapes a representative workload will use —
        a long-lived scheduler does this once per shape bucket; benchmarks
        call it so the measured window is steady-state (scheduler_perf
        excludes setup the same way). Returns True when the context is
        armed."""
        import jax
        import numpy as np
        from kubernetes_tpu.encode.patch import fork_meta
        from kubernetes_tpu.models.gang import (
            batch_shapes, build_drain_context, drain_step, unify_batches)
        if not sample_pods:
            return False
        profile = self.cfg.profile_for(sample_pods[0].spec.scheduler_name)
        if profile is None:
            return False
        B, P = max(1, self.cfg.max_drain_batches), self.cfg.batch_size
        nodes, ct, meta = self.cache.snapshot(
            pending_pods=sample_pods[:P], slot_headroom=slot_headroom)
        if not nodes:
            return False
        chunks = [sample_pods[i * P:(i + 1) * P] or sample_pods[:P]
                  for i in range(B)]
        pbs = [self.cache.encode_pods(profile.apply_added_affinity(c),
                                      meta, min_p=P,
                                      cache_rows=not profile.added_affinity)
               for c in chunks]
        pb_stack = jax.tree_util.tree_map(
            lambda *xs: np.stack(xs), *unify_batches(pbs))
        built = build_drain_context(ct, pbs, nom_bucket=DRAIN_NOM_BUCKET,
                                    mesh=self._mesh)
        if built is None:
            return False
        ct_dev, e0, fill = built
        oot = (None if profile.out_of_tree is None
               else set(profile.out_of_tree))
        plugins = self.registry.tensor_plugins(oot)
        # Compile + execute TWICE (throwaway results): the first call takes
        # the freshly-staged arrays, the second takes the first call's
        # returned (donated) buffers — whose XLA layouts can differ, which
        # would otherwise trigger a multi-second recompile on the first
        # steady-state drain. Then re-stage a clean context for real traffic.
        kw = dict(e0=e0, seed=self.cfg.seed,
                  fit_strategy=profile.fit_strategy,
                  topo_keys=meta.topo_keys,
                  weights=tuple(sorted(profile.weights().items())),
                  enabled_filters=tuple(sorted(profile.enabled_filters or ())),
                  max_rounds=self.cfg.max_gang_rounds, plugins=plugins,
                  winners_sharding=self._winners_sharding,
                  mesh=self._mesh)
        # the SAME staging path (and spans) the live dispatch uses — warms
        # the stager thread + pre-split layouts, and keeps this call site
        # inside the scheduler/stage_batch attribution
        pb_staged = self._stage_batch(
            pb_stack, self.cache.stage_submit(pb_stack), len(sample_pods))
        fill0_dev = self._stage_fill(fill)
        with self._mesh_scope():
            _, _, ct_dev2, fill2 = drain_step(ct_dev, pb_staged, fill0_dev,
                                              **kw)
            # second call matches the steady-state variant exactly: donated-
            # buffer layouts AND a device-resident fill scalar
            _, _, ct_dev3, fill3 = drain_step(ct_dev2, pb_staged, fill2, **kw)
            # rehearse the real churn alternation at the standard patch
            # write buckets so every steady-state program compiles here,
            # at each other's output layouts (a layout mismatch recompiles
            # drain_step for seconds inside the measured window). Fused
            # mode alternates drain(patch=None) with drain(patch=...);
            # the standalone apply_ctx_patch still stages rebuild-time
            # nominee reservations (and is THE churn program with
            # fusedFold off), so it warms in both modes.
            try:
                from kubernetes_tpu.models.gang import apply_ctx_patch
                cs_warm = self.cache.patch_state_fork()
                if cs_warm is not None:
                    warm_patch = self.cache.stage_patch(
                        self.cache.compile_ctx_patch(
                            fork_meta(meta), cs_warm, [], {},
                            DRAIN_NOM_BUCKET))
                    if warm_patch is not None and self._fused_fold:
                        _, _, ct_dev4, fill4 = drain_step(
                            ct_dev3, pb_staged, fill3, warm_patch, **kw)
                        # plain drain over the fused variant's output
                        # layout, then the standalone apply program
                        _, _, ct_dev5, _ = drain_step(ct_dev4, pb_staged,
                                                      fill4, **kw)
                        apply_ctx_patch(ct_dev5, warm_patch,
                                        mesh=self._mesh)
                    elif warm_patch is not None:
                        ct_dev4 = apply_ctx_patch(ct_dev3, warm_patch,
                                                  mesh=self._mesh)
                        drain_step(ct_dev4, pb_staged, fill3, **kw)
            except Exception:
                _LOG.exception("patch-program warmup failed (non-fatal)")
        built = build_drain_context(ct, pbs, nom_bucket=DRAIN_NOM_BUCKET,
                                    mesh=self._mesh)
        cs = self.cache.patch_state_fork()
        if built is None or cs is None:
            return False
        ct_dev, e0, fill = built
        from kubernetes_tpu.encode.patch import sync_resident_widths
        sync_resident_widths(cs, ct_dev)
        # the context upload streams asynchronously over the (remote) device
        # link; returning before it lands makes the FIRST real drain eat the
        # remaining transfer (~seconds at 10k-scale encodings) inside the
        # measured window
        jax.block_until_ready(ct_dev)
        from kubernetes_tpu.sched.staging import ResidentShadow
        self._drain_ctx = {"ct": ct_dev, "e0": e0,
                           "fill_dev": self._stage_fill(fill),
                           "fill_bound": fill,
                           "meta": fork_meta(meta), "nodes": nodes,
                           "cs": cs,
                           "seq": self.cache.last_snapshot_seq(),
                           "pb_shape": batch_shapes(pb_stack),
                           "profile": profile.scheduler_name,
                           "shadow": ResidentShadow(ct.allocatable,
                                                    ct.requested),
                           "mesh_epoch": self._mesh_epoch}
        return True

    # ---- degraded floor: pure-numpy oracle scheduling --------------------

    def _schedule_oracle(self, profile, items) -> int:
        """Degrade-don't-die floor: schedule a batch with the serial
        pure-numpy oracle (sched/oracle.py — the documented CPU fallback
        path). Orders of magnitude slower than the tensor programs, but
        device-free and exactly parity-tested against them — the breaker
        routes here when the device layer is broken so a scheduling cycle
        is never dropped."""
        import dataclasses
        from kubernetes_tpu.sched.oracle import OracleScheduler
        t0 = time.time()
        if self._extenders:
            # an extender's filter veto is authoritative (it guards state
            # the scheduler cannot see — storage capacity, license seats);
            # the oracle cannot consult it mid-outage, and binding past a
            # veto is worse than waiting one backoff for the device (or
            # the operator) to come back
            _LOG.warning("degraded to oracle but %d extender(s) are "
                         "configured: requeueing %d pods instead of "
                         "bypassing extender filters", len(self._extenders),
                         len(items))
            for pod, attempts in items:
                self.queue.add_unschedulable(pod, attempts + 1)
                SCHEDULE_ATTEMPTS.inc({"result": "unschedulable"})
            return 0
        nodes = self.cache.list_nodes()
        if not nodes:
            for pod, attempts in items:
                self.queue.add_unschedulable(pod, attempts + 1)
                SCHEDULE_ATTEMPTS.inc({"result": "unschedulable"})
            return 0
        orc = OracleScheduler(
            nodes, bound_pods=self.cache.bound_pods(include_assumed=True),
            weights=profile.weights(), seed=self.cfg.seed,
            volumes=self.cache.volume_catalog,
            namespace_labels=self.cache.namespace_labels(),
            dra=self.cache.dra_catalog)
        pods = profile.apply_added_affinity([p for p, _ in items])
        # the oracle's assume() writes node_name onto what it schedules:
        # give it detached views so a failed bind can requeue the ORIGINAL
        # pod unbound
        views = [dataclasses.replace(p, spec=dataclasses.replace(p.spec))
                 for p in pods]
        placed = orc.schedule_all(views)
        # same assume-time nomination re-check as the tensor paths: the
        # oracle's node states carried no reservation overlay. The prune
        # matters here too — in a long oracle window this is the ONLY
        # path running, and an unpruned stale nomination would reserve a
        # node for the whole outage.
        self._fold_staged_nominations()
        now = time.time()
        self._nominated = {
            k: e for k, e in self._nominated.items()
            if now - e[3] < self._nominated_ttl
            and not self.cache.is_bound(k)}
        batch_keys = {p.key for p, _ in items}
        reserved: dict[str, int] = {}
        for k, (n, prio, _p, _ts) in self._nominated.items():
            if k not in batch_keys:
                reserved[n] = max(prio, reserved.get(n, prio))
        n_bound = n_unsched = 0
        to_bind: list[tuple[Pod, str]] = []
        failures: list[tuple[Pod, int]] = []
        for (pod, attempts), ni in zip(items, placed):
            if ni is None:
                failures.append((pod, attempts))
                n_unsched += 1
                continue
            node_name = nodes[ni].metadata.name
            rp = reserved.get(node_name)
            if rp is not None and rp >= pod.spec.priority:
                failures.append((pod, attempts))
                n_unsched += 1
                continue
            self._nominated.pop(pod.key, None)
            self.cache.assume(pod, node_name)
            to_bind.append((pod, node_name))
            n_bound += 1
        if FLIGHT.enabled:
            for pod, _n in to_bind:
                FLIGHT.record(pod.key, "resolve", mode="oracle")
        self._handle_failures(failures)
        self._bind_async_batch(to_bind, profile)
        dt = time.time() - t0
        for result, n in (("scheduled", n_bound),
                          ("unschedulable", n_unsched)):
            if n:
                SCHEDULE_ATTEMPTS.inc({"result": result}, by=n)
                ATTEMPT_DURATION.observe(dt, {"result": result}, n=n)
        return n_bound

    # ---- failure path: PostFilter / preemption ---------------------------

    def _handle_failure(self, pod: Pod, attempts: int):
        self._handle_failures([(pod, attempts)])

    def _handle_failures(self, failures: list[tuple[Pod, int]]):
        """Failure path for a whole batch: preemption-eligible pods are
        resolved as ONE wave (sequential-commit device program,
        sched/preemption.py preempt_wave) instead of one full dry-run per
        pod — a preemption storm was 0.67s/pod of host re-encoding before.
        (Metrics for the unschedulable result are batched by the caller.)"""
        preemptable: list[tuple[Pod, int]] = []
        preempt_on = self.features.enabled("PreemptionSimulation")
        unschedulable: list[Pod] = []
        slice_gangs: dict[str, list[tuple[Pod, int]]] = {}
        for pod, attempts in failures:
            if self.cache.is_bound(pod.key):
                # Bound by another party while in-flight (its own bound copy
                # may even be why the gang step couldn't place it).
                # Requeueing would cycle it through backoffQ forever — no
                # future event clears it. No FailedScheduling event either:
                # the pod IS scheduled.
                continue
            unschedulable.append(pod)
            g = self._carve_gang_of(pod)
            if g is not None:
                # failed-carve slice members: the whole gang preempts as
                # one contiguous victim set (below), never as per-pod
                # wave entries chasing unrelated nodes
                slice_gangs.setdefault(g, []).append((pod, attempts))
            elif pod.spec.priority > 0 and preempt_on:
                preemptable.append((pod, attempts))
            else:
                self._after_preempt(pod, attempts, None)
        self._emit_failed_scheduling(unschedulable)
        for g, gang_members in sorted(slice_gangs.items()):
            self._slice_preempt_gang(g, gang_members, preempt_on)
        if not preemptable:
            return
        if self._custom_preemptor or len(preemptable) == 1:
            # injected preemptors keep the one-pod contract
            for pod, attempts in preemptable:
                self._after_preempt(pod, attempts, self.preemptor(pod))
        else:
            nominations = self._default_preempt_wave(
                [p for p, _ in preemptable])
            for (pod, attempts), node in zip(preemptable, nominations):
                self._after_preempt(pod, attempts, node)

    def _emit_failed_scheduling(self, pods: list[Pod]) -> None:
        """FailedScheduling events for one cycle's unschedulable pods. The
        explainer owns them when it accepts the capture (its verdict is the
        upstream-style per-filter message); the generic single-line event
        remains the fallback for pods it refused (backlog full, disabled)."""
        if not pods:
            return
        if self._carve_plans:
            # failed-carve slice members get the carve's own verdict — the
            # per-node explainer cannot say "the free nodes don't compose
            # into a 2x2x4 box"; the stashed score planes can
            remaining: list[Pod] = []
            for pod in pods:
                g = self._carve_gang_of(pod)
                if g is not None:
                    plan = self._carve_plans[g]
                    msg = self._slice_fail_message(plan)
                    self.recorder.event(
                        pod, "Warning", "FailedScheduling", msg)
                    if self.explainer is not None:
                        # carve verdict into the explanations ConfigMap so
                        # ktpu why shows it (event emission stays here)
                        self.explainer.submit_direct(
                            pod, msg,
                            {"SliceCarve": len(plan["nodes"])},
                            len(plan["nodes"]),
                            profile=pod.spec.scheduler_name)
                else:
                    remaining.append(pod)
            pods = remaining
            if not pods:
                return
        leftovers = pods
        if self.explainer is not None:
            by_prof: dict[str, list[Pod]] = {}
            for p in pods:
                by_prof.setdefault(p.spec.scheduler_name, []).append(p)
            leftovers = []
            for name, group in by_prof.items():
                if not self.explainer.submit(
                        self.cache, self.cfg.profile_for(name),
                        self._attempt_level, group):
                    leftovers.extend(group)
        for pod in leftovers:
            self.recorder.event(pod, "Warning", "FailedScheduling",
                                "no node satisfied the pod's scheduling "
                                "constraints this cycle")

    def _after_preempt(self, pod: Pod, attempts: int,
                       nominated: Optional[str]):
        if nominated:
            # Victims were evicted: retry immediately (no backoff) so the
            # freed capacity isn't stolen by lower-priority arrivals; until
            # the pod binds, the reservation also shields the capacity from
            # lower-priority pods in other batches (fit_mask nominated terms).
            pod.status.nominated_node_name = nominated
            self._nominated[pod.key] = (nominated, pod.spec.priority, pod,
                                        time.time())
            # this entry is in-memory, whatever the key's history: a stale
            # external flag left by an earlier API nomination of the same
            # key (pruned from _nominated without a fold running since)
            # would let an unrelated no-nomination MODIFIED tombstone clear
            # the preemption reservation
            self._nominated_external.discard(pod.key)
            self.queue.add(pod)
        else:
            self.queue.add_unschedulable(pod, attempts + 1)
            if self.cache.is_bound(pod.key):  # bound event raced the requeue
                self.queue.delete(pod)

    def _preempt_view(self, pod: Pod) -> Pod:
        """Feasibility view of the pod for preemption: the profile's
        addedAffinity applies there too (upstream preemption re-runs the
        NodeAffinity plugin, which carries the args)."""
        profile = self.cfg.profile_for(pod.spec.scheduler_name)
        if profile is None or not profile.added_affinity:
            return pod
        return profile.apply_added_affinity([pod])[0]

    def _default_preempt(self, pod: Pod) -> Optional[str]:
        nodes, _, _ = self.cache.snapshot()
        bound = self.cache.bound_pods(include_assumed=True)
        if self._attempt_level == "oracle":
            # device known-broken: go straight to the exact host scan
            # instead of paying a doomed device dry-run first
            res = preemption_mod.find_candidate(
                nodes, bound, self._preempt_view(pod),
                pdbs=self.pdb_lister(), dra=self.cache.dra_catalog)
        else:
            res = preemption_mod.find_candidate_tensor(
                nodes, bound, self._preempt_view(pod),
                pdbs=self.pdb_lister(), dra=self.cache.dra_catalog)
        if res is None:
            return None
        if not self._evict_victims(pod, res.victims):
            return None
        return res.node_name

    @staticmethod
    def _pod_tenant(pod: Pod):
        from kubernetes_tpu.encode.snapshot import tenant_label_of
        return tenant_label_of(pod.metadata.labels)

    def _evict_victims(self, preemptor: Pod, victims: list) -> bool:
        """Evict a preemption result's victims — REFUSING the whole result
        if any victim belongs to a foreign tenant. The tenant gate makes a
        cross-tenant candidate node unreachable, so this can only fire on
        scheduler-side corruption; when it does, evicting a sibling
        tenant's workload is strictly worse than failing this preemptor
        (the audit invariant + bench fail-fast catch the count)."""
        pt = self._pod_tenant(preemptor)
        foreign = [v for v in victims if self._pod_tenant(v) != pt]
        if foreign:
            LOOP_ERRORS.inc({"site": "cross_tenant_preempt"})
            _LOG.error(
                "REFUSING preemption for %s: victim(s) %s belong to a "
                "foreign tenant", preemptor.key,
                ", ".join(v.key for v in foreign))
            return False
        for v in victims:
            self._evict(v)
        return True

    def _preempt_serial(self, nodes, bound, views) -> list:
        """Serial host-scan preemption for a wave: each winner's victims
        leave the shared bound view before the next pick, mirroring the
        wave's sequential-commit semantics without the device."""
        results = []
        bound_left = list(bound)
        for v in views:
            res = preemption_mod.find_candidate(
                nodes, bound_left, v, pdbs=self.pdb_lister(),
                dra=self.cache.dra_catalog)
            results.append(res)
            if res is not None:
                gone = {x.key for x in res.victims}
                bound_left = [p for p in bound_left if p.key not in gone]
        return results

    def resident_plan_view(self) -> tuple[Optional[dict], str]:
        """(view, reason) for consumers of the DEVICE-RESIDENT drain
        context — the preemption wave and the three background planners
        (encode/overlay.ResidentPlanner). ``view`` is None when the
        resident encoding cannot stand in for a fresh snapshot, with
        ``reason`` naming why (decline accounting for ``ktpu status``
        and the PlannerLoop bench). Valid only when the context is
        accountable (untainted), staged under the CURRENT mesh epoch,
        and current with the cache — every unconsumed delta-log entry is
        an assume the context already folded. That is exactly the state
        at a drain resolve and between quiesced planner cycles: consumers
        then share the sharded resident cluster image (masks run on it in
        place, per-node totals read back from it or its host shadow,
        victim request vectors served from its fold ledger) instead of
        re-staging tensors the device already holds. Reads are GIL-atomic
        snapshots of the context fields, safe from the planner threads."""
        import numpy as np
        from kubernetes_tpu.encode.patch import entries_all_folded
        ctx = self._drain_ctx
        if ctx is None:
            return None, "no_ctx"
        if self._pending:
            # in-flight drains' winners are folded into the resident
            # requested[N,R] but not yet in the cache's bound view — the
            # consumers' semantics (judge against bound+assumed, like the
            # snapshot path) require the two to agree
            return None, "in_flight"
        cs = ctx["cs"]
        if cs.tainted:
            return None, "tainted"
        if ctx.get("mesh_epoch") != self._mesh_epoch:
            return None, "mesh_epoch"
        entries = self.cache.deltas_since(ctx["seq"])
        if entries is None or not entries_all_folded(cs, entries):
            return None, "stale_log"
        nodes = self.cache.list_nodes()
        meta = ctx["meta"]
        rows = []
        for n in nodes:
            ni = meta.node_index.get(n.metadata.name, -1)
            if ni < 0:
                return None, "missing_node"  # node the context has not absorbed
            rows.append(ni)
        return {"ct": ctx["ct"], "meta": meta, "cs": cs,
                "nodes": nodes, "rows": np.asarray(rows, np.int32),
                "shadow": ctx.get("shadow"), "mesh": self._mesh}, "ok"

    def _resident_wave_view(self) -> Optional[dict]:
        """The preemption wave's view of the resident drain context (see
        resident_plan_view) — the wave has no decline accounting."""
        view, _reason = self.resident_plan_view()
        return view

    def _resident_cluster_arrays(self, view: dict):
        """``fn(resources) -> (allocatable, requested) | None`` for
        dry_run_wave: the resident [N,R] totals, rows gathered into the
        live node-list order and columns remapped onto the wave's resource
        axis. Steady state serves them from the HOST SHADOW
        (sched/staging.py ResidentShadow — winner folds mirrored at
        resolve, churn patches applied from their host arrays), so the
        wave performs ZERO device round-trips for cluster totals; a
        poisoned or absent shadow falls back to one device_get of the
        resident arrays. Resources the resident encoding doesn't know
        stay 0 on both arrays — identical to the host encode, which
        scales ``alloc.get(r, 0)`` and can have no bound requests for a
        resource no bound pod carries (patches refuse unknown resource
        kinds)."""
        import jax
        import numpy as np

        def arrays(resources):
            cs = view["cs"]
            got = None
            shadow = view.get("shadow")
            if shadow is not None:
                shadow.catch_up(
                    lambda p: self.cache.request_vector(p, cs.resources))
                got = shadow.arrays()
            if got is None:
                try:
                    got = jax.device_get(
                        (view["ct"].allocatable, view["ct"].requested))
                except Exception:
                    _LOG.exception("resident totals readback failed; wave "
                                   "falls back to the host encode")
                    return None
            alloc_res, req_res = got
            rows = view["rows"]
            res_index = cs.res_index
            N, R = len(view["nodes"]), len(resources)
            allocatable = np.zeros((N, R), np.int64)
            requested = np.zeros((N, R), np.int64)
            for j, r in enumerate(resources):
                ri = res_index.get(r)
                if ri is not None:
                    allocatable[:, j] = alloc_res[rows, ri]
                    requested[:, j] = req_res[rows, ri]
            return allocatable, requested

        return arrays

    def _resident_req_lookup(self, view: dict):
        """``fn(pod, resources) -> [R] | None`` serving victim request
        vectors from the fold ledger's cached per-pod vectors (compiled at
        encode/patch time on the RESIDENT resource axis), remapped onto
        the wave's axis. Pods the ledger holds as raw Pod objects (device
        folds defer the vector) fall back to the wave's own computation —
        which is memoized on the Pod instance anyway."""
        import numpy as np
        slot_req = view["cs"].slot_req
        res_index = view["cs"].res_index

        def lookup(pod, resources):
            v = slot_req.get(pod.key)
            if not isinstance(v, np.ndarray):
                return None
            out = np.zeros(len(resources), np.int64)
            for j, r in enumerate(resources):
                ri = res_index.get(r)
                if ri is not None:
                    out[j] = int(v[ri])
            return out

        return lookup

    def _default_preempt_wave(self, pods: list[Pod]) -> list[Optional[str]]:
        """One sequential-commit wave program for a batch of preemptors
        (preempt_wave); victims are evicted per winner in wave order,
        mirroring Q serial _default_preempt calls. The wave is an extra
        stage of the resident scheduling program whenever the drain
        context is current (_resident_wave_view): static masks run on the
        device-resident sharded encoding in place, per-node totals read
        back from it, and victim vectors come from its fold ledger — no
        snapshot, no re-encode, no per-wave re-staging of cluster tensors.
        Only when the context is stale/tainted does the wave fall back to
        one cache snapshot (which itself reuses the cached encoding)."""
        from kubernetes_tpu.utils.tracing import TRACER
        resident = None
        if self._attempt_level != "oracle":
            # bound is captured BEFORE the staleness check: a foreign bind
            # racing this wave from the informer thread is then either in
            # BOTH the victim list and the delta log (the view declines) or
            # in NEITHER the list nor the resident totals — the two views
            # dry_run_wave reconciles can never disagree
            bound = self.cache.bound_pods(include_assumed=True)
            resident = self._resident_wave_view()
        if resident is not None:
            with TRACER.span("preempt/resident", pods=len(pods)):
                nodes = resident["nodes"]
                ct, meta = resident["ct"], resident["meta"]
        else:
            with TRACER.span("preempt/snapshot"):
                nodes, ct, meta = self.cache.snapshot()
                bound = self.cache.bound_pods(include_assumed=True)
        views = [self._preempt_view(p) for p in pods]
        if self._attempt_level == "oracle":
            # device known-broken this cycle: don't pay a doomed wave
            # dispatch (possibly a multi-second compile/tunnel timeout)
            # before falling back — go straight to the host scan
            with TRACER.span("preempt/serial", pods=len(pods)):
                results = self._preempt_serial(nodes, bound, views)
            out_serial: list[Optional[str]] = []
            with TRACER.span("preempt/evict"):
                for p, res in zip(pods, results):
                    if res is None or not self._evict_victims(p, res.victims):
                        out_serial.append(None)
                        continue
                    out_serial.append(res.node_name)
            return out_serial
        try:
            with TRACER.span("preempt/masks", pods=len(pods)):
                masks = preemption_mod.tensor_static_masks(
                    nodes, views, ct=ct, meta=meta,
                    encode_pods=self.cache.encode_pods,
                    min_p=preemption_mod.WAVE_BUCKET, mesh=self._mesh,
                    pre_staged=resident is not None,
                    node_rows=(resident["rows"] if resident is not None
                               else None))
        except Exception:
            _LOG.exception("static masks from resident encoding failed; "
                           "preempt_wave will re-encode")
            masks = None  # preempt_wave computes its own
        device_wave = True
        with TRACER.span("preempt/wave", pods=len(pods),
                         nodes=len(nodes)):
            try:
                results = preemption_mod.preempt_wave(
                    nodes, bound, views, pdbs=self.pdb_lister(),
                    dra=self.cache.dra_catalog, static_masks=masks,
                    min_q=preemption_mod.WAVE_BUCKET, mesh=self._mesh,
                    resident_arrays=(
                        self._resident_cluster_arrays(resident)
                        if resident is not None else None),
                    req_lookup=(self._resident_req_lookup(resident)
                                if resident is not None else None))
            except Exception:
                # device wave broke: feed the breaker and fall back to the
                # serial host scan (the wave's sequential-commit
                # semantics, minus the device)
                LOOP_ERRORS.inc({"site": "device_preempt"})
                _LOG.warning("preempt_wave device program failed; "
                             "degrading to the serial host scan",
                             exc_info=True)
                self.breaker.fail(self._attempt_level)
                device_wave = False
                results = self._preempt_serial(nodes, bound, views)
        if device_wave and self.sentinel is not None:
            # parity sample for the DEVICE wave only — the serial fallback
            # IS the oracle. Inputs are the exact host objects the wave's
            # masks were built from; judging runs off this thread.
            self.sentinel.maybe_submit_wave(
                nodes, bound, views, results, self._attempt_level,
                namespace_labels=self.cache.namespace_labels)
        out: list[Optional[str]] = []
        with TRACER.span("preempt/evict"):
            for p, res in zip(pods, results):
                if res is None or not self._evict_victims(p, res.victims):
                    out.append(None)
                    continue
                out.append(res.node_name)
        return out

    def _evict(self, victim: Pod):
        """Delete the victim via the binder-side client (overridden by the
        connected scheduler); cache removal happens via the watch event."""
        self.cache.remove_pod(victim.key)

    # ---- binding cycle (async, overlaps next batch) ----------------------

    def _bind_async_batch(self, pairs: list[tuple[Pod, str]], profile):
        """Dispatch a batch's bindings: pods needing per-pod ceremony
        (lifecycle hooks, extender binds, DRA claims, volume binding) go one
        POST each; the rest ride ONE bulk-binding call per chunk."""
        if not pairs:
            return
        oot = (None if profile is None or profile.out_of_tree is None
               else set(profile.out_of_tree))
        lifecycle = self.registry.lifecycle_plugins(oot)
        if (self._bulk_binder is None or lifecycle
                or self._extender_bind is not None):
            for pod, node_name in pairs:
                self._bind_async(pod, node_name)
            return
        simple: list[tuple[Pod, str]] = []
        for pod, node_name in pairs:
            if pod.spec.resource_claims or pod.pvc_names():
                self._bind_async(pod, node_name)
            else:
                simple.append((pod, node_name))
        # chunk bulk requests so one call never grows unbounded (request
        # size + per-item store work stay bounded; chunks also spread
        # across the worker pool)
        CHUNK = 2048
        for i in range(0, len(simple), CHUNK):
            chunk = simple[i:i + CHUNK]
            self._enqueue_bind(("bulk", chunk), n=len(chunk))

    def _bind_async(self, pod: Pod, node_name: str):
        self._enqueue_bind(("one", pod, node_name), n=1)

    def _enqueue_bind(self, item, n: int):
        with self._bind_cv:
            self._bind_inflight += n
            if (len(self._bind_workers) < max(1, self.cfg.bind_workers)
                    and len(self._bind_workers) < self._bind_inflight):
                t = threading.Thread(target=self._bind_worker, daemon=True,
                                     name=f"binder-{len(self._bind_workers)}")
                t.start()
                self._bind_workers.append(t)
        self._bind_q.put(item)

    def _bind_worker(self):
        while True:
            item = self._bind_q.get()
            if item is None:  # poison pill from close()
                return
            n = 1
            try:
                if item[0] == "bulk":
                    n = len(item[1])
                    self._bind_bulk(item[1])
                else:
                    self._bind_one(item[1], item[2])
            except Exception:
                LOOP_ERRORS.inc({"site": "bind_worker"})
                _LOG.exception("binding cycle failed")
            finally:
                with self._bind_cv:
                    self._bind_inflight -= n
                    if self._bind_inflight == 0:
                        self._bind_cv.notify_all()

    def _bind_bulk(self, pairs: list[tuple[Pod, str]]):
        """One API call binds the whole chunk; per-item results fan back out
        into the same success/failure handling as _bind_one."""
        try:
            results = self._bulk_binder(pairs)
        except Exception:
            _LOG.exception("bulk binding failed (%d pods)", len(pairs))
            results = [False] * len(pairs)
        if len(results) != len(pairs):
            results = list(results) + [False] * (len(pairs) - len(results))
        for (pod, node_name), ok in zip(pairs, results):
            if ok:
                self.cache.finish_binding(pod.key)
                FLIGHT.record(pod.key, "bind", node=node_name)
                self.recorder.event(
                    pod, "Normal", "Scheduled",
                    f"Successfully assigned {pod.key} to {node_name}")
            elif ok is None:
                # the pod vanished while its binding was in flight (e.g. a
                # churn delete): drop the assumption quietly — requeueing
                # would retry-404 forever with no future event to clear it,
                # and it is not a scheduling error either. The informer's
                # DELETED event owns the queue cleanup; deleting here by
                # ns/name could strand a just-RE-CREATED pod's queue entry.
                self.cache.forget(pod.key)
            else:
                self.cache.forget(pod.key)
                if not self.cache.is_bound(pod.key):
                    self.queue.add_unschedulable(pod, 1)
                    if self.cache.is_bound(pod.key):  # event raced the requeue
                        self.queue.delete(pod)
                SCHEDULE_ATTEMPTS.inc({"result": "error"})

    def close(self, timeout: float = 5.0):
        """Stop the binding pool: poison-pill every worker and join them.
        Idempotent; the runner's stop path calls this so embedders and long
        test suites don't accumulate daemon threads."""
        try:
            self._resolve_pending()  # land every in-flight drain's bindings
        except Exception:
            _LOG.exception("resolving in-flight drains at close")
        with self._resolver_swap_lock:  # vs a racing watchdog restart
            if self._resolver_q is not None:
                self._resolver_q.put(None)  # poison pill; thread is daemon
                self._resolver_thread = None
                self._resolver_q = None
        self.cache.close_staging()  # poison the batch-stager (daemon too)
        if self.sentinel is not None:
            self.sentinel.close()
        if self.explainer is not None:
            self.explainer.close()
        if self._staged:
            # parked fragments go back to the queue, not the void — with
            # their attempt history, so backoff does not reset
            for pod, attempts in self._staged:
                self.queue.add(pod, attempts=attempts)
            self._staged = []
        with self._bind_cv:
            workers = list(self._bind_workers)
            self._bind_workers = []
        for _ in workers:
            self._bind_q.put(None)
        for t in workers:
            t.join(timeout=timeout)

    def _bind_one(self, pod: Pod, node_name: str):
        from kubernetes_tpu.sched import framework as fw
        # lifecycle hooks honor the pod's profile opt-in like tensor plugins
        profile = self.cfg.profile_for(pod.spec.scheduler_name)
        oot = (None if profile is None or profile.out_of_tree is None
               else set(profile.out_of_tree))
        lifecycle = self.registry.lifecycle_plugins(oot)
        rollback: list = []
        try:
            # Permit -> PreBind -> Bind (framework extension-point order);
            # plugins that allowed/prepared join the unreserve rollback set
            ok, permitted = fw.run_permit(lifecycle, pod, node_name)
            rollback.extend(permitted)
            if ok:
                ok, prebound = fw.run_pre_bind(lifecycle, pod, node_name)
                rollback.extend(p for p in prebound if p not in rollback)
            if ok:
                delegated = None
                if self._extender_bind is not None:
                    # an interested extender with a bindVerb owns the binding
                    delegated = self._extender_bind(pod, node_name)
                ok = (self.binder(pod, node_name) if delegated is None
                      else delegated)
        except Exception:
            LOOP_ERRORS.inc({"site": "bind_lifecycle"})
            _LOG.exception("binding cycle for %s failed", pod.key)
            ok = False
        # a binder returning None means the pod no longer exists (deleted
        # while the binding was in flight — expected under churn): there is
        # nothing to requeue and nothing failed
        gone = ok is None
        if ok:
            fw.run_post_bind(lifecycle, pod, node_name)
            FLIGHT.record(pod.key, "bind", node=node_name)
            self.recorder.event(pod, "Normal", "Scheduled",
                                f"Successfully assigned {pod.key} to {node_name}")
        else:
            fw.run_unreserve(rollback, pod, node_name)
        if ok:
            self.cache.finish_binding(pod.key)
        elif gone:
            # deleted mid-flight: forget only — the informer's DELETED
            # event owns queue cleanup (a delete by ns/name here could
            # strand a just-re-created pod's queue entry)
            self.cache.forget(pod.key)
        else:
            self.cache.forget(pod.key)
            # 409 ordering: if another party bound this pod while it was
            # in-flight, the informer's MODIFIED(nodeName) event (and its
            # queue.delete) may have already fired — requeueing now would
            # retry-409 forever with no further event to clear it. Mirrors
            # the reference's handleSchedulingFailure assigned-pod check.
            if not self.cache.is_bound(pod.key):
                self.queue.add_unschedulable(pod, 1)
                if self.cache.is_bound(pod.key):  # event raced the requeue
                    self.queue.delete(pod)
            SCHEDULE_ATTEMPTS.inc({"result": "error"})

    def wait_for_bindings(self, timeout: float = 5.0):
        deadline = time.time() + timeout
        with self._bind_cv:
            while self._bind_inflight > 0:
                remaining = deadline - time.time()
                if remaining <= 0 or not self._bind_cv.wait(remaining):
                    break

    # ---- loop ------------------------------------------------------------

    def taint_ctx(self) -> None:
        """Mark the device-resident drain context unaccountable: the next
        dispatch rebuilds from a host snapshot instead of patching arrays
        whose true device state is unknown (mid-cycle failure, watchdog
        thread restart)."""
        ctx = self._drain_ctx
        if ctx is not None:
            ctx["cs"].tainted = True

    def audit_ctx_view(self) -> Optional[dict]:
        """Plain-value view of the resident drain context's host-side fold
        ledger for the invariant auditor (audit/invariants.py ctx_parity).
        Reads from a foreign thread: each field is one GIL-atomic read or
        dict copy off a local ctx reference — a concurrent dispatch can
        make the view momentarily inconsistent, which the auditor's
        confirm-across-sweeps engine absorbs."""
        ctx = self._drain_ctx
        if ctx is None:
            return None
        cs = ctx["cs"]
        return {"profile": ctx["profile"], "tainted": cs.tainted,
                "seq": ctx["seq"], "fill_bound": ctx["fill_bound"],
                "fill_host": cs.fill_host, "top": cs.top,
                "folded": dict(cs.folded),
                "mesh_epoch": ctx["mesh_epoch"],
                "pending": len(self._pending)}

    def run(self, stop: threading.Event):
        """wait.UntilWithContext(sched.ScheduleOne, 0) analog — hardened:
        a run_once failure is logged + counted (never swallowed, never
        fatal), the resident drain context is tainted (a mid-dispatch
        death leaves its device state unaccountable), and the loop backs
        off briefly and continues. Only a BaseException — watchdog food
        like ChaosThreadDeath, or interpreter shutdown — escapes."""
        consecutive = 0
        while not stop.is_set() and not self.queue.closed:
            self.heartbeat()
            try:
                chaos_point("loop")
                self.run_once()
                consecutive = 0
            except Exception:
                consecutive += 1
                LOOP_ERRORS.inc({"site": "run_once"})
                _LOG.exception("run_once failed (%d consecutive); "
                               "self-healing", consecutive)
                self.taint_ctx()
                stop.wait(min(0.05 * (2 ** min(consecutive, 6)), 2.0))
