"""Oracle scheduler — the serial, readable reference implementation.

Semantics mirror the reference's scheduling cycle
(``pkg/scheduler/schedule_one.go``: ``findNodesThatFitPod`` ->
``prioritizeNodes`` -> ``selectHost``) pod-by-pod over typed API objects. It
exists for three jobs:

1. Parity target: every tensor op in ops/ is tested against it.
2. CPU fallback path: clusters without a TPU run this scheduler.
3. Semantic documentation: this file is the plain-English statement of what
   the fused tensor program computes.

Resource arithmetic uses the SAME scaled integer units as the tensor path
(encode/scaling.py) and scores use float32, so parity is exact, not
approximate. Plugin weights default to the reference's
(pkg/scheduler/apis/config/v1/default_plugins.go).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from kubernetes_tpu.api.selectors import (
    label_selector_matches,
    node_fields,
    node_selector_matches,
)
from kubernetes_tpu.api.types import (
    EFFECT_NO_EXECUTE,
    EFFECT_NO_SCHEDULE,
    EFFECT_PREFER_NO_SCHEDULE,
    NODE_INCLUSION_HONOR,
    NODE_INCLUSION_IGNORE,
    Node,
    NodeSelectorTerm,
    Pod,
    Requirement,
    Taint,
    Toleration,
    TopologySpreadConstraint,
)
from kubernetes_tpu.encode.scaling import UNLIMITED, scale_allocatable, scale_request
from kubernetes_tpu.encode.snapshot import tenant_label_of
from kubernetes_tpu.encode.termprep import (
    affinity_term_selector,
    resolve_term_namespaces,
    spread_selector,
)

UNSCHED_TAINT = Taint(key="node.kubernetes.io/unschedulable", effect=EFFECT_NO_SCHEDULE)

# Reference default plugin score weights (default_plugins.go).
DEFAULT_WEIGHTS = {
    "NodeResourcesFit": 1.0,
    "NodeResourcesBalancedAllocation": 1.0,
    "ImageLocality": 1.0,
    "NodeAffinity": 2.0,
    "TaintToleration": 3.0,
    "PodTopologySpread": 2.0,
    "InterPodAffinity": 2.0,
}

# ImageLocality constants (image_locality.go): mb, minThreshold, maxContainerThreshold.
_MB = 1024 * 1024
IMG_MIN_THRESHOLD = 23 * _MB
IMG_MAX_CONTAINER_THRESHOLD = 1000 * _MB


def tie_break(n: int, seed: int, salt: int = 0) -> int:
    """Deterministic tie-break among max-score nodes: the reference reservoir-
    samples with math/rand (schedule_one.go selectHost); we use a seeded
    multiplicative hash so TPU and oracle agree bit-for-bit. ``salt`` is the
    pod's batch position (ops/scores.select_host uses the same mixing)."""
    s = ((seed + salt) * 2246822519) & 0xFFFFFFFF
    return (((n * 2654435761) & 0xFFFFFFFF) ^ s) & 0x3FFFFFFF


@dataclass
class NodeState:
    node: Node
    allocatable: dict[str, int] = field(default_factory=dict)  # scaled units
    requested: dict[str, int] = field(default_factory=dict)
    pods: list[Pod] = field(default_factory=list)

    @classmethod
    def build(cls, node: Node) -> "NodeState":
        alloc = {r: scale_allocatable(r, q) for r, q in node.allocatable_canonical().items()}
        alloc.setdefault("pods", UNLIMITED)
        return cls(node=node, allocatable=alloc)

    def add_pod(self, pod: Pod):
        self.pods.append(pod)
        for r, q in pod.resource_requests().items():
            self.requested[r] = self.requested.get(r, 0) + scale_request(r, q)

    def remove_pod(self, pod: Pod):
        self.pods = [p for p in self.pods if p.metadata.uid != pod.metadata.uid]
        for r, q in pod.resource_requests().items():
            self.requested[r] = self.requested.get(r, 0) - scale_request(r, q)

    @property
    def labels(self) -> dict[str, str]:
        return self.node.metadata.labels


def tolerates_all(tolerations: list[Toleration], taints: list[Taint],
                  effects: tuple[str, ...]) -> bool:
    for t in taints:
        if t.effect in effects and not any(tol.tolerates(t) for tol in tolerations):
            return False
    return True


class FailReason:
    TENANT = "node(s) belonged to a different tenant"
    UNSCHEDULABLE = "node(s) were unschedulable"
    NODE_NAME = "node(s) didn't match the requested node name"
    RESOURCES = "Insufficient resources"
    AFFINITY = "node(s) didn't match Pod's node affinity/selector"
    TAINT = "node(s) had untolerated taint"
    PORTS = "node(s) didn't have free ports"
    SPREAD = "node(s) didn't satisfy topology spread constraints"
    POD_AFFINITY = "node(s) didn't match pod affinity rules"
    POD_ANTI_AFFINITY = "node(s) didn't satisfy existing pods anti-affinity rules"
    VOLUME = "node(s) had volume node affinity conflict"
    CLAIM = "pod has missing/unresolved ResourceClaims"
    SLICE_UNAVAILABLE = ("node(s) were outside every carveable slice of "
                         "the requested shape")


class OracleScheduler:
    """Serial scheduler over NodeState list. Mutating: ``assume`` folds
    assignments in, mirroring Cache.AssumePod optimism."""

    def __init__(self, nodes: list[Node], bound_pods: Optional[list[Pod]] = None,
                 weights: Optional[dict[str, float]] = None, seed: int = 0,
                 volumes=None, namespace_labels: Optional[dict] = None,
                 dra=None):
        self.states = [NodeState.build(n) for n in nodes]
        self.node_index = {n.metadata.name: i for i, n in enumerate(nodes)}
        # tenant-local tie-break ranks (ops/filters.tenant_local_rank's
        # host twin): node i's rank among ITS TENANT's nodes — arange for
        # single-tenant clusters, so tie-breaks are unchanged there and
        # bit-equal to standalone runs under a fleet
        _tcounts: dict = {}
        self._node_rank: list[int] = []
        for n in nodes:
            t = self._tenant_of(n.metadata.labels)
            r = _tcounts.get(t, 0)
            _tcounts[t] = r + 1
            self._node_rank.append(r)
        self.weights = dict(weights or DEFAULT_WEIGHTS)
        self.seed = seed
        self.volumes = volumes  # VolumeCatalog | None
        self.dra = dra          # sched/dra.DraCatalog | None
        # namespace name -> labels, for namespaceSelector resolution
        # (GetNamespaceLabelsSnapshot analog)
        self.namespace_labels = dict(namespace_labels or {})
        if dra is not None:
            # device slices extend node allocatable as dra:<class> counts —
            # the same synthetic-resource folding the encoder does
            for st in self.states:
                for r, q in dra.node_capacity(st.node.metadata.name).items():
                    st.allocatable[r] = scale_allocatable(r, q)
        # Count of bound pods carrying REQUIRED anti-affinity: the symmetry
        # veto scan in _pod_ctx walks every bound pod on every call, which
        # dominated preemption verification at fleet scale — when no bound
        # pod has such a term (the overwhelmingly common case) the scan is
        # skipped outright. Maintained by every mutation path.
        self._n_anti = 0
        for p in bound_pods or []:
            i = self.node_index.get(p.spec.node_name)
            if i is not None:
                self.states[i].add_pod(p)
                self._fold_demands(self.states[i], p)
                self._n_anti += self._has_required_anti(p)
        from kubernetes_tpu.sched.volumebinding import cluster_volume_state
        self._vol_rwo, self._vol_attach, self._vol_rwop = cluster_volume_state(
            [p for st in self.states for p in st.pods], volumes)
        # topology slice carving (topology/): node coordinates + grid extent
        # for the oracle carver; the per-node SliceCarve explain gate is
        # OPT-IN (the explainer arms it) because preemption's per-node
        # re-filter frees a slice one cell at a time — a default-on gate
        # would veto its own repair
        from kubernetes_tpu.topology.slicing import coords_of_labels, grid_dims
        self._coords = [coords_of_labels(n.metadata.labels) for n in nodes]
        self._dims = grid_dims([c for c in self._coords if c is not None])
        self.slice_explain = False

    @staticmethod
    def _has_required_anti(p: Pod) -> bool:
        aff = p.spec.affinity
        return bool(aff and aff.pod_anti_affinity
                    and aff.pod_anti_affinity.required)

    def _fold_demands(self, st: NodeState, pod: Pod, sign: int = 1):
        """Fold a pod's DRA device demands into the node's requested map."""
        if self.dra is None:
            return
        for r, q in self.dra.pod_demands(pod).items():
            st.requested[r] = st.requested.get(r, 0) + sign * scale_request(r, q)

    def _eff_requests(self, pod: Pod) -> dict:
        reqs = dict(pod.resource_requests())
        if self.dra is not None:
            reqs.update(self.dra.pod_demands(pod))
        return reqs

    def _volume_ok(self, pod: Pod, node: Node, vinfo) -> bool:
        """VolumeBinding/Zone/Restrictions/Limits, serial reference form."""
        from kubernetes_tpu.api.selectors import node_fields, node_selector_matches
        from kubernetes_tpu.sched.volumebinding import node_attach_limit
        name = node.metadata.name
        for group in vinfo.groups:
            if not group:
                return False  # unsatisfiable PVC
            if not node_selector_matches(group, node.metadata.labels,
                                         node_fields(name)):
                return False
        in_use = set(self._vol_rwo.get(name, []))
        if any(pv in in_use for pv in vinfo.rwo_pv_names):
            return False
        limit = node_attach_limit(node.status.allocatable)
        if limit >= 0 and self._vol_attach.get(name, 0) + vinfo.attach_count > limit:
            return False
        return True

    # ---- filters ---------------------------------------------------------

    _tenant_of = staticmethod(tenant_label_of)

    def _filter_one(self, pod: Pod, st: NodeState, ni: int, ctx: dict) -> Optional[str]:
        node = st.node
        # fleet visibility gate, FIRST (mirrors run_filters' validity gate
        # and explain's stack order): a pod only ever sees its own
        # tenant's nodes; untenanted == untenanted passes, so
        # single-tenant clusters are unaffected
        if self._tenant_of(pod.metadata.labels) != self._tenant_of(st.labels):
            return FailReason.TENANT
        if node.spec.unschedulable and not any(
                t.tolerates(UNSCHED_TAINT) for t in pod.spec.tolerations):
            return FailReason.UNSCHEDULABLE
        if pod.spec.node_name and pod.spec.node_name != node.metadata.name:
            return FailReason.NODE_NAME
        sl = ctx.get("slice_ok")
        if sl is not None and not sl[ni]:
            return FailReason.SLICE_UNAVAILABLE
        if self.dra is not None and pod.spec.resource_claims:
            if not self.dra.pod_claims_ready(pod):
                return FailReason.CLAIM  # template-generated claim not yet made
            pin = self.dra.pod_allocated_node(pod)
            if not pod.spec.node_name and pin and pin != node.metadata.name:
                return FailReason.NODE_NAME  # allocated claim pins the pod
        for r, q in self._eff_requests(pod).items():
            need = scale_request(r, q)
            if need > st.allocatable.get(r, 0) - st.requested.get(r, 0):
                return FailReason.RESOURCES
        if not self._node_affinity_ok(pod, node):
            return FailReason.AFFINITY
        if not tolerates_all(pod.spec.tolerations, node.spec.taints,
                             (EFFECT_NO_SCHEDULE, EFFECT_NO_EXECUTE)):
            return FailReason.TAINT
        if self._ports_conflict(pod, st):
            return FailReason.PORTS
        if ctx.get("vol") is not None and not self._volume_ok(pod, node, ctx["vol"]):
            return FailReason.VOLUME
        if not self._spread_ok(st, ctx):
            return FailReason.SPREAD
        r = self._interpod_ok(st, ctx)
        if r is not None:
            return r
        return None

    def _pod_ctx(self, pod: Pod) -> dict:
        """Node-independent precomputation for one pod (the PreFilter analog):
        per-constraint domain counts, affinity pair counts + bootstrap flag,
        and the symmetry veto set. Computed ONCE per pod, not per node."""
        aff = pod.spec.affinity
        pa = aff.pod_affinity if aff else None
        pan = aff.pod_anti_affinity if aff else None
        ns = pod.metadata.namespace
        spread = []
        for sc in pod.spec.topology_spread_constraints:
            if sc.when_unsatisfiable != "DoNotSchedule":
                continue
            eff = spread_selector(sc, pod.metadata.labels)
            counts = self._domain_counts(pod, sc, eff)
            self_match = label_selector_matches(eff, pod.metadata.labels)
            min_count = min(counts.values()) if counts else 0
            # minDomains: fewer eligible domains than required -> the global
            # minimum is treated as 0 (filtering.go minMatchNum).
            if sc.min_domains is not None and len(counts) < sc.min_domains:
                min_count = 0
            spread.append((sc, counts, min_count, self_match))
        aff_counts = []
        self_matches_all = True
        for term in (pa.required if pa else []):
            prep = self._prep_term(term, ns, pod.metadata.labels)
            counts: dict[str, int] = {}
            for st in self.states:
                dv = st.labels.get(term.topology_key)
                if dv is None:
                    continue
                for p in st.pods:
                    if self._prepped_matches(prep, ns, p):
                        counts[dv] = counts.get(dv, 0) + 1
            if not self._prepped_matches(prep, ns, pod):
                self_matches_all = False
            aff_counts.append((term, counts))
        # filtering.go bootstrap: NO term has a matching pair anywhere AND the
        # incoming pod matches ALL its own terms (incl. their namespace sets).
        bootstrap = (bool(aff_counts)
                     and all(not c for _, c in aff_counts)
                     and self_matches_all)
        anti_counts = []
        for term in (pan.required if pan else []):
            prep = self._prep_term(term, ns, pod.metadata.labels)
            counts = {}
            for st in self.states:
                dv = st.labels.get(term.topology_key)
                if dv is None:
                    continue
                for p in st.pods:
                    if self._prepped_matches(prep, ns, p):
                        counts[dv] = counts.get(dv, 0) + 1
            anti_counts.append((term, counts))
        # Symmetry: (topology_key, domain value) pairs where some existing
        # pod's required anti-affinity matches this pod. The term resolves
        # against the EXISTING pod's namespace + labels (it owns the term).
        sym_veto: set[tuple[str, str]] = set()
        for other_st in (self.states if self._n_anti else ()):
            for p in other_st.pods:
                paff = p.spec.affinity
                pananti = paff.pod_anti_affinity if paff else None
                for term in (pananti.required if pananti else []):
                    prep = self._prep_term(
                        term, p.metadata.namespace, p.metadata.labels)
                    if not self._prepped_matches(
                            prep, p.metadata.namespace, pod):
                        continue
                    dv = other_st.labels.get(term.topology_key)
                    if dv is not None:
                        sym_veto.add((term.topology_key, dv))
        from kubernetes_tpu.sched.volumebinding import compile_pod_volumes
        vol = (compile_pod_volumes(pod, self.volumes, self._vol_rwop)
               if self.volumes is not None else None)
        slice_ok = None
        if self.slice_explain:
            shape = self._slice_shape_of(pod)
            if shape is not None:
                from kubernetes_tpu.topology import carve as carve_mod
                slice_ok = carve_mod.covered_nodes(
                    self.oracle_carve([pod], shape, set()),
                    len(self.states))
        return dict(spread=spread, aff=aff_counts, bootstrap=bootstrap,
                    anti=anti_counts, sym=sym_veto, vol=vol,
                    slice_ok=slice_ok)

    def _node_affinity_ok(self, pod: Pod, node: Node) -> bool:
        labels, fields = node.metadata.labels, node_fields(node.metadata.name)
        for k, v in pod.spec.node_selector.items():
            if labels.get(k) != v:
                return False
        aff = pod.spec.affinity
        na = aff.node_affinity if aff else None
        if na and na.required:
            if not node_selector_matches(na.required, labels, fields):
                return False
        return True

    def _ports_conflict(self, pod: Pod, st: NodeState) -> bool:
        used = [hp for p in st.pods for hp in p.host_ports()]
        for (ip, proto, port) in pod.host_ports():
            for (uip, uproto, uport) in used:
                if port == uport and proto == uproto and (
                        ip == uip or ip == "0.0.0.0" or uip == "0.0.0.0"):
                    return True
        return False

    # ---- topology spread -------------------------------------------------

    def _spread_node_eligible(self, pod: Pod, sc: TopologySpreadConstraint,
                              st: NodeState) -> bool:
        """Does this node participate in the constraint's skew computation?
        (common.go: has the topology key + nodeAffinityPolicy [default Honor]
        + nodeTaintsPolicy [default Ignore])."""
        if sc.topology_key not in st.labels:
            return False
        # fleet scoping: a sibling tenant's nodes don't participate in skew
        # or the global minimum (tensor twin: _spread_policy_elig)
        if self._tenant_of(pod.metadata.labels) != self._tenant_of(st.labels):
            return False
        if (sc.node_affinity_policy != NODE_INCLUSION_IGNORE
                and not self._node_affinity_ok(pod, st.node)):
            return False
        if (sc.node_taints_policy == NODE_INCLUSION_HONOR
                and not tolerates_all(pod.spec.tolerations, st.node.spec.taints,
                                      (EFFECT_NO_SCHEDULE, EFFECT_NO_EXECUTE))):
            return False
        return True

    def _domain_counts(self, pod: Pod, sc: TopologySpreadConstraint, eff_sel):
        """Counts per domain value over *eligible* nodes only (see
        ``_spread_node_eligible``); pods on excluded nodes don't count and
        their domains don't participate in the global minimum. Counts include
        only pods matching ``eff_sel`` in the incoming pod's namespace."""
        counts: dict[str, int] = {}
        for st in self.states:
            if not self._spread_node_eligible(pod, sc, st):
                continue
            dv = st.labels[sc.topology_key]
            counts.setdefault(dv, 0)
            for p in st.pods:
                if (p.metadata.namespace == pod.metadata.namespace
                        and label_selector_matches(eff_sel, p.metadata.labels)):
                    counts[dv] += 1
        return counts

    def _spread_ok(self, st: NodeState, ctx: dict) -> bool:
        for sc, counts, min_count, self_match in ctx["spread"]:
            dv = st.labels.get(sc.topology_key)
            if dv is None:
                return False  # node without the key can't satisfy the constraint
            if counts.get(dv, 0) + (1 if self_match else 0) - min_count > sc.max_skew:
                return False
        return True

    # ---- inter-pod affinity ---------------------------------------------

    def _prep_term(self, term, owner_ns: str, owner_labels: dict):
        """-> (ns_set | None, effective selector) via encode/termprep.py."""
        return (resolve_term_namespaces(term, owner_ns, self.namespace_labels),
                affinity_term_selector(term, owner_labels))

    @staticmethod
    def _prepped_matches(prep, owner_ns: str, target: Pod) -> bool:
        ns_set, eff = prep
        tns = target.metadata.namespace
        if (tns != owner_ns) if ns_set is None else (tns not in ns_set):
            return False
        return label_selector_matches(eff, target.metadata.labels)

    def _interpod_ok(self, st: NodeState, ctx: dict) -> Optional[str]:
        # Required affinity (filtering.go satisfyPodAffinity): every term's
        # topology key must exist on the node; every term needs a matching pod
        # in the node's domain, OR the global bootstrap applies.
        if ctx["aff"]:
            sat = True
            for term, counts in ctx["aff"]:
                dv = st.labels.get(term.topology_key)
                if dv is None:
                    return FailReason.POD_AFFINITY
                if counts.get(dv, 0) <= 0:
                    sat = False
            if not sat and not ctx["bootstrap"]:
                return FailReason.POD_AFFINITY
        # Required anti-affinity: no matching existing pod in this domain
        # (node without the key satisfies trivially).
        for term, counts in ctx["anti"]:
            dv = st.labels.get(term.topology_key)
            if dv is not None and counts.get(dv, 0) > 0:
                return FailReason.POD_ANTI_AFFINITY
        # Symmetry: existing pods' required anti-affinity veto the newcomer.
        for key, dv in ctx["sym"]:
            if st.labels.get(key) == dv:
                return FailReason.POD_ANTI_AFFINITY
        return None

    # ---- incremental what-if support (preemption dry-run verification) ---

    def remove_bound(self, pod: Pod) -> None:
        """Temporarily evict a bound pod from the simulation (preemption
        what-if); O(node) instead of rebuilding the oracle."""
        i = self.node_index.get(pod.spec.node_name)
        if i is None:
            return
        self.states[i].remove_pod(pod)
        self._fold_demands(self.states[i], pod, sign=-1)
        self._n_anti -= self._has_required_anti(pod)
        self._refresh_volume_state()

    def restore_bound(self, pod: Pod) -> None:
        """Undo remove_bound (the reprieve pass re-adds victims)."""
        i = self.node_index.get(pod.spec.node_name)
        if i is None:
            return
        self.states[i].add_pod(pod)
        self._fold_demands(self.states[i], pod)
        self._n_anti += self._has_required_anti(pod)
        self._refresh_volume_state()

    def _refresh_volume_state(self) -> None:
        if self.volumes is None:
            return  # volume tensors unused without a catalog
        from kubernetes_tpu.sched.volumebinding import cluster_volume_state
        self._vol_rwo, self._vol_attach, self._vol_rwop = cluster_volume_state(
            [p for st in self.states for p in st.pods], self.volumes)

    def feasible_one(self, pod: Pod, ni: int) -> bool:
        """Feasibility of ``pod`` on node index ``ni`` only — the per-node
        half of DryRunPreemption's re-filter, without scanning the fleet."""
        ctx = self._pod_ctx(pod)
        return self._filter_one(pod, self.states[ni], ni, ctx) is None

    def feasible(self, pod: Pod):
        """-> (mask list[bool], reasons dict node_name -> reason)."""
        ctx = self._pod_ctx(pod)
        mask, reasons = [], {}
        for i, st in enumerate(self.states):
            r = self._filter_one(pod, st, i, ctx)
            mask.append(r is None)
            if r is not None:
                reasons[st.node.metadata.name] = r
        return mask, reasons

    # ---- scores ----------------------------------------------------------

    def score(self, pod: Pod, mask: list[bool]) -> np.ndarray:
        """Weighted sum of normalized plugin scores; -inf for infeasible."""
        N = len(self.states)
        total = np.zeros(N, np.float32)
        fmask = np.asarray(mask, bool)
        for name, fn in [
            ("NodeResourcesFit", self._score_least_allocated),
            ("NodeResourcesBalancedAllocation", self._score_balanced),
            ("ImageLocality", self._score_image_locality),
            ("NodeAffinity", self._score_node_affinity),
            ("TaintToleration", self._score_taints),
            ("PodTopologySpread", self._score_spread),
            ("InterPodAffinity", self._score_interpod),
        ]:
            w = self.weights.get(name, 0.0)
            if w:
                total += np.float32(w) * fn(pod, fmask).astype(np.float32)
        return np.where(fmask, total, -np.inf).astype(np.float32)

    def _fractions(self, pod: Pod, st: NodeState):
        reqs = pod.resource_requests()
        out = []
        for r in ("cpu", "memory"):
            alloc = st.allocatable.get(r, 0)
            if alloc <= 0 or alloc >= UNLIMITED:
                out.append(np.float32(0) if r not in reqs else np.float32(1))
                continue
            used = st.requested.get(r, 0) + scale_request(r, reqs.get(r, 0))
            out.append(np.float32(used) / np.float32(alloc))
        return out

    def _score_least_allocated(self, pod: Pod, mask) -> np.ndarray:
        """least_allocated.go: mean over {cpu,memory} of 100*(alloc-used)/alloc."""
        out = np.zeros(len(self.states), np.float32)
        for i, st in enumerate(self.states):
            fr = self._fractions(pod, st)
            out[i] = np.float32(
                sum(np.float32(100) * (np.float32(1) - np.clip(f, 0, 1)) for f in fr)
                / np.float32(len(fr)))
        return out

    def _score_balanced(self, pod: Pod, mask) -> np.ndarray:
        """balanced_allocation.go: 100 * (1 - std(fractions))."""
        out = np.zeros(len(self.states), np.float32)
        for i, st in enumerate(self.states):
            fr = np.asarray(self._fractions(pod, st), np.float32)
            fr = np.clip(fr, 0, 1)
            mean = fr.mean(dtype=np.float32)
            std = np.sqrt(((fr - mean) ** 2).mean(dtype=np.float32))
            out[i] = np.float32(100) * (np.float32(1) - std)
        return out

    def _score_image_locality(self, pod: Pod, mask) -> np.ndarray:
        """image_locality.go: sum of scaled sizes of present images -> threshold ramp."""
        N = len(self.states)
        imgs = [c.image for c in pod.spec.containers if c.image]
        out = np.zeros(N, np.float32)
        if not imgs:
            return out
        # fleet scoping: the spread factor counts the POD'S TENANT'S nodes
        # only (tensor twin: ops/scores.image_locality) — a sibling fleet
        # growing must not shift this pod's locality ramp
        pt = self._tenant_of(pod.metadata.labels)
        visible = [self._tenant_of(st.labels) == pt for st in self.states]
        n_vis = sum(visible)
        have = [set(n.names[0] for n in st.node.status.images if n.names)
                for st in self.states]
        num_nodes_with = {im: sum(im in h for h, v in zip(have, visible)
                                  if v) for im in imgs}
        sizes = {}
        for st in self.states:
            for n in st.node.status.images:
                if n.names:
                    sizes[n.names[0]] = max(sizes.get(n.names[0], 0), n.size_bytes)
        max_threshold = IMG_MAX_CONTAINER_THRESHOLD * max(len(imgs), 1)
        for i, st in enumerate(self.states):
            ssum = np.float32(0)
            for im in imgs:
                if im in have[i]:
                    spread = np.float32(num_nodes_with[im]) / np.float32(
                        max(n_vis, 1))
                    ssum += np.float32(sizes.get(im, 0)) * spread
            val = (ssum - np.float32(IMG_MIN_THRESHOLD)) / np.float32(
                max_threshold - IMG_MIN_THRESHOLD)
            out[i] = np.clip(val, 0, 1) * np.float32(100)
        return out

    def _score_node_affinity(self, pod: Pod, mask) -> np.ndarray:
        """Sum of matching preferred-term weights, DefaultNormalizeScore to 0-100."""
        aff = pod.spec.affinity
        na = aff.node_affinity if aff else None
        raw = np.zeros(len(self.states), np.float32)
        for t in (na.preferred if na else []):
            for i, st in enumerate(self.states):
                from kubernetes_tpu.api.selectors import node_selector_term_matches
                if node_selector_term_matches(t.preference, st.labels,
                                              node_fields(st.node.metadata.name)):
                    raw[i] += np.float32(t.weight)
        return _default_normalize(raw, mask, reverse=False)

    def _score_taints(self, pod: Pod, mask) -> np.ndarray:
        raw = np.zeros(len(self.states), np.float32)
        for i, st in enumerate(self.states):
            c = 0
            for t in st.node.spec.taints:
                if t.effect == EFFECT_PREFER_NO_SCHEDULE and not any(
                        tol.tolerates(t) for tol in pod.spec.tolerations):
                    c += 1
            raw[i] = c
        return _default_normalize(raw, mask, reverse=True)

    def _score_spread(self, pod: Pod, mask) -> np.ndarray:
        """ScheduleAnyway constraints only (scoring.go PreScore): fewer
        matching pods in the node's domain is better."""
        N = len(self.states)
        raw = np.zeros(N, np.float32)
        has_any = False
        for sc in pod.spec.topology_spread_constraints:
            if sc.when_unsatisfiable != "ScheduleAnyway":
                continue
            has_any = True
            eff = spread_selector(sc, pod.metadata.labels)
            counts = self._domain_counts(pod, sc, eff)
            for i, st in enumerate(self.states):
                dv = st.labels.get(sc.topology_key)
                raw[i] += np.float32(counts.get(dv, 0) if dv is not None else 0)
        if not has_any:
            return np.zeros(N, np.float32)
        return _default_normalize(raw, mask, reverse=True)

    def _score_interpod(self, pod: Pod, mask) -> np.ndarray:
        """Preferred inter-pod (anti)affinity of the incoming pod: +/- weight per
        matching existing pod in the node's domain."""
        aff = pod.spec.affinity
        pa = aff.pod_affinity if aff else None
        pan = aff.pod_anti_affinity if aff else None
        N = len(self.states)
        raw = np.zeros(N, np.float32)
        ns = pod.metadata.namespace
        terms = [(t.weight, t.term) for t in (pa.preferred if pa else [])]
        terms += [(-t.weight, t.term) for t in (pan.preferred if pan else [])]
        if not terms:
            return raw
        for w, term in terms:
            prep = self._prep_term(term, ns, pod.metadata.labels)
            # count matching pods per domain value
            counts: dict[str, int] = {}
            for st in self.states:
                dv = st.labels.get(term.topology_key)
                if dv is None:
                    continue
                counts.setdefault(dv, 0)
                for p in st.pods:
                    if self._prepped_matches(prep, ns, p):
                        counts[dv] += 1
            for i, st in enumerate(self.states):
                dv = st.labels.get(term.topology_key)
                if dv is not None:
                    raw[i] += np.float32(w) * np.float32(counts.get(dv, 0))
        return _minmax_normalize(raw, mask)

    # ---- topology slice carving (topology/) ------------------------------

    def _slice_shape_of(self, pod: Pod):
        """The pod's requested slice shape: the slice-shape label, else a
        slice-shaped ResourceClaim when a DRA catalog is attached."""
        from kubernetes_tpu.topology.slicing import shape_of_labels
        s = shape_of_labels(pod.metadata.labels)
        if s is None and self.dra is not None:
            s = self.dra.pod_slice_shape(pod)
        return s

    def _slice_member_req(self, pods: list[Pod]) -> dict:
        """Conservative homogeneous gang view: elementwise MAX of the
        members' scaled requests (the device carver mirrors this over
        pb.requests rows)."""
        req: dict = {}
        for p in pods:
            for r, q in self._eff_requests(p).items():
                req[r] = max(req.get(r, 0), scale_request(r, q))
        return req

    def oracle_carve(self, members: list[Pod], shape: tuple,
                     claimed: set):
        """The numpy oracle carver: per-node host verdicts from the CURRENT
        NodeStates fed to topology/carve.numpy_grids — the bit-parity twin
        of the device's carve_step (asserted by the parity tests and the
        sentinel's carve site). ``claimed`` holds node indices earlier
        gangs of the same cycle already took."""
        from kubernetes_tpu.topology import carve as carve_mod
        if self._dims is None or not members:
            return None
        member_req = self._slice_member_req(members)
        tenant = self._tenant_of(members[0].metadata.labels)
        free, evictable, n_pods = [], [], []
        for i, st in enumerate(self.states):
            usable = (self._coords[i] is not None
                      and tenant == self._tenant_of(st.labels)
                      and not st.node.spec.unschedulable
                      and i not in claimed)
            fits_free = all(q <= st.allocatable.get(r, 0)
                            - st.requested.get(r, 0)
                            for r, q in member_req.items())
            fits_alone = all(q <= st.allocatable.get(r, 0)
                             for r, q in member_req.items())
            free.append(usable and fits_free)
            evictable.append(usable and fits_alone)
            n_pods.append(len(st.pods))
        return carve_mod.numpy_grids(self._coords, free, evictable,
                                     n_pods, self._dims, shape)

    def plan_slices(self, pods: list[Pod], validate: bool = True) -> dict:
        """Carve every slice gang among ``pods`` in the device path's exact
        order (sorted gang ids; earlier gangs' cells claimed against later
        ones; members in sorted-key order <-> C-order box cells) ->
        {gang id: {pod key: node name} or None}. With ``validate`` every
        member must ALSO pass the full oracle filter stack on its cell
        (schedule_all uses this, so an oracle-mode cycle never places an
        infeasible member); the parity sentinel replays with
        validate=False to judge the CARVE alone — the device's gang
        program applies its own filters after the carve pins."""
        from kubernetes_tpu.topology import carve as carve_mod
        from kubernetes_tpu.topology.slicing import GANG_LABEL
        groups: dict[str, list[Pod]] = {}
        shapes: dict[str, tuple] = {}
        for p in pods:
            shape = self._slice_shape_of(p)
            if shape is None:
                continue
            g = (p.metadata.labels or {}).get(GANG_LABEL) or f"pod:{p.key}"
            groups.setdefault(g, []).append(p)
            shapes[g] = shape
        plans: dict[str, Optional[dict]] = {}
        claimed: set = set()
        for g in sorted(groups):
            members = sorted(groups[g], key=lambda p: p.key)
            shape = shapes[g]
            asg = None
            if len(members) == shape[0] * shape[1] * shape[2]:
                res = self.oracle_carve(members, shape, claimed)
                asg = carve_mod.select_assignment(res)
            if asg is not None and validate:
                for m, p in enumerate(members):
                    if self._filter_one(p, self.states[asg[m]], asg[m],
                                        self._pod_ctx(p)) is not None:
                        asg = None
                        break
            if asg is None:
                plans[g] = None
                continue
            claimed.update(asg)
            plans[g] = {p.key: self.states[asg[m]].node.metadata.name
                        for m, p in enumerate(members)}
        return plans

    # ---- cycle -----------------------------------------------------------

    def select_host(self, scores: np.ndarray, salt: int = 0) -> Optional[int]:
        if not np.isfinite(scores).any():
            return None
        best = np.max(scores)
        cands = [i for i in range(len(scores)) if scores[i] == best]
        return min(cands, key=lambda n: tie_break(self._node_rank[n],
                                                  self.seed, salt))

    def schedule_one(self, pod: Pod, salt: int = 0):
        """-> (node index or None, reasons). Does NOT assume; caller decides."""
        mask, reasons = self.feasible(pod)
        if not any(mask):
            return None, reasons
        scores = self.score(pod, mask)
        return self.select_host(scores, salt), reasons

    def assume(self, pod: Pod, node_idx: int):
        pod.spec.node_name = self.states[node_idx].node.metadata.name
        self.states[node_idx].add_pod(pod)
        self._fold_demands(self.states[node_idx], pod)
        self._n_anti += self._has_required_anti(pod)

    def schedule_all(self, pods: list[Pod]):
        """Serial loop over the batch (ScheduleOne x N) in activeQ order —
        priority desc, then arrival (list) order, exactly like the reference's
        PrioritySort queue and the gang batcher's rank. The tie-break salt
        stays the pod's original batch position. Results in input order."""
        order = sorted(range(len(pods)), key=lambda i: (-pods[i].spec.priority, i))
        out: list[Optional[int]] = [None] * len(pods)
        # slice gangs first: carve + assume up front, so no ordinary pod in
        # this batch can nibble a planned cell's capacity between the carve
        # and the member's turn in priority order (contiguous placements
        # are the scarcest resource in the batch)
        slice_nodes: dict[str, Optional[int]] = {}
        if any(self._slice_shape_of(p) is not None for p in pods):
            plans = self.plan_slices(pods)
            picked: dict[str, str] = {}
            for plan in plans.values():
                picked.update(plan or {})
            for p in pods:
                if self._slice_shape_of(p) is None:
                    continue
                ni = self.node_index.get(picked.get(p.key, ""))
                if ni is not None:
                    self.assume(p, ni)
                slice_nodes[p.key] = ni
        for i in order:
            if pods[i].key in slice_nodes:
                out[i] = slice_nodes[pods[i].key]
                continue
            ni, _ = self.schedule_one(pods[i], salt=i)
            if ni is not None:
                self.assume(pods[i], ni)
            out[i] = ni
        return out


def _default_normalize(raw: np.ndarray, mask: np.ndarray, reverse: bool) -> np.ndarray:
    """helper.DefaultNormalizeScore over feasible nodes: scale raw to 0-100 by
    max; reverse flips."""
    mx = np.max(raw[mask]) if mask.any() else np.float32(0)
    if mx <= 0:
        return np.full_like(raw, np.float32(100) if reverse else np.float32(0))
    s = raw * np.float32(100) / np.float32(mx)
    return np.float32(100) - s if reverse else s


def _minmax_normalize(raw: np.ndarray, mask: np.ndarray) -> np.ndarray:
    """InterPodAffinity normalize over feasible nodes: min-max to 0-100
    (scoring.go NormalizeScore)."""
    if raw.size == 0 or not mask.any():
        return np.zeros_like(raw)
    mn, mx = np.min(raw[mask]), np.max(raw[mask])
    if mx == mn:
        return np.zeros_like(raw)
    return (raw - mn) * np.float32(100) / np.float32(mx - mn)
