"""Fleet scheduling — K tenant clusters through ONE warm resident program.

The tensor formulation makes multi-cluster the cheap axis the Go scheduler
never had: tenants concatenate along the NODE axis of the one device-resident
cluster encoding, with per-tenant visibility enforced by the pre-interned
``kubernetes-tpu.io/tenant`` label plane (encode/snapshot.py TENANT_KEY_ID —
``tenant_of_node`` / ``tenant_of_pod`` are label columns, so churn patches,
sharding specs, overlays and the staging arena carry tenancy for free).
Pods from all tenants ride the SAME ``drain_step`` dispatch, churn from all
tenants folds into the SAME resident ctx, and compile cost + device
residency amortize fleet-wide.

Three layers live here:

``rekey_for_tenant``/``unrekey_for_tenant``
    The translation boundary. Each tenant is an independent apiserver with
    its own name space; objects ingest into the shared scheduler re-keyed
    (namespaces and cluster-scoped names get a ``t<id>.`` prefix, every
    object is stamped with the tenant label, pod references — nodeName,
    nominatedNodeName, affinity ``namespaces`` lists, ``metadata.name``
    matchFields — are rewritten consistently) and every write routes back
    through the inverse.

``FleetClient``
    A routing clientset facade over the K tenant clients: aggregate
    re-keyed reads for ``ns=None`` listers (the invariant auditor, the
    stale-nomination GC), per-tenant routed writes for prefixed
    namespaces (binds, evictions, status updates, events). List/watch
    stays on the REAL per-tenant clients — each tenant keeps its own
    informer set and resourceVersion space.

``FleetQueue`` / ``FleetRunner``
    The fairness plane and the multiplexer: one scheduler process, N
    informer sets, one shared drain pipeline. ``FleetQueue.pop_batch``
    fills the drain in ``batch_size`` single-tenant blocks, weighted
    round-robin across tenants, so a churning tenant cannot starve
    siblings' batch slots — and because every tenant's pods sit at
    positions 0..n of their own block, fleet-batched placements are
    bit-equal to independent per-tenant runs (tests/test_fleet.py).
"""

from __future__ import annotations

import json
import logging
import re
import threading
import time
from typing import Optional

from kubernetes_tpu.client.clientset import ApiError
from kubernetes_tpu.client.informer import InformerFactory
from kubernetes_tpu.config.types import SchedulerConfiguration
from kubernetes_tpu.encode.snapshot import TENANT_LABEL, tenant_label_of
from kubernetes_tpu.metrics.registry import (
    BIND_RESULTS,
    FLEET_BATCH_SHARE,
    FLEET_PENDING,
    LOOP_ERRORS,
)
from kubernetes_tpu.sched.queue import SchedulingQueue, _QueuedPod
from kubernetes_tpu.sched.runner import SchedulerRunner

_LOG = logging.getLogger(__name__)

# per-tenant scheduler status ConfigMap, published to EVERY tenant's own
# apiserver (``ktpu status`` pointed at any tenant shows the fleet line)
FLEET_SCHED_CONFIGMAP = "kubernetes-tpu-fleet-sched-status"

_PREFIX_RE = re.compile(r"^t(\d+)\.")

# kinds whose identity is their (cluster-scoped) name: the name carries the
# tenant prefix. Everything else is namespaced and prefixes the namespace.
CLUSTER_SCOPED = frozenset({
    "nodes", "namespaces", "storageclasses", "deviceclasses",
    "resourceslices", "persistentvolumes",
})


def fleet_name(tid: int, name: str) -> str:
    return f"t{tid}.{name}"


def split_fleet_name(name: str) -> tuple[Optional[int], str]:
    """-> (tenant id, raw name); (None, name) when unprefixed."""
    m = _PREFIX_RE.match(name or "")
    if not m:
        return None, name
    return int(m.group(1)), name[m.end():]


def _strip(name: Optional[str], tid: int) -> Optional[str]:
    pref = f"t{tid}."
    if name and name.startswith(pref):
        return name[len(pref):]
    return name


def _rekey_pod_affinity_terms(terms: list, pref: str) -> list:
    out = []
    for t in terms:
        t = dict(t)
        inner = t.get("podAffinityTerm")
        if inner is not None:  # weighted form
            t["podAffinityTerm"] = _rekey_pod_affinity_terms([inner], pref)[0]
        elif t.get("namespaces"):
            t["namespaces"] = [pref + n for n in t["namespaces"]]
        out.append(t)
    return out


def _map_pv_terms(terms: list, fn) -> list:
    """Apply ``fn`` to the node-name matchFields values and zone-label
    matchExpressions values of PV nodeSelectorTerms. CSI topology names
    nodes and zones inside PV nodeAffinity; both are per-tenant names, so
    they cross the fleet boundary through the same rewrite as nodeName —
    two tenants publishing the same zone string must NOT appear co-located
    in the shared view."""
    from kubernetes_tpu.sched.volumebinding import ZONE_LABELS
    out = []
    for t in terms:
        t = dict(t)
        mf = t.get("matchFields")
        if mf:
            t["matchFields"] = [
                (dict(e, values=[fn(v) for v in e.get("values") or []])
                 if e.get("key") == "metadata.name" else e)
                for e in mf]
        me = t.get("matchExpressions")
        if me:
            t["matchExpressions"] = [
                (dict(e, values=[fn(v) for v in e.get("values") or []])
                 if e.get("key") in ZONE_LABELS else e)
                for e in me]
        out.append(t)
    return out


def _map_pv_node_affinity(spec: dict, fn) -> dict:
    na = spec.get("nodeAffinity")
    req = (na or {}).get("required")
    if not (req or {}).get("nodeSelectorTerms"):
        return spec
    spec["nodeAffinity"] = dict(na, required=dict(
        req, nodeSelectorTerms=_map_pv_terms(req["nodeSelectorTerms"], fn)))
    return spec


def _map_zone_labels(md: dict, fn) -> dict:
    """Rewrite CSI topology label VALUES on the object's metadata (nodes
    and PVs carry zone/region labels that volume binding compares)."""
    from kubernetes_tpu.sched.volumebinding import ZONE_LABELS
    labels = md.get("labels")
    if not labels or not any(labels.get(z) for z in ZONE_LABELS):
        return md
    labels = dict(labels)
    for z in ZONE_LABELS:
        if labels.get(z):
            labels[z] = fn(labels[z])
    md["labels"] = labels
    return md


def _rekey_match_fields(term: dict, pref: str) -> dict:
    mf = term.get("matchFields")
    if not mf:
        return term
    term = dict(term)
    term["matchFields"] = [
        (dict(e, values=[pref + v for v in e.get("values") or []])
         if e.get("key") == "metadata.name" else e)
        for e in mf]
    return term


def _rekey_affinity(aff: dict, pref: str) -> dict:
    aff = dict(aff)
    for k in ("podAffinity", "podAntiAffinity"):
        a = aff.get(k)
        if not a:
            continue
        a = dict(a)
        for req in ("requiredDuringSchedulingIgnoredDuringExecution",
                    "preferredDuringSchedulingIgnoredDuringExecution"):
            if a.get(req):
                a[req] = _rekey_pod_affinity_terms(a[req], pref)
        aff[k] = a
    na = aff.get("nodeAffinity")
    if na:
        na = dict(na)
        req = na.get("requiredDuringSchedulingIgnoredDuringExecution")
        if req and req.get("nodeSelectorTerms"):
            na["requiredDuringSchedulingIgnoredDuringExecution"] = dict(
                req, nodeSelectorTerms=[
                    _rekey_match_fields(t, pref)
                    for t in req["nodeSelectorTerms"]])
        pol = na.get("preferredDuringSchedulingIgnoredDuringExecution")
        if pol:
            na["preferredDuringSchedulingIgnoredDuringExecution"] = [
                dict(w, preference=_rekey_match_fields(
                    w.get("preference") or {}, pref)) for w in pol]
        aff["nodeAffinity"] = na
    return aff


def rekey_for_tenant(tid: int, plural: str, obj: Optional[dict]
                     ) -> Optional[dict]:
    """A tenant apiserver object as the SHARED scheduler sees it: tenant
    label stamped, namespace (or cluster-scoped name) prefixed, and every
    intra-object reference that names another object rewritten to match.
    Copies every level it mutates — informer stores share the originals."""
    if obj is None:
        return None
    pref = f"t{tid}."
    out = dict(obj)
    md = dict(out.get("metadata") or {})
    labels = dict(md.get("labels") or {})
    labels[TENANT_LABEL] = str(tid)
    md["labels"] = labels
    if plural in CLUSTER_SCOPED:
        md["name"] = pref + (md.get("name") or "")
    else:
        md["namespace"] = pref + (md.get("namespace") or "default")
    out["metadata"] = md
    if plural == "pods":
        spec = dict(out.get("spec") or {})
        if spec.get("nodeName"):
            spec["nodeName"] = pref + spec["nodeName"]
        if spec.get("affinity"):
            spec["affinity"] = _rekey_affinity(spec["affinity"], pref)
        out["spec"] = spec
        st = out.get("status")
        if st and st.get("nominatedNodeName"):
            out["status"] = dict(
                st, nominatedNodeName=pref + st["nominatedNodeName"])
    elif plural == "persistentvolumeclaims":
        spec = dict(out.get("spec") or {})
        for f in ("volumeName", "storageClassName"):
            if spec.get(f):
                spec[f] = pref + spec[f]
        out["spec"] = spec
    elif plural == "persistentvolumes":
        spec = dict(out.get("spec") or {})
        if spec.get("storageClassName"):
            spec["storageClassName"] = pref + spec["storageClassName"]
        cr = spec.get("claimRef")
        if cr and cr.get("namespace"):
            spec["claimRef"] = dict(cr, namespace=pref + cr["namespace"])
        spec = _map_pv_node_affinity(spec, lambda v: pref + v)
        out["spec"] = spec
        out["metadata"] = _map_zone_labels(md, lambda v: pref + v)
    elif plural == "nodes":
        out["metadata"] = _map_zone_labels(md, lambda v: pref + v)
    return out


def unrekey_for_tenant(tid: int, plural: str, obj: Optional[dict]
                       ) -> Optional[dict]:
    """Inverse of ``rekey_for_tenant`` — what the shared scheduler writes
    back to tenant ``tid``'s apiserver."""
    if obj is None:
        return None
    out = dict(obj)
    md = dict(out.get("metadata") or {})
    labels = dict(md.get("labels") or {})
    if labels.get(TENANT_LABEL) == str(tid):
        labels.pop(TENANT_LABEL)
        md["labels"] = labels
    if plural in CLUSTER_SCOPED:
        md["name"] = _strip(md.get("name"), tid)
    else:
        md["namespace"] = _strip(md.get("namespace"), tid)
    out["metadata"] = md
    if plural == "pods":
        spec = dict(out.get("spec") or {})
        if spec.get("nodeName"):
            spec["nodeName"] = _strip(spec["nodeName"], tid)
        out["spec"] = spec
        st = out.get("status")
        if st and st.get("nominatedNodeName"):
            out["status"] = dict(st, nominatedNodeName=_strip(
                st["nominatedNodeName"], tid))
    elif plural == "persistentvolumeclaims":
        # inverse of the ingest rewrites PLUS the binder's write-backs:
        # spec.volumeName/storageClassName carry the fleet prefix, and the
        # provisioner-facing selected-node annotation names a FLEET node
        spec = dict(out.get("spec") or {})
        for f in ("volumeName", "storageClassName"):
            if spec.get(f):
                spec[f] = _strip(spec[f], tid)
        out["spec"] = spec
        ann = md.get("annotations")
        sel = (ann or {}).get("volume.kubernetes.io/selected-node")
        if sel:
            md["annotations"] = dict(ann, **{
                "volume.kubernetes.io/selected-node": _strip(sel, tid)})
    elif plural == "persistentvolumes":
        spec = dict(out.get("spec") or {})
        if spec.get("storageClassName"):
            spec["storageClassName"] = _strip(spec["storageClassName"], tid)
        cr = spec.get("claimRef")
        if cr and cr.get("namespace"):
            spec["claimRef"] = dict(cr, namespace=_strip(cr["namespace"],
                                                         tid))
        spec = _map_pv_node_affinity(spec, lambda v: _strip(v, tid))
        out["spec"] = spec
        out["metadata"] = _map_zone_labels(md, lambda v: _strip(v, tid))
    elif plural == "nodes":
        out["metadata"] = _map_zone_labels(md, lambda v: _strip(v, tid))
    elif plural == "resourceclaims":
        # the scheduler's PreBind allocation embeds the node name
        st = out.get("status")
        alloc = (st or {}).get("allocation")
        if alloc and alloc.get("nodeName"):
            out["status"] = dict(st, allocation=dict(
                alloc, nodeName=_strip(alloc["nodeName"], tid)))
    elif plural == "events":
        # the recorder builds involvedObject from the fleet-view pod; a
        # tenant apiserver must never see the internal prefix
        io_ = out.get("involvedObject")
        if io_ and io_.get("namespace"):
            out["involvedObject"] = dict(
                io_, namespace=_strip(io_["namespace"], tid))
    return out


# ---------------------------------------------------------------------------
# FleetClient: a routing clientset facade over K tenant clients
# ---------------------------------------------------------------------------

class _TenantResource:
    """One tenant's ResourceClient behind the rekey/unrekey boundary."""

    def __init__(self, fleet: "FleetClient", tid: int, plural: str,
                 raw_ns: Optional[str]):
        self._fleet = fleet
        self._tid = tid
        self._plural = plural
        self._res = fleet.clients[tid].resource(plural, raw_ns)

    def _rk(self, obj):
        return rekey_for_tenant(self._tid, self._plural, obj)

    def _uk(self, obj):
        return unrekey_for_tenant(self._tid, self._plural, obj)

    def _name(self, name: str) -> str:
        return (_strip(name, self._tid) if self._plural in CLUSTER_SCOPED
                else name)

    def create(self, obj: dict, **kw) -> dict:
        return self._rk(self._res.create(self._uk(obj), **kw))

    def create_many(self, objs: list) -> list:
        return [self._rk(o)
                for o in self._res.create_many([self._uk(o) for o in objs])]

    def get(self, name: str) -> dict:
        return self._rk(self._res.get(self._name(name)))

    def list(self, **kw) -> list:
        return [self._rk(o) for o in self._res.list(**kw)]

    def update(self, obj: dict) -> dict:
        return self._rk(self._res.update(self._uk(obj)))

    def update_status(self, obj: dict) -> dict:
        return self._rk(self._res.update_status(self._uk(obj)))

    def delete(self, name: str, **kw):
        return self._res.delete(self._name(name), **kw)

    def evict(self, name: str):
        return self._res.evict(self._name(name))

    def bind(self, name: str, node_name: str) -> dict:
        ntid, raw = split_fleet_name(node_name)
        if ntid != self._tid:
            # the tenant gate makes this unreachable from the scheduler;
            # refusing here is the transport-level backstop
            raise ApiError(403, f"cross-tenant bind: pod of tenant "
                                f"{self._tid} onto node {node_name!r}")
        return self._res.bind(name, raw)


class _FleetAllResource:
    """``ns=None`` aggregate reader: the auditor's and the GC's fleet-wide
    listers. Reads concatenate every tenant's re-keyed objects (stable
    tenant order); name-addressed writes route by prefix for
    cluster-scoped kinds."""

    def __init__(self, fleet: "FleetClient", plural: str):
        self._fleet = fleet
        self._plural = plural

    def list(self, **kw) -> list:
        out: list = []
        for tid in sorted(self._fleet.clients):
            res = self._fleet.clients[tid].resource(self._plural, None)
            out += [rekey_for_tenant(tid, self._plural, o)
                    for o in res.list(**kw)]
        return out

    def _route(self, name: str):
        tid, raw = split_fleet_name(name)
        if tid is None or tid not in self._fleet.clients:
            raise ApiError(404, f"no tenant for {name!r}")
        return tid, self._fleet.clients[tid].resource(self._plural, None), raw

    def get(self, name: str) -> dict:
        tid, res, raw = self._route(name)
        return rekey_for_tenant(tid, self._plural, res.get(raw))

    def delete(self, name: str, **kw):
        _tid, res, raw = self._route(name)
        return res.delete(raw, **kw)

    def update(self, obj: dict) -> dict:
        """Cluster-scoped update routed by name prefix — the volume
        binder's static-PV claimRef write (persistentvolumes, ns=None)
        goes through here."""
        md = obj.get("metadata") or {}
        tid, res, _raw = self._route(md.get("name") or "")
        return rekey_for_tenant(
            tid, self._plural,
            res.update(unrekey_for_tenant(tid, self._plural, obj)))

    def update_status(self, obj: dict) -> dict:
        md = obj.get("metadata") or {}
        tid, res, _raw = self._route(md.get("name") or "")
        return rekey_for_tenant(
            tid, self._plural,
            res.update_status(unrekey_for_tenant(tid, self._plural, obj)))


class FleetClient:
    """Routing clientset over K tenant clients. Namespaced calls with a
    ``t<id>.`` prefix route (and translate) to that tenant; ``ns=None``
    reads aggregate; unprefixed namespaces pass through to the HOME tenant
    (tenant 0) untranslated — that is where the runner's own status
    ConfigMaps live."""

    def __init__(self, clients: list):
        self.clients = {i: c for i, c in enumerate(clients)}

    def default_user_agent(self, ua: str) -> None:
        for c in self.clients.values():
            if hasattr(c, "default_user_agent"):
                c.default_user_agent(ua)

    def resource(self, plural: str, ns: Optional[str] = "default"):
        if ns is None:
            return _FleetAllResource(self, plural)
        tid, raw = split_fleet_name(ns)
        if tid is not None and plural not in CLUSTER_SCOPED:
            if tid not in self.clients:
                raise ApiError(404, f"unknown tenant namespace {ns!r}")
            return _TenantResource(self, tid, plural, raw)
        return self.clients[0].resource(plural, ns)

    def pods(self, ns: str = "default"):
        return self.resource("pods", ns)

    def nodes(self):
        return self.resource("nodes", None)

    def leases(self, ns: str = "kube-system"):
        return self.clients[0].leases(ns)


# ---------------------------------------------------------------------------
# FleetQueue: the fairness plane
# ---------------------------------------------------------------------------

class FleetQueue(SchedulingQueue):
    """SchedulingQueue whose ``pop_batch`` fills the drain in
    ``block``-sized SINGLE-TENANT blocks, weighted round-robin across the
    tenants with pending pods. Two properties fall out:

    - fairness: a tenant churning 4x harder than its siblings gets its
      weighted share of batch slots per rotation, never the whole batch —
      the rotation cursor advances every pop, so nobody is pinned to the
      tail.
    - bit-parity: each tenant's pods enter the device program at positions
      0..n of their own block (the first SHORT block closes the pop, so a
      later tenant can never start mid-chunk), which together with the
      tenant-local tie-break ranks makes fleet placements identical to
      standalone runs.

    Single-tenant queues (no tenant labels) degrade to the base behavior
    exactly: one group, plain priority-ordered drain."""

    def __init__(self, block: int = 256, weights: Optional[dict] = None,
                 **kw):
        super().__init__(**kw)
        self._block = max(1, int(block))
        self._weights = {str(k): max(1, int(v))
                         for k, v in (weights or {}).items()}
        self._rr = 0
        # pods handed to the scheduler per tenant (monotone; the fleet
        # status ConfigMap and scheduler_fleet_batch_share report it)
        self.batch_share: dict[str, int] = {}

    @staticmethod
    def _tenant(pod) -> str:
        return tenant_label_of(pod.metadata.labels) or ""

    def set_weight(self, tenant, blocks: int) -> None:
        """Quota-weighted fill: ``blocks`` batch blocks per rotation."""
        with self._lock:
            self._weights[str(tenant)] = max(1, int(blocks))

    def pending_by_tenant(self) -> dict[str, int]:
        with self._lock:
            out: dict[str, int] = {}
            for item in self._entries.values():
                t = self._tenant(item.pod)
                out[t] = out.get(t, 0) + 1
            return out

    def pop_batch(self, max_batch: int = 256, wait: float = 0.5
                  ) -> list:
        import heapq
        deadline = time.time() + wait
        with self._lock:
            if not self._wait_for_work_locked(deadline):
                return []
            # Drain a bounded look-ahead window in priority order, group by
            # tenant (order within a tenant stays priority order). The
            # window is PROPORTIONAL to the batch — under a deep backlog a
            # fixed large floor would heappop+push thousands of entries of
            # pure churn per cycle on the hot loop. FIFO tie-breaks age
            # out-of-window tenants to the front across cycles, so nobody
            # is starved by the bound.
            drained: list[_QueuedPod] = []
            cap = max(max_batch * 4, 256)
            while self._active and len(drained) < cap:
                item = heapq.heappop(self._active)
                if self._current_locked(item):
                    drained.append(item)
            groups: dict[str, list] = {}
            order: list[str] = []
            for item in drained:
                t = self._tenant(item.pod)
                if t not in groups:
                    groups[t] = []
                    order.append(t)
                groups[t].append(item)
            if len(groups) <= 1:
                chosen = drained[:max_batch]
                leftovers = drained[max_batch:]
            else:
                chosen, leftovers = self._fill_fair(groups, order, max_batch)
            for item in leftovers:
                heapq.heappush(self._active, item)
            out = []
            for item in chosen:
                self._keys_queued.discard(item.pod.key)
                self._entries.pop(item.pod.key, None)
                out.append((item.pod, item.attempts))
                t = self._tenant(item.pod)
                self.batch_share[t] = self.batch_share.get(t, 0) + 1
            return out

    def _fill_fair(self, groups: dict, order: list, max_batch: int):
        """Weighted round-robin block fill. The first block that comes up
        SHORT (its tenant ran out of pods) is the pop's final block —
        alignment before greed: the leftover trickle pods get the next
        cycle (milliseconds away) instead of starting mid-chunk now."""
        ring = sorted(order)
        start = self._rr % len(ring)
        ring = ring[start:] + ring[:start]
        self._rr += 1
        chosen: list[_QueuedPod] = []
        closed = False
        for _rotation in range(max(2, max_batch // self._block + 2)):
            took_any = False
            for t in ring:
                if closed or len(chosen) >= max_batch:
                    break
                g = groups[t]
                for _b in range(self._weights.get(t, 1)):
                    if not g or len(chosen) >= max_batch:
                        break
                    n = min(self._block, max_batch - len(chosen), len(g))
                    chosen.extend(g[:n])
                    del g[:n]
                    took_any = True
                    if n < self._block:
                        closed = True  # short block: only ever the last
                        break
            if closed or not took_any or len(chosen) >= max_batch:
                break
        leftovers = [it for t in order for it in groups[t]]
        return chosen, leftovers


# ---------------------------------------------------------------------------
# FleetRunner: N informer sets -> one scheduler
# ---------------------------------------------------------------------------

class FleetRunner(SchedulerRunner):
    """ONE scheduler process serving K tenant apiservers: per-tenant
    informer factories feed the shared cache/queue through the rekey
    boundary; binds, evictions, events, nomination GC and the invariant
    auditor route back through the FleetClient. One warm resident device
    program serves every tenant's drain."""

    def __init__(self, tenant_clients: list,
                 cfg: Optional[SchedulerConfiguration] = None,
                 identity: str = "kubernetes-tpu-fleet-scheduler",
                 tenant_weights: Optional[dict] = None, **kw):
        if cfg is not None and cfg.leader_elect:
            raise ValueError("fleet mode owns the loop lifecycle; "
                             "leader election is per-tenant-cluster state "
                             "and is not supported")
        self.tenant_clients = list(tenant_clients)
        if not self.tenant_clients:
            raise ValueError("FleetRunner needs >= 1 tenant client")
        self._tenant_weights = dict(tenant_weights or {})
        fleet_client = FleetClient(self.tenant_clients)
        super().__init__(fleet_client, cfg, identity=identity, **kw)
        self.scheduler.fleet_mode = True
        # real per-tenant informer factories (each tenant keeps its own
        # resourceVersion space + watch streams); the base class's
        # self.factory (over the FleetClient) is never started
        self.factories = [InformerFactory(c) for c in self.tenant_clients]
        self._fleet_status_lock = threading.Lock()

    # ---- construction hooks ---------------------------------------------

    def _build_queue(self, cfg: SchedulerConfiguration) -> SchedulingQueue:
        return FleetQueue(block=cfg.batch_size,
                          weights=getattr(self, "_tenant_weights", None),
                          backoff_initial=cfg.backoff_initial_s,
                          backoff_max=cfg.backoff_max_s)

    def _all_informers(self):
        out = []
        for f in getattr(self, "factories", []):
            out += list(f._informers.values())
        return out

    # ---- lifecycle -------------------------------------------------------

    def _start(self, wait_sync: float, start_loop: bool):
        for tid, factory in enumerate(self.factories):
            self._register_tenant_informers(tid, factory)
            factory.start_all()
        for factory in self.factories:
            factory.wait_for_cache_sync(wait_sync)
        self.scheduler.pdb_lister = self._list_pdbs
        if start_loop:
            self._start_loop()
        self.auditor.start()
        self.publish_status()
        return self

    def _register_tenant_informers(self, tid: int,
                                   factory: InformerFactory) -> None:
        """SchedulerRunner._wire_informers with a re-keying wrap — the
        base class owns THE list of watched resources, so a resource
        added there reaches every tenant automatically."""
        def wrap(handler, plural):
            def h(type_, obj, old):
                handler(type_, rekey_for_tenant(tid, plural, obj),
                        rekey_for_tenant(tid, plural, old)
                        if old is not None else old)
            return h

        self._wire_informers(factory, wrap=wrap)

    def _list_pdbs(self) -> list:
        out: list = []
        for tid, factory in enumerate(self.factories):
            inf = factory._informers.get(("poddisruptionbudgets", None))
            if inf is not None:
                out += [rekey_for_tenant(tid, "poddisruptionbudgets", o)
                        for o in inf.store.list()]
        return out

    def stop(self):
        super().stop()
        for f in self.factories:
            f.stop_all()

    def kill(self):
        super().kill()
        for f in self.factories:
            f.stop_all()

    # ---- binding ---------------------------------------------------------

    def _bind_many(self, pairs) -> list:
        """Bulk binder, split per tenant: one POST pods/-/binding per
        tenant apiserver. Cross-tenant pairs are refused outright (the
        tenant gate makes them unreachable; refusing beats binding)."""
        out: list = [False] * len(pairs)
        groups: dict[int, list] = {}
        for idx, (pod, node) in enumerate(pairs):
            tid, raw_ns = split_fleet_name(pod.metadata.namespace)
            ntid, raw_node = split_fleet_name(node)
            if tid is None or ntid != tid:
                LOOP_ERRORS.inc({"site": "cross_tenant_bind"})
                _LOG.error("REFUSING cross-tenant bind %s -> %s",
                           pod.key, node)
                continue
            groups.setdefault(tid, []).append(
                (idx, raw_ns, pod, raw_node))
        for tid, entries in groups.items():
            bindings = [(ns, pod.metadata.name, node)
                        for (_i, ns, pod, node) in entries]
            try:
                errs = self._retry(
                    lambda t=tid, b=bindings:
                    self.tenant_clients[t].pods("default").bind_many(b))
            except ApiError as e:
                BIND_RESULTS.inc({"result": "error"}, by=len(entries))
                _LOG.warning("bulk bind of %d pods (tenant %d) failed: %s",
                             len(entries), tid, e)
                continue
            except Exception as e:
                BIND_RESULTS.inc({"result": "connection"}, by=len(entries))
                _LOG.warning("bulk bind (tenant %d): API unreachable: %s",
                             tid, e)
                continue
            for (idx, _ns, pod, node), err in zip(entries, errs):
                if err is None:
                    out[idx] = True
                elif "not found" in err:
                    BIND_RESULTS.inc({"result": "gone"})
                    _LOG.debug("bind %s -> %s: pod gone", pod.key, node)
                    out[idx] = None
                else:
                    label = "conflict" if "bound" in err else "error"
                    BIND_RESULTS.inc({"result": label})
                    if label != "conflict":
                        _LOG.warning("bind %s -> %s failed: %s",
                                     pod.key, node, err)
        return out

    # ---- per-tenant status -----------------------------------------------

    def set_tenant_weight(self, tenant, blocks: int) -> None:
        """Quota knob: give a tenant ``blocks`` batch blocks per fill
        rotation (default 1)."""
        self.queue.set_weight(str(tenant), blocks)

    def fleet_sched_status(self) -> dict:
        """The per-tenant fairness figures the fleet ConfigMap and the
        ``scheduler_fleet_*`` gauges publish."""
        pending = self.queue.pending_by_tenant() \
            if isinstance(self.queue, FleetQueue) else {}
        share = dict(getattr(self.queue, "batch_share", {}) or {})
        bound: dict[str, int] = {}
        for key in (self.cache.audit_view().get("bound") or {}):
            tid, _rest = split_fleet_name(key)
            t = str(tid) if tid is not None else ""
            bound[t] = bound.get(t, 0) + 1
        tenants = {}
        for tid in range(len(self.tenant_clients)):
            t = str(tid)
            tenants[t] = {
                "pending": pending.get(t, 0),
                "bound": bound.get(t, 0),
                "batchShare": share.get(t, 0),
                "weight": self.queue._weights.get(t, 1)
                if isinstance(self.queue, FleetQueue) else 1,
            }
            FLEET_PENDING.set(pending.get(t, 0), {"tenant": t})
            FLEET_BATCH_SHARE.set(share.get(t, 0), {"tenant": t})
        return {"tenants": len(self.tenant_clients),
                "identity": self.identity,
                "tenant": tenants,
                "updated": time.time()}

    def publish_status(self) -> None:
        super().publish_status()
        from kubernetes_tpu.utils.configmap import upsert_configmap
        with self._fleet_status_lock:
            doc = {"fleetSched": json.dumps(self.fleet_sched_status())}
            for client in self.tenant_clients:
                upsert_configmap(client, self.status_namespace,
                                 FLEET_SCHED_CONFIGMAP, doc,
                                 site="publish_status")
