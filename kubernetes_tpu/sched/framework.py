"""Framework extension points — out-of-tree plugins without forking.

Reference: ``pkg/scheduler/framework/`` (``Registry`` in runtime/registry.go,
the ``Plugin`` interfaces in interface.go, ``NewFramework``'s out-of-tree
registry merge in scheduler.go). Upstream extension points map here as:

  Filter / Score        TensorPlugin — TRACEABLE functions over the encoded
                        (ClusterTensors, PodBatch) that run INSIDE the jitted
                        gang program: a filter returns a [P,N] mask ANDed
                        into feasibility, a score returns raw [P,N] merged
                        through the shared normalize/weight pipeline. This
                        is the TPU-native plugin ABI: you extend the device
                        program, not a Go callback chain.
  Permit / PreBind /    LifecyclePlugin — host-side hooks on the binding
  PostBind / Unreserve  cycle (waiting-pod gate, pre-bind side effects with
                        rollback, post-bind notification), exactly where
                        volume binding and DRA allocation already sit.

Profiles opt in by plugin name (``Profile.out_of_tree``); unlisted profiles
run every registered plugin, mirroring the default-enablement of
out-of-tree registries compiled into upstream schedulers.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Optional

_LOG = logging.getLogger(__name__)

# permit verdicts (framework.Code)
ALLOW, DENY, WAIT = "allow", "deny", "wait"


@dataclass(frozen=True)
class TensorPlugin:
    """A Filter and/or Score extension compiled into the device program.

    ``filter_fn(ct, pb, topo_keys) -> bool [P,N]`` — False vetoes the node.
    ``score_fn(ct, pb, topo_keys) -> float32 [P,N]`` raw scores, merged via
    ``normalize`` ("minmax" | "default" | "default_reverse") and ``weight``
    like any in-tree score plugin. Functions MUST be traceable (jax.numpy,
    no Python control flow on values) — they are jitted with the step.
    """

    name: str
    filter_fn: Optional[Callable] = None
    score_fn: Optional[Callable] = None
    normalize: str = "minmax"
    weight: float = 1.0


@dataclass(frozen=True)
class LifecyclePlugin:
    """Host-side binding-cycle hooks.

    ``permit(pod, node_name) -> "allow" | "deny" | ("wait", seconds)``
    ``pre_bind(pod, node_name) -> bool`` — False aborts the bind.
    ``post_bind(pod, node_name)`` — notification after a successful bind.
    ``unreserve(pod, node_name)`` — rollback when the cycle fails after
    this plugin's pre_bind succeeded (or permit allowed).
    """

    name: str
    permit: Optional[Callable] = None
    pre_bind: Optional[Callable] = None
    post_bind: Optional[Callable] = None
    unreserve: Optional[Callable] = None


class Registry:
    """Out-of-tree plugin registry (runtime.Registry analog)."""

    def __init__(self):
        self._tensor: dict[str, TensorPlugin] = {}
        self._lifecycle: dict[str, LifecyclePlugin] = {}
        self._lock = threading.Lock()

    def register(self, plugin) -> "Registry":
        with self._lock:
            if isinstance(plugin, TensorPlugin):
                from kubernetes_tpu.config.types import (
                    ALL_FILTER_PLUGINS,
                    ALL_SCORE_PLUGINS,
                )
                if (plugin.name in ALL_FILTER_PLUGINS
                        or plugin.name in ALL_SCORE_PLUGINS):
                    # an in-tree name would silently shadow or double-count
                    # in the shared weight map (combined_score keys by name)
                    raise ValueError(
                        f"{plugin.name!r} is an in-tree plugin name")
                if plugin.name in self._tensor:
                    raise ValueError(f"tensor plugin {plugin.name!r} already "
                                     "registered")
                self._tensor[plugin.name] = plugin
            elif isinstance(plugin, LifecyclePlugin):
                if plugin.name in self._lifecycle:
                    raise ValueError(f"lifecycle plugin {plugin.name!r} "
                                     "already registered")
                self._lifecycle[plugin.name] = plugin
            else:
                raise TypeError(f"unknown plugin type {type(plugin)!r}")
        return self

    def tensor_plugins(self, enabled: Optional[set] = None) -> tuple:
        """-> static tuple for the jit (order-stable by name)."""
        with self._lock:
            return tuple(p for n, p in sorted(self._tensor.items())
                         if enabled is None or n in enabled)

    def lifecycle_plugins(self, enabled: Optional[set] = None) -> tuple:
        with self._lock:
            return tuple(p for n, p in sorted(self._lifecycle.items())
                         if enabled is None or n in enabled)


def run_permit(plugins: tuple, pod, node_name: str,
               max_wait_s: float = 30.0) -> tuple[bool, list]:
    """Permit phase: every plugin must allow. "wait" polls the plugin until
    it answers allow/deny or the timeout lapses (WaitingPod analog, polled
    rather than callback-driven). -> (ok, plugins that ALLOWED — they join
    the unreserve rollback set if the cycle fails later)."""
    allowed: list = []
    for p in plugins:
        if p.permit is None:
            continue
        deadline = time.time() + max_wait_s
        while True:
            verdict = p.permit(pod, node_name)
            if isinstance(verdict, tuple) and verdict and verdict[0] == WAIT:
                wait_s = float(verdict[1]) if len(verdict) > 1 else 0.1
                if time.time() + wait_s > deadline:
                    return False, allowed  # timed-out waits reject (upstream)
                time.sleep(min(wait_s, max(deadline - time.time(), 0)))
                continue
            if verdict == WAIT:
                if time.time() >= deadline:
                    return False, allowed
                time.sleep(0.05)
                continue
            if verdict != ALLOW:
                return False, allowed
            allowed.append(p)
            break
    return True, allowed


def run_pre_bind(plugins: tuple, pod, node_name: str) -> tuple[bool, list]:
    """-> (ok, plugins whose pre_bind succeeded — for unreserve rollback)."""
    done: list = []
    for p in plugins:
        if p.pre_bind is None:
            continue
        try:
            ok = bool(p.pre_bind(pod, node_name))
        except Exception:
            _LOG.exception("preBind plugin %r failed; aborting bind",
                           getattr(p, 'name', p))
            ok = False
        if not ok:
            return False, done
        done.append(p)
    return True, done


def run_unreserve(plugins: list, pod, node_name: str) -> None:
    for p in reversed(plugins):
        if p.unreserve is not None:
            try:
                p.unreserve(pod, node_name)
            except Exception:
                # best-effort rollback chain: later plugins still unwind
                _LOG.exception("unreserve plugin %r failed",
                               getattr(p, 'name', p))


def run_post_bind(plugins: tuple, pod, node_name: str) -> None:
    for p in plugins:
        if p.post_bind is not None:
            try:
                p.post_bind(pod, node_name)
            except Exception:
                # informational hook: the bind already landed
                _LOG.exception("postBind plugin %r failed",
                               getattr(p, 'name', p))
