"""Preemption — the PostFilter plugin (victim search + nomination).

Reference: ``pkg/scheduler/framework/plugins/defaultpreemption/
default_preemption.go`` (``SelectVictimsOnNode``) and
``framework/preemption/preemption.go`` (``Evaluator``, ``DryRunPreemption``).

Round-1 implementation simulates on the oracle (host-side): the reference's
DryRunPreemption is itself a per-node simulation loop, and preemption runs
only for pods that already failed the (fast) main cycle, so the volume is low.
A tensorized dry-run (vmap over candidate victim prefixes) is a later round's
optimization.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from kubernetes_tpu.api.policy import _matches, compute_pdb_status
from kubernetes_tpu.api.types import Node, Pod
from kubernetes_tpu.sched.oracle import OracleScheduler


@dataclass
class PreemptionResult:
    node_name: str
    victims: list[Pod]  # sorted by priority asc (evict lowest first)
    num_pdb_violations: int = 0


def _pdb_budgets(pdbs: list[dict], bound_pods: list[Pod]) -> list[tuple]:
    """-> [(pdb_ns, selector, disruptionsAllowed)] computed live."""
    out = []
    pod_dicts = [p.to_dict() for p in bound_pods]
    for pdb in pdbs or []:
        ns = (pdb.get("metadata") or {}).get("namespace", "")
        sel = (pdb.get("spec") or {}).get("selector")
        allowed = compute_pdb_status(
            pdb, [d for d in pod_dicts
                  if (d.get("metadata") or {}).get("namespace", "") == ns]
        )["disruptionsAllowed"]
        out.append((ns, sel, allowed))
    return out


def _violates(pod: Pod, budgets_used: list) -> bool:
    """True if evicting ``pod`` would exceed some covering PDB's remaining
    budget; charges the budget either way (filterPodsWithPDBViolation)."""
    violating = False
    for entry in budgets_used:
        ns, sel, allowed, used = entry
        if pod.metadata.namespace != ns:
            continue
        if not _matches(sel, pod.metadata.labels):
            continue
        if used >= allowed:
            violating = True
        entry[3] += 1
    return violating


def find_candidate(nodes: list[Node], bound_pods: list[Pod], pod: Pod,
                   pdbs: Optional[list[dict]] = None, dra=None,
                   ) -> Optional[PreemptionResult]:
    """Find the best node + minimal victim set enabling ``pod`` to schedule.

    Per node: remove lower-priority pods — PDB-unprotected ones first — until
    feasible, then reprieve (re-add highest-first while staying feasible),
    mirroring SelectVictimsOnNode's split into violating/non-violating
    victims. A budget MAY be violated as a last resort, exactly as upstream.
    Candidate selection mirrors pickOneNodeForPreemption: fewest PDB
    violations, then min highest-victim-priority, then min victim count,
    then node order.
    """
    budgets = _pdb_budgets(pdbs or [], bound_pods)
    best: Optional[tuple] = None
    for i, node in enumerate(nodes):
        found = _victims_on_node(nodes, bound_pods, pod, node, budgets, dra=dra)
        if found is None:
            continue
        victims, violations = found
        key = (violations,
               max((v.spec.priority for v in victims), default=-1),
               len(victims), i)
        if best is None or key < best[0]:
            best = (key, node.metadata.name, victims, violations)
    if best is None:
        return None
    return PreemptionResult(
        node_name=best[1],
        victims=sorted(best[2], key=lambda p: p.spec.priority),
        num_pdb_violations=best[3])


def _victims_on_node(nodes, bound_pods, pod, node, budgets, dra=None
                     ) -> Optional[tuple[list[Pod], int]]:
    on_node = [p for p in bound_pods if p.spec.node_name == node.metadata.name]
    lower = [p for p in on_node if p.spec.priority < pod.spec.priority]
    if not lower:
        return None
    # classify against fresh per-node budget accounting, then try
    # non-violating victims (priority asc) before violating ones
    used = [[ns, sel, allowed, 0] for (ns, sel, allowed) in budgets]
    flagged = [(p, _violates(p, used))
               for p in sorted(lower, key=lambda p: p.spec.priority)]
    ordered = ([p for p, v in flagged if not v]
               + [p for p, v in flagged if v])
    violating_uids = {p.metadata.uid for p, v in flagged if v}
    ni = next(i for i, n in enumerate(nodes) if n.metadata.name == node.metadata.name)

    def feasible_without(removed: set[str]) -> bool:
        remaining = [p for p in bound_pods if p.metadata.uid not in removed]
        # the dra catalog keeps device demand/capacity visible to the
        # what-if feasibility check (else victimless device shortages
        # would look solvable by evicting unrelated pods)
        orc = OracleScheduler(nodes, remaining, dra=dra)
        mask, _ = orc.feasible(pod)
        return bool(mask[ni])

    removed: set[str] = set()
    victims: list[Pod] = []
    ok = False
    for v in ordered:
        removed.add(v.metadata.uid)
        victims.append(v)
        if feasible_without(removed):
            ok = True
            break
    if not ok:
        return None
    # Reprieve: re-add victims that aren't actually needed — PDB-violating
    # candidates first (so budgets are preserved whenever possible), then by
    # priority desc, mirroring SelectVictimsOnNode's two reprieve passes.
    for v in sorted(victims,
                    key=lambda p: (p.metadata.uid not in violating_uids,
                                   -p.spec.priority)):
        trial = removed - {v.metadata.uid}
        if feasible_without(trial):
            removed = trial
            victims = [p for p in victims if p.metadata.uid != v.metadata.uid]
    violations = sum(1 for v in victims if v.metadata.uid in violating_uids)
    return victims, violations
