"""Preemption — the PostFilter plugin (victim search + nomination).

Reference: ``pkg/scheduler/framework/plugins/defaultpreemption/
default_preemption.go`` (``SelectVictimsOnNode``) and
``framework/preemption/preemption.go`` (``Evaluator``, ``DryRunPreemption``).

Round-1 implementation simulates on the oracle (host-side): the reference's
DryRunPreemption is itself a per-node simulation loop, and preemption runs
only for pods that already failed the (fast) main cycle, so the volume is low.
A tensorized dry-run (vmap over candidate victim prefixes) is a later round's
optimization.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from kubernetes_tpu.api.types import Node, Pod
from kubernetes_tpu.sched.oracle import OracleScheduler


@dataclass
class PreemptionResult:
    node_name: str
    victims: list[Pod]  # sorted by priority asc (evict lowest first)


def find_candidate(nodes: list[Node], bound_pods: list[Pod], pod: Pod,
                   ) -> Optional[PreemptionResult]:
    """Find the best node + minimal victim set enabling ``pod`` to schedule.

    Per node: remove lower-priority pods lowest-first until feasible, then
    reprieve (re-add highest-first while staying feasible) — mirrors
    SelectVictimsOnNode. Candidate selection mirrors pickOneNodeForPreemption:
    min highest-victim-priority, then min victim count, then node order.
    """
    best: Optional[tuple] = None
    for i, node in enumerate(nodes):
        victims = _victims_on_node(nodes, bound_pods, pod, node)
        if victims is None:
            continue
        key = (max((v.spec.priority for v in victims), default=-1), len(victims), i)
        if best is None or key < best[0]:
            best = (key, node.metadata.name, victims)
    if best is None:
        return None
    return PreemptionResult(node_name=best[1],
                            victims=sorted(best[2], key=lambda p: p.spec.priority))


def _victims_on_node(nodes, bound_pods, pod, node) -> Optional[list[Pod]]:
    on_node = [p for p in bound_pods if p.spec.node_name == node.metadata.name]
    lower = sorted([p for p in on_node if p.spec.priority < pod.spec.priority],
                   key=lambda p: p.spec.priority)
    if not lower:
        return None
    ni = next(i for i, n in enumerate(nodes) if n.metadata.name == node.metadata.name)

    def feasible_without(removed: set[str]) -> bool:
        remaining = [p for p in bound_pods if p.metadata.uid not in removed]
        orc = OracleScheduler(nodes, remaining)
        mask, _ = orc.feasible(pod)
        return bool(mask[ni])

    removed: set[str] = set()
    victims: list[Pod] = []
    ok = False
    for v in lower:
        removed.add(v.metadata.uid)
        victims.append(v)
        if feasible_without(removed):
            ok = True
            break
    if not ok:
        return None
    # Reprieve: re-add highest-priority victims that aren't actually needed.
    for v in sorted(victims, key=lambda p: -p.spec.priority):
        trial = removed - {v.metadata.uid}
        if feasible_without(trial):
            removed = trial
            victims = [p for p in victims if p.metadata.uid != v.metadata.uid]
    return victims
