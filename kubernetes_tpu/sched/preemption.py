"""Preemption — the PostFilter plugin (victim search + nomination).

Reference: ``pkg/scheduler/framework/plugins/defaultpreemption/
default_preemption.go`` (``SelectVictimsOnNode``) and
``framework/preemption/preemption.go`` (``Evaluator``, ``DryRunPreemption``).

Two paths:

``find_candidate``          the exact serial simulation (per node: evict
                            lower-priority pods until feasible, reprieve,
                            pickOneNode) — the parity reference.
``find_candidate_tensor``   the TPU path: ops/preemption.py runs the whole
                            N×V victim dry-run as ONE device program
                            (prefix-sum capacity release), the host exactly
                            verifies + reprieves only the ranked winners.
                            Falls back to the exact scan whenever the device
                            narrowing can't be trusted (relational/port/
                            volume-driven failures).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from kubernetes_tpu.api.policy import _matches, compute_pdb_status
from kubernetes_tpu.api.types import Node, Pod
from kubernetes_tpu.sched.oracle import OracleScheduler


@dataclass
class PreemptionResult:
    node_name: str
    victims: list[Pod]  # sorted by priority asc (evict lowest first)
    num_pdb_violations: int = 0


def _pdb_budgets(pdbs: list[dict], bound_pods: list[Pod]) -> list[tuple]:
    """-> [(pdb_ns, selector, disruptionsAllowed)] computed live."""
    out = []
    pod_dicts = [p.to_dict() for p in bound_pods]
    for pdb in pdbs or []:
        ns = (pdb.get("metadata") or {}).get("namespace", "")
        sel = (pdb.get("spec") or {}).get("selector")
        allowed = compute_pdb_status(
            pdb, [d for d in pod_dicts
                  if (d.get("metadata") or {}).get("namespace", "") == ns]
        )["disruptionsAllowed"]
        out.append((ns, sel, allowed))
    return out


def _violates(pod: Pod, budgets_used: list) -> bool:
    """True if evicting ``pod`` would exceed some covering PDB's remaining
    budget; charges the budget either way (filterPodsWithPDBViolation)."""
    violating = False
    for entry in budgets_used:
        ns, sel, allowed, used = entry
        if pod.metadata.namespace != ns:
            continue
        if not _matches(sel, pod.metadata.labels):
            continue
        if used >= allowed:
            violating = True
        entry[3] += 1
    return violating


def find_candidate(nodes: list[Node], bound_pods: list[Pod], pod: Pod,
                   pdbs: Optional[list[dict]] = None, dra=None,
                   ) -> Optional[PreemptionResult]:
    """Find the best node + minimal victim set enabling ``pod`` to schedule.

    Per node: remove lower-priority pods — PDB-unprotected ones first — until
    feasible, then reprieve (re-add highest-first while staying feasible),
    mirroring SelectVictimsOnNode's split into violating/non-violating
    victims. A budget MAY be violated as a last resort, exactly as upstream.
    Candidate selection mirrors pickOneNodeForPreemption: fewest PDB
    violations, then min highest-victim-priority, then min victim count,
    then node order.
    """
    budgets = _pdb_budgets(pdbs or [], bound_pods)
    # one shared simulation, mutated and restored per node trial — building
    # a fresh oracle per candidate node is O(nodes x bound) each
    orc = OracleScheduler(nodes, bound_pods, dra=dra)
    best: Optional[tuple] = None
    for i, node in enumerate(nodes):
        found = _victims_on_node(nodes, bound_pods, pod, node, budgets,
                                 dra=dra, orc=orc)
        if found is None:
            continue
        victims, violations = found
        key = (violations,
               max((v.spec.priority for v in victims), default=-1),
               len(victims), i)
        if best is None or key < best[0]:
            best = (key, node.metadata.name, victims, violations)
    if best is None:
        return None
    return PreemptionResult(
        node_name=best[1],
        victims=sorted(best[2], key=lambda p: p.spec.priority),
        num_pdb_violations=best[3])


def find_candidate_tensor(nodes: list[Node], bound_pods: list[Pod], pod: Pod,
                          pdbs: Optional[list[dict]] = None, dra=None,
                          verify_limit: int = 8
                          ) -> Optional[PreemptionResult]:
    """Device-narrowed preemption: rank (node, victim-count) candidates with
    one [N,V+1] dry-run program, then exactly verify + reprieve the winners
    host-side. Sound by construction (every returned result passed the full
    serial check); falls back to the exact scan when the failure could be
    relational/port/volume-driven — i.e. when some node looks feasible with
    ZERO evictions resource-wise (so something the dry-run doesn't model
    blocked the main cycle), or when the device path errors."""
    from kubernetes_tpu.ops.preemption import dry_run_candidates
    budgets = _pdb_budgets(pdbs or [], bound_pods)
    try:
        cands, zero_evict = dry_run_candidates(nodes, bound_pods, pod,
                                               budgets, dra=dra)
    except Exception:
        return find_candidate(nodes, bound_pods, pod, pdbs=pdbs, dra=dra)
    if zero_evict:
        # some node fits without evicting anyone: the main-cycle failure was
        # relational/ports/volumes, which the dry-run doesn't model
        return find_candidate(nodes, bound_pods, pod, pdbs=pdbs, dra=dra)
    if not cands:
        return None  # no node becomes resource-feasible by evicting
    orc = OracleScheduler(nodes, bound_pods, dra=dra)
    for _key, ni, _k in cands[:verify_limit]:
        found = _victims_on_node(nodes, bound_pods, pod, nodes[ni], budgets,
                                 dra=dra, orc=orc)
        if found is not None:
            victims, violations = found
            return PreemptionResult(
                node_name=nodes[ni].metadata.name,
                victims=sorted(victims, key=lambda p: p.spec.priority),
                num_pdb_violations=violations)
    # ranked candidates failed exact verification (relational terms the
    # dry-run doesn't model): the serial scan is the source of truth
    return find_candidate(nodes, bound_pods, pod, pdbs=pdbs, dra=dra)


def _victims_on_node(nodes, bound_pods, pod, node, budgets, dra=None,
                     orc: Optional[OracleScheduler] = None
                     ) -> Optional[tuple[list[Pod], int]]:
    on_node = [p for p in bound_pods if p.spec.node_name == node.metadata.name]
    lower = [p for p in on_node if p.spec.priority < pod.spec.priority]
    if not lower:
        return None
    # classify against fresh per-node budget accounting, then try
    # non-violating victims (priority asc) before violating ones
    used = [[ns, sel, allowed, 0] for (ns, sel, allowed) in budgets]
    flagged = [(p, _violates(p, used))
               for p in sorted(lower, key=lambda p: p.spec.priority)]
    ordered = ([p for p, v in flagged if not v]
               + [p for p, v in flagged if v])
    violating_uids = {p.metadata.uid for p, v in flagged if v}
    ni = next(i for i, n in enumerate(nodes) if n.metadata.name == node.metadata.name)

    # One oracle, mutated incrementally and RESTORED before returning (so a
    # caller-shared instance survives many node trials): the old per-probe
    # rebuild was O(nodes x bound) per candidate victim, which dominated
    # preemption at fleet scale; remove/restore are O(node) and the
    # single-node re-filter is what DryRunPreemption's per-node simulation
    # does. The dra catalog keeps device demand/capacity visible to the
    # what-if check (else victimless device shortages would look solvable
    # by evicting unrelated pods).
    if orc is None:
        orc = OracleScheduler(nodes, bound_pods, dra=dra)
    removed_now: list[Pod] = []
    try:
        victims: list[Pod] = []
        ok = False
        for v in ordered:
            orc.remove_bound(v)
            removed_now.append(v)
            victims.append(v)
            if orc.feasible_one(pod, ni):
                ok = True
                break
        if not ok:
            return None
        # Reprieve: re-add victims that aren't actually needed —
        # PDB-violating candidates first (so budgets are preserved whenever
        # possible), then by priority desc, mirroring SelectVictimsOnNode's
        # two reprieve passes.
        for v in sorted(victims,
                        key=lambda p: (p.metadata.uid not in violating_uids,
                                       -p.spec.priority)):
            orc.restore_bound(v)
            removed_now.remove(v)
            if orc.feasible_one(pod, ni):
                victims = [p for p in victims
                           if p.metadata.uid != v.metadata.uid]
            else:
                orc.remove_bound(v)  # still needed
                removed_now.append(v)
        violations = sum(1 for v in victims if v.metadata.uid in violating_uids)
        return victims, violations
    finally:
        for v in removed_now:
            orc.restore_bound(v)
