"""Preemption — the PostFilter plugin (victim search + nomination).

Reference: ``pkg/scheduler/framework/plugins/defaultpreemption/
default_preemption.go`` (``SelectVictimsOnNode``) and
``framework/preemption/preemption.go`` (``Evaluator``, ``DryRunPreemption``).

Two paths:

``find_candidate``          the exact serial simulation (per node: evict
                            lower-priority pods until feasible, reprieve,
                            pickOneNode) — the parity reference.
``find_candidate_tensor``   the TPU path: ops/preemption.py runs the whole
                            N×V victim dry-run as ONE device program
                            (prefix-sum capacity release), the host exactly
                            verifies + reprieves only the ranked winners.
                            Falls back to the exact scan whenever the device
                            narrowing can't be trusted (relational/port/
                            volume-driven failures).
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Optional

_LOG = logging.getLogger(__name__)

from kubernetes_tpu.api.policy import _matches, compute_pdb_status
from kubernetes_tpu.api.types import Node, Pod
from kubernetes_tpu.sched.oracle import OracleScheduler


@dataclass
class PreemptionResult:
    node_name: str
    victims: list[Pod]  # sorted by priority asc (evict lowest first)
    num_pdb_violations: int = 0


def _pdb_budgets(pdbs: list[dict], bound_pods: list[Pod]) -> list[tuple]:
    """-> [(pdb_ns, selector, disruptionsAllowed)] computed live."""
    out = []
    pod_dicts = [p.to_dict() for p in bound_pods]
    for pdb in pdbs or []:
        ns = (pdb.get("metadata") or {}).get("namespace", "")
        sel = (pdb.get("spec") or {}).get("selector")
        allowed = compute_pdb_status(
            pdb, [d for d in pod_dicts
                  if (d.get("metadata") or {}).get("namespace", "") == ns]
        )["disruptionsAllowed"]
        out.append((ns, sel, allowed))
    return out


def _violates(pod: Pod, budgets_used: list) -> bool:
    """True if evicting ``pod`` would exceed some covering PDB's remaining
    budget; charges the budget either way (filterPodsWithPDBViolation)."""
    violating = False
    for entry in budgets_used:
        ns, sel, allowed, used = entry
        if pod.metadata.namespace != ns:
            continue
        if not _matches(sel, pod.metadata.labels):
            continue
        if used >= allowed:
            violating = True
        entry[3] += 1
    return violating


def find_candidate(nodes: list[Node], bound_pods: list[Pod], pod: Pod,
                   pdbs: Optional[list[dict]] = None, dra=None,
                   orc: Optional[OracleScheduler] = None,
                   budgets: Optional[list] = None,
                   ) -> Optional[PreemptionResult]:
    """Find the best node + minimal victim set enabling ``pod`` to schedule.

    Per node: remove lower-priority pods — PDB-unprotected ones first — until
    feasible, then reprieve (re-add highest-first while staying feasible),
    mirroring SelectVictimsOnNode's split into violating/non-violating
    victims. A budget MAY be violated as a last resort, exactly as upstream.
    Candidate selection mirrors pickOneNodeForPreemption: fewest PDB
    violations, then min highest-victim-priority, then min victim count,
    then node order. ``orc``/``budgets``: a caller-maintained simulation +
    live budget accounting (the wave path threads one oracle through many
    preemptors instead of rebuilding O(nodes x bound) state per call).
    """
    if budgets is None:
        budgets = _pdb_budgets(pdbs or [], bound_pods)
    # one shared simulation, mutated and restored per node trial — building
    # a fresh oracle per candidate node is O(nodes x bound) each
    if orc is None:
        orc = OracleScheduler(nodes, bound_pods, dra=dra)
    best: Optional[tuple] = None
    for i, node in enumerate(nodes):
        found = _victims_on_node(nodes, bound_pods, pod, node, budgets,
                                 dra=dra, orc=orc)
        if found is None:
            continue
        victims, violations = found
        key = (violations,
               max((v.spec.priority for v in victims), default=-1),
               len(victims), i)
        if best is None or key < best[0]:
            best = (key, node.metadata.name, victims, violations)
    if best is None:
        return None
    return PreemptionResult(
        node_name=best[1],
        victims=sorted(best[2], key=lambda p: p.spec.priority),
        num_pdb_violations=best[3])


def find_candidate_tensor(nodes: list[Node], bound_pods: list[Pod], pod: Pod,
                          pdbs: Optional[list[dict]] = None, dra=None,
                          verify_limit: int = 8
                          ) -> Optional[PreemptionResult]:
    """Device-narrowed preemption: rank (node, victim-count) candidates with
    one [N,V+1] dry-run program, then exactly verify + reprieve the winners
    host-side. Sound by construction (every returned result passed the full
    serial check); falls back to the exact scan when the failure could be
    relational/port/volume-driven — i.e. when some node looks feasible with
    ZERO evictions resource-wise (so something the dry-run doesn't model
    blocked the main cycle), or when the device path errors."""
    from kubernetes_tpu.ops.preemption import dry_run_candidates
    budgets = _pdb_budgets(pdbs or [], bound_pods)
    try:
        cands, zero_evict = dry_run_candidates(nodes, bound_pods, pod,
                                               budgets, dra=dra)
    except Exception:
        _LOG.exception("preemption dry-run device program failed; "
                       "degrading to the exact host scan")
        return find_candidate(nodes, bound_pods, pod, pdbs=pdbs, dra=dra)
    if zero_evict:
        # some node fits without evicting anyone: the main-cycle failure was
        # relational/ports/volumes, which the dry-run doesn't model
        return find_candidate(nodes, bound_pods, pod, pdbs=pdbs, dra=dra)
    if not cands:
        return None  # no node becomes resource-feasible by evicting
    orc = OracleScheduler(nodes, bound_pods, dra=dra)
    # Exactly evaluate EVERY candidate within the verify budget and re-rank
    # by the exact post-reprieve pickOneNode key: the device key uses
    # pre-reprieve estimates, which can rank a different node first than
    # the reference's pickOneNodeForPreemption would.
    best: Optional[tuple] = None
    for _key, ni, _k in cands[:verify_limit]:
        found = _victims_on_node(nodes, bound_pods, pod, nodes[ni], budgets,
                                 dra=dra, orc=orc)
        if found is None:
            continue
        victims, violations = found
        key = (violations,
               max((v.spec.priority for v in victims), default=-1),
               len(victims), ni)
        if best is None or key < best[0]:
            best = (key, ni, victims, violations)
    if best is not None:
        _key, ni, victims, violations = best
        return PreemptionResult(
            node_name=nodes[ni].metadata.name,
            victims=sorted(victims, key=lambda p: p.spec.priority),
            num_pdb_violations=violations)
    # ranked candidates failed exact verification (relational terms the
    # dry-run doesn't model): the serial scan is the source of truth
    return find_candidate(nodes, bound_pods, pod, pdbs=pdbs, dra=dra)


def _charge_budgets(budgets: list, victim: Pod) -> None:
    """Evicting ``victim`` consumes one disruption from every covering PDB —
    live accounting threaded across a wave (may go negative: a budget
    violated as a last resort stays violated for later preemptors)."""
    for entry in budgets:
        ns, sel, _allowed = entry[0], entry[1], entry[2]
        if victim.metadata.namespace == ns and _matches(
                sel, victim.metadata.labels):
            entry[2] -= 1


# The victim-INDEPENDENT filter set: evicting pods can never change these
# verdicts (ports/volumes/relational CAN change, and are settled by exact
# host verification instead). One definition, shared by the wave's own
# encoder path and the scheduler's resident-encoding path.
STATIC_FILTERS = frozenset({"NodeUnschedulable", "NodeName", "NodeAffinity",
                            "TaintToleration"})


_STATIC_FILTERS_JIT = None


def _static_filters_program(ct, pb):
    """One COMPILED program for the static filter AND — eager run_filters
    dispatches dozens of individual ops, which on remote-attached TPUs is
    dozens of ~100ms round trips PER CALL (measured 33s/wave at 128x5000;
    jitted: one dispatch)."""
    global _STATIC_FILTERS_JIT
    if _STATIC_FILTERS_JIT is None:
        import jax
        from functools import partial
        from kubernetes_tpu.ops.filters import run_filters
        _STATIC_FILTERS_JIT = jax.jit(
            partial(run_filters, enabled=STATIC_FILTERS))
    return _STATIC_FILTERS_JIT(ct, pb)


def tensor_static_masks(nodes, preemptors, ct=None, meta=None,
                        bound_pods=None, encode_pods=None,
                        min_p: int = 1, mesh=None, pre_staged: bool = False,
                        node_rows=None) -> "np.ndarray":
    """[Q,N] victim-independent feasibility via the encoded filter masks —
    ONE device program instead of Q x N host-side oracle probes, which
    dominated wave setup at fleet scale. Pass an already-encoded cluster
    (``ct``/``meta`` + an ``encode_pods(pods, meta, min_p=...)`` callable —
    e.g. the scheduler cache's) to skip the fresh encode. ``min_p`` pins
    the pod-batch bucket (WAVE_BUCKET) so varying wave sizes share one
    compiled program. ``mesh``: optional ("pods","nodes") Mesh — the
    [Q,N]-dominant filter program (the preempt/masks span) runs sharded
    under GSPMD, cluster split on "nodes", the preemptor batch on "pods";
    the [Q,N] result mask is O(Q*N) bools either way.

    ``pre_staged``: ``ct`` is already device-resident (the scheduler's
    drain context) — skip the per-wave device_put of the whole cluster
    encoding, which dominated wave setup once everything else was batched.
    ``node_rows``: optional row index per entry of ``nodes`` into ``ct``'s
    node axis — the resident context's row order diverges from the node
    list after node churn patches, so the columns are gathered by row
    instead of sliced positionally."""
    import jax
    import numpy as np
    if ct is None:
        from kubernetes_tpu.encode.snapshot import SnapshotEncoder
        enc = SnapshotEncoder()
        ct, meta = enc.encode_cluster(nodes, bound_pods or [])
        encode_pods = enc.encode_pods
    pb = encode_pods(preemptors, meta, min_p=min_p)
    if mesh is not None:
        from kubernetes_tpu.parallel.mesh import shard_batch, shard_cluster
        with mesh:
            ct_dev = ct if pre_staged else shard_cluster(mesh, ct)
            # ktpu-lint: disable=KTL005 -- preemption wave readback: explicit staging in / one fetch out is the wave's documented transfer contract
            mask = np.asarray(jax.device_get(_static_filters_program(
                ct_dev, shard_batch(mesh, pb))))
    else:
        # EXPLICIT staging (same cost the jit's implicit transfer paid):
        # when the wave rides the resident drain encoding, the whole
        # steady-state cycle must add zero implicit host->device
        # transfers — the transfer-guard invariant tests pin this
        ct_dev = ct if pre_staged else jax.device_put(ct)
        # ktpu-lint: disable=KTL005 -- preemption wave readback: explicit staging in / one fetch out is the wave's documented transfer contract
        mask = np.asarray(jax.device_get(_static_filters_program(
            ct_dev, jax.device_put(pb))))
    if node_rows is not None:
        return mask[:len(preemptors)][:, np.asarray(node_rows)]
    return mask[:len(preemptors), :len(nodes)]


# waves pad to this bucket so a storm's varying wave sizes share ONE
# compiled scan/mask program (warmed once); larger waves bucket upward
WAVE_BUCKET = 256


def preempt_wave(nodes: list[Node], bound_pods: list[Pod],
                 preemptors: list[Pod], pdbs: Optional[list[dict]] = None,
                 dra=None, static_masks=None, min_q: int = 1,
                 mesh=None, resident_arrays=None,
                 req_lookup=None) -> list[Optional[PreemptionResult]]:
    """Resolve a WAVE of preemptors with sequential-commit semantics in one
    device program + one shared host simulation.

    Reference behavior being batched: the failure path runs
    ``DryRunPreemption`` per pod, evicts, and the next failed pod sees the
    mutated cluster. Here the [Q,N,V+1] scan (ops/preemption.py
    ``_wave_scan``) commits each winner's victims and reservation into the
    device-side state, and the host EXACTLY verifies each proposal in wave
    order against ONE OracleScheduler that absorbs the committed evictions
    and nominee reservations — so results are identical in soundness to Q
    serial ``find_candidate_tensor`` calls, minus Q re-encodes of the
    cluster and Q oracle rebuilds (the 0.67s/preemptor host tax VERDICT r3
    flagged).

    ``resident_arrays``/``req_lookup``: the scheduler's resident-context
    fast path (ops/preemption.py dry_run_wave) — per-wave cluster totals
    read back from the device-resident drain encoding and per-victim
    request vectors served from its fold ledger, instead of re-encoding
    every bound pod per wave.

    Returns one ``PreemptionResult | None`` per preemptor, in order."""
    import numpy as np
    from kubernetes_tpu.ops.preemption import dry_run_wave
    if not preemptors:
        return []
    budgets = _pdb_budgets(pdbs or [], bound_pods)
    if static_masks is None and len(preemptors) * len(nodes) > (1 << 14):
        try:
            static_masks = tensor_static_masks(nodes, preemptors,
                                               bound_pods=bound_pods,
                                               min_p=min_q, mesh=mesh)
        except Exception:
            _LOG.exception("tensor static masks failed; using host helper")
            static_masks = None  # host helper path inside dry_run_wave
    try:
        proposals = dry_run_wave(nodes, bound_pods, preemptors, budgets,
                                 dra=dra, static_masks=static_masks,
                                 min_q=min_q,
                                 resident_arrays=resident_arrays,
                                 req_lookup=req_lookup)
    except Exception:
        # every preemptor degrades to the serial exact scan — correct but
        # ~three orders slower; never let that happen silently
        _LOG.exception("preemption wave device program failed; "
                       "degrading %d preemptors to the exact host scan",
                       len(preemptors))
        proposals = ["zero_evict"] * len(preemptors)

    import dataclasses
    orc = OracleScheduler(nodes, bound_pods, dra=dra)
    live = list(bound_pods)
    budgets_live = [[ns, sel, allowed] for (ns, sel, allowed) in budgets]
    results: list[Optional[PreemptionResult]] = []
    # Drift accounting: a host REPRIEVE evicts fewer victims than the device
    # committed, leaving the device state only OPTIMISTIC about capacity —
    # a device "no" stays trustworthy. Anything that makes the device state
    # PESSIMISTIC — a phantom commit the host rejected outright, a fallback
    # commit the device never saw, a different node chosen by the exact
    # re-rank, or the host evicting pods outside the device's set — flips
    # ``drifted`` and later device "no"s are re-checked exactly.
    drifted = False
    for pod, prop in zip(preemptors, proposals):
        res: Optional[PreemptionResult] = None
        via_fallback = False
        dev_victims = None
        snap = [tuple(b) for b in budgets_live]
        if prop is None and not drifted:
            # no resource-feasible eviction set exists device-side; since
            # evictions only ever free resources and the device state is
            # not pessimistic, the exact path cannot succeed either
            results.append(None)
            continue
        if prop == "zero_evict" or prop is None:
            res = find_candidate(nodes, live, pod, dra=dra, orc=orc,
                                 budgets=snap)
            via_fallback = True
        else:
            cand_idxs, dev_vs = prop
            dev_victims = {v.metadata.uid for v in dev_vs}
            # exactly verify the device's K-best candidates and re-rank by
            # the exact post-reprieve pickOneNode key (mirrors
            # find_candidate_tensor's verify_limit pass)
            best: Optional[tuple] = None
            for ni in cand_idxs:
                found = _victims_on_node(nodes, live, pod, nodes[ni], snap,
                                         dra=dra, orc=orc)
                if found is None:
                    continue
                victims, violations = found
                key = (violations,
                       max((v.spec.priority for v in victims), default=-1),
                       len(victims), ni)
                if best is None or key < best[0]:
                    best = (key, ni, victims, violations)
            if best is not None:
                _key, ni, victims, violations = best
                res = PreemptionResult(
                    node_name=nodes[ni].metadata.name,
                    victims=sorted(victims, key=lambda p: p.spec.priority),
                    num_pdb_violations=violations)
            else:
                # every ranked candidate failed exact verification
                # (relational terms, or drift from earlier commits)
                res = find_candidate(nodes, live, pod, dra=dra, orc=orc,
                                     budgets=snap)
                via_fallback = True
        # drift bookkeeping (device committed on its TOP candidate)
        if dev_victims is not None:
            if res is None:
                drifted = True  # phantom device commit, host found nothing
            else:
                host_victims = {v.metadata.uid for v in res.victims}
                dev_node = nodes[prop[0][0]].metadata.name
                if (via_fallback or res.node_name != dev_node
                        or not host_victims <= dev_victims):
                    drifted = True
        elif res is not None:
            drifted = True  # fallback commit the device never saw
        if res is not None:
            # commit: evictions + the nominee's reservation become the
            # state every later preemptor is verified against
            evicted = {v.metadata.uid for v in res.victims}
            for v in res.victims:
                orc.remove_bound(v)
                _charge_budgets(budgets_live, v)
            live = [p for p in live if p.metadata.uid not in evicted]
            nominee = dataclasses.replace(
                pod, spec=dataclasses.replace(pod.spec,
                                              node_name=res.node_name))
            orc.restore_bound(nominee)
            live.append(nominee)
        results.append(res)
    return results


def _victims_on_node(nodes, bound_pods, pod, node, budgets, dra=None,
                     orc: Optional[OracleScheduler] = None
                     ) -> Optional[tuple[list[Pod], int]]:
    on_node = [p for p in bound_pods if p.spec.node_name == node.metadata.name]
    lower = [p for p in on_node if p.spec.priority < pod.spec.priority]
    if not lower:
        return None
    # classify against fresh per-node budget accounting, then try
    # non-violating victims (priority asc) before violating ones
    used = [[ns, sel, allowed, 0] for (ns, sel, allowed) in budgets]
    flagged = [(p, _violates(p, used))
               for p in sorted(lower, key=lambda p: p.spec.priority)]
    ordered = ([p for p, v in flagged if not v]
               + [p for p, v in flagged if v])
    violating_uids = {p.metadata.uid for p, v in flagged if v}
    ni = next(i for i, n in enumerate(nodes) if n.metadata.name == node.metadata.name)

    # One oracle, mutated incrementally and RESTORED before returning (so a
    # caller-shared instance survives many node trials): the old per-probe
    # rebuild was O(nodes x bound) per candidate victim, which dominated
    # preemption at fleet scale; remove/restore are O(node) and the
    # single-node re-filter is what DryRunPreemption's per-node simulation
    # does. The dra catalog keeps device demand/capacity visible to the
    # what-if check (else victimless device shortages would look solvable
    # by evicting unrelated pods).
    if orc is None:
        orc = OracleScheduler(nodes, bound_pods, dra=dra)
    removed_now: list[Pod] = []
    try:
        victims: list[Pod] = []
        ok = False
        for v in ordered:
            orc.remove_bound(v)
            removed_now.append(v)
            victims.append(v)
            if orc.feasible_one(pod, ni):
                ok = True
                break
        if not ok:
            return None
        # Reprieve: re-add victims that aren't actually needed —
        # PDB-violating candidates first (so budgets are preserved whenever
        # possible), then by priority desc, mirroring SelectVictimsOnNode's
        # two reprieve passes.
        for v in sorted(victims,
                        key=lambda p: (p.metadata.uid not in violating_uids,
                                       -p.spec.priority)):
            orc.restore_bound(v)
            removed_now.remove(v)
            if orc.feasible_one(pod, ni):
                victims = [p for p in victims
                           if p.metadata.uid != v.metadata.uid]
            else:
                orc.remove_bound(v)  # still needed
                removed_now.append(v)
        violations = sum(1 for v in victims if v.metadata.uid in violating_uids)
        return victims, violations
    finally:
        for v in removed_now:
            orc.restore_bound(v)
