"""Scheduler extender — delegate filter/prioritize/bind to external services.

Reference: ``pkg/scheduler/extender.go`` (``HTTPExtender``): the scheduler
POSTs JSON to configured webhook verbs during the scheduling cycle —
``ExtenderArgs`` out, ``ExtenderFilterResult``/``HostPriorityList`` back —
letting an external process veto nodes, add weighted scores, or own the
binding for pods it manages. Wire shapes mirror
``staging/src/k8s.io/kube-scheduler/extender/v1/types.go``.

TPU integration: extender calls are host-side HTTP (inherently untraceable),
so their results enter the device program as a per-batch feasibility mask
[P,N] ANDed into the filter output and a score overlay [P,N] added before
selection — the same position in the cycle as the reference's
``findNodesThatPassExtenders`` / extender prioritize contributions.
"""

from __future__ import annotations

import json
import urllib.error
import urllib.request
from dataclasses import dataclass, field
from typing import Optional

from kubernetes_tpu.api.types import Pod

# extender scores are 0..10 (extender/v1 MaxExtenderPriority); the reference
# rescales them by weight before merging with plugin scores
MAX_EXTENDER_PRIORITY = 10


@dataclass
class ExtenderConfig:
    """config Extender (kube-scheduler/config/v1 Extender)."""

    url_prefix: str
    filter_verb: str = ""          # "" = extender does not filter
    prioritize_verb: str = ""
    bind_verb: str = ""
    weight: float = 1.0
    node_cache_capable: bool = False  # send node names instead of full nodes
    ignorable: bool = False        # errors skip the extender vs fail the pod
    timeout_s: float = 5.0
    # only pods requesting at least one of these resources are sent; empty =
    # every pod (ManagedResources semantics)
    managed_resources: list[str] = field(default_factory=list)

    @classmethod
    def from_dict(cls, d: dict) -> "ExtenderConfig":
        return cls(
            url_prefix=d.get("urlPrefix", ""),
            filter_verb=d.get("filterVerb", ""),
            prioritize_verb=d.get("prioritizeVerb", ""),
            bind_verb=d.get("bindVerb", ""),
            weight=float(d.get("weight", 1)),
            node_cache_capable=bool(d.get("nodeCacheCapable", False)),
            ignorable=bool(d.get("ignorable", False)),
            timeout_s=float(d.get("httpTimeout", 5)),
            managed_resources=_parse_managed(d.get("managedResources") or []),
        )


def _parse_managed(entries: list) -> list[str]:
    """managedResources: [{"name": ...}] or bare strings; anything else is a
    config error rejected at parse time, not at scheduling time."""
    out = []
    for r in entries:
        if isinstance(r, dict):
            if "name" not in r:
                raise ValueError(f"managedResources entry missing 'name': {r}")
            out.append(str(r["name"]))
        else:
            out.append(str(r))
    return out


class ExtenderError(RuntimeError):
    pass


class HTTPExtender:
    """One configured extender endpoint (extender.go HTTPExtender)."""

    def __init__(self, cfg: ExtenderConfig):
        self.cfg = cfg

    # -- plumbing ----------------------------------------------------------

    def _post(self, verb: str, payload: dict) -> dict:
        url = self.cfg.url_prefix.rstrip("/") + "/" + verb
        req = urllib.request.Request(
            url, data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        try:
            with urllib.request.urlopen(req, timeout=self.cfg.timeout_s) as r:
                return json.loads(r.read() or b"{}")
        except (urllib.error.URLError, OSError, ValueError) as e:
            raise ExtenderError(f"extender {url}: {e}") from e

    def is_interested(self, pod: Pod) -> bool:
        """IsInterested: pods requesting none of the managed resources skip
        this extender entirely."""
        if not self.cfg.managed_resources:
            return True
        reqs = pod.resource_requests()
        return any(r in reqs for r in self.cfg.managed_resources)

    @staticmethod
    def _name(n) -> str:
        return n if isinstance(n, str) else n.metadata.name

    def _args(self, pod: Pod, nodes: list) -> dict:
        """``nodes``: Node objects (preferred) or bare names. Non-cache-
        capable extenders get FULL node objects — that mode exists for
        extenders without their own node watch (extender.go)."""
        args = {"pod": pod.to_dict()}
        if self.cfg.node_cache_capable:
            args["nodenames"] = [self._name(n) for n in nodes]
        else:
            args["nodes"] = {"items": [
                {"metadata": {"name": n}} if isinstance(n, str) else n.to_dict()
                for n in nodes]}
        return args

    # -- verbs -------------------------------------------------------------

    def filter(self, pod: Pod, nodes: list) -> list[str]:
        """-> surviving node names. Raises ExtenderError on transport failure
        AND on a result-level ``error`` — both are extender failures subject
        to the caller's ``ignorable`` policy (findNodesThatPassExtenders)."""
        result = self._post(self.cfg.filter_verb, self._args(pod, nodes))
        if result.get("error"):
            raise ExtenderError(
                f"extender {self.cfg.url_prefix}: {result['error']}")
        if result.get("nodenames") is not None:
            return list(result["nodenames"])
        items = ((result.get("nodes") or {}).get("items")) or []
        return [(n.get("metadata") or {}).get("name", "") for n in items]

    def prioritize(self, pod: Pod, nodes: list) -> dict[str, float]:
        """-> node name -> weighted score contribution."""
        result = self._post(self.cfg.prioritize_verb, self._args(pod, nodes))
        out = {}
        for hp in (result if isinstance(result, list) else
                   result.get("hostPriorityList") or []):
            out[hp.get("host", "")] = float(hp.get("score", 0)) * self.cfg.weight
        return out

    def bind(self, pod: Pod, node_name: str) -> bool:
        """ExtenderBindingArgs -> ExtenderBindingResult."""
        result = self._post(self.cfg.bind_verb, {
            "podName": pod.metadata.name,
            "podNamespace": pod.metadata.namespace,
            "podUID": pod.metadata.uid,
            "node": node_name})
        return not result.get("error")


def run_extenders(extenders: list[HTTPExtender], pods: list[Pod],
                  nodes: list):
    """Host-side extender pass for one batch. ``nodes``: Node objects (or
    bare names in tests).

    -> (mask [P,N] bool | None, scores [P,N] float32 | None,
        errors set[int]): the feasibility AND-mask and weighted score
    overlay for the device program (None when no extender applied — keeps
    the no-extender trace unchanged), plus the batch indices of pods whose
    NON-ignorable extender call failed. Those are attempt ERRORS, not
    unschedulability — the caller must requeue them without running
    preemption (the reference fails the scheduling cycle for them).
    Prioritize errors are always ignored (prioritizeNodesWithExtenders
    logs and continues). Per-pod extender chains are independent, so pods
    fan out on a thread pool — wall time is bounded by the slowest single
    chain, not the sum.
    """
    import numpy as np
    from concurrent.futures import ThreadPoolExecutor
    if not extenders:
        return None, None, set()
    node_names = [HTTPExtender._name(n) for n in nodes]
    by_name = dict(zip(node_names, nodes))
    P, N = len(pods), len(nodes)
    mask = np.ones((P, N), bool)
    scores = np.zeros((P, N), np.float32)
    idx = {n: i for i, n in enumerate(node_names)}

    def one_pod(pod):
        """-> (surviving names, {node: score}, filtered?, error?)"""
        surviving = list(node_names)
        filtered = False
        contrib: dict[str, float] = {}
        for ext in extenders:
            if not ext.is_interested(pod):
                continue
            if ext.cfg.filter_verb:
                try:
                    returned = ext.filter(pod, [by_name[n] for n in surviving])
                    seen: set = set()
                    surviving = []
                    for n in returned:
                        if n in idx and n not in seen:
                            seen.add(n)
                            surviving.append(n)
                    filtered = True
                except ExtenderError:
                    if ext.cfg.ignorable:
                        continue
                    return [], {}, False, True
            if ext.cfg.prioritize_verb:
                try:
                    got = ext.prioritize(pod, [by_name[n] for n in surviving])
                    for n, s in got.items():
                        if n in idx:
                            contrib[n] = contrib.get(n, 0.0) + s
                except ExtenderError:
                    pass  # prioritize errors never fail the pod
        return surviving, contrib, filtered, False

    with ThreadPoolExecutor(max_workers=min(16, max(P, 1))) as pool:
        results = list(pool.map(one_pod, pods))

    any_mask = any_score = False
    errors: set[int] = set()
    for p_i, (surviving, contrib, filtered, err) in enumerate(results):
        if err:
            errors.add(p_i)
            continue
        if filtered:
            any_mask = True
            row = np.zeros(N, bool)
            row[[idx[n] for n in surviving]] = True
            mask[p_i] = row
        if contrib:
            any_score = True
            for n, s in contrib.items():
                scores[p_i, idx[n]] += s
    return (mask if any_mask else None), (scores if any_score else None), errors


def extender_binder(extenders: list[HTTPExtender]):
    """-> binder(pod, node) -> bool | None: delegates to the first interested
    extender with a bindVerb; None = no extender claims it (use the default
    binder)."""
    binders = [e for e in extenders if e.cfg.bind_verb]

    def maybe_bind(pod: Pod, node_name: str):
        for ext in binders:
            if ext.is_interested(pod):
                try:
                    return ext.bind(pod, node_name)
                except ExtenderError:
                    return False
        return None
    return maybe_bind
