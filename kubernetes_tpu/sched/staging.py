"""Zero-copy steady state: pre-sharded, double-buffered batch staging.

MULTICHIP_r06 pinned the sharded ConnectedMesh regression on ONE span:
``scheduler/stage_batch`` — the per-dispatch ``device_put`` of the pod
batch stack split on "pods" grew 381 -> 1641 ms under the mesh, because
``device_put`` re-lays-out every leaf against its NamedSharding on the
scheduling thread, inside the dispatch path. SNIPPETS [1]/[3] name the fix
exactly: ship inputs already pre-partitioned to match the program's
``in_axis_resources``.

Two pieces live here:

``StagingArena``
    A background "batch-stager" thread that uploads batch K+1's host stack
    into PRE-SHARDED device buffers while batch K's drain still runs —
    one batched sharded put by default, or host-side per-shard slices +
    ``make_array_from_single_device_arrays`` assembly with KTPU_PRESPLIT=1
    (parallel/mesh.py ``presplit_stack``; zero runtime re-layout, for
    runtimes where ``device_put`` against a NamedSharding re-lays-out).
    Double-buffered: at most ``depth`` uploads in flight (the buffer being
    dispatched + the one uploading). At dispatch time
    ``Scheduler._stage_batch`` REDEEMS the ticket — a buffer swap, not a
    ``device_put``. Invalidation discipline mirrors the resident drain
    context: a mesh install/reshape (``SchedulerCache.set_mesh``) bumps the
    arena epoch and every in-flight ticket redeems to None — the caller
    falls back to the legacy inline ``device_put`` path with bit-identical
    placements (the staged copy is a faithful snapshot of the submitted
    host stack, so a DECLINED swap never loses data, only the overlap).

``ResidentShadow``
    Host mirror of the resident cluster encoding's [N,R] allocatable /
    requested totals. The preemption wave used to ``device_get`` the two
    arrays from the resident context per wave — the one remaining host
    round-trip between a drain resolve and its preemption wave. The shadow
    is maintained from data the host already touches: winner folds are
    mirrored at resolve (lazily — request vectors are computed only when a
    wave actually needs the totals), churn patches apply their host-side
    ``req_delta``/``n_alloc``/``n_reset`` arrays. With it, the steady-state
    cycle's ONLY device->host transfer is the O(P) compact winners fetch.
"""

from __future__ import annotations

import logging
import queue as queue_mod
import threading
from typing import Any, Optional

import numpy as np

_LOG = logging.getLogger(__name__)

# bounded wait for an in-flight upload at redeem time: a stuck stager
# thread must degrade to the inline path, never hang the scheduling loop
REDEEM_WAIT_S = 30.0


class StageTicket:
    """One submitted upload: done Event + result slot + validity stamps."""

    __slots__ = ("done", "staged", "error", "epoch", "mesh", "nbytes")

    def __init__(self, epoch: int, mesh):
        self.done = threading.Event()
        self.staged = None
        self.error: Optional[BaseException] = None
        self.epoch = epoch
        self.mesh = mesh
        self.nbytes = 0


def _tree_nbytes(tree) -> int:
    import jax
    return int(sum(np.asarray(l).nbytes
                   for l in jax.tree_util.tree_leaves(tree)))


class StagingArena:
    """Double-buffered pre-sharded device staging for drain batch stacks."""

    def __init__(self, depth: int = 2):
        self.depth = max(1, int(depth))
        self._lock = threading.Lock()
        self._q: "queue_mod.Queue" = queue_mod.Queue()
        self._thread: Optional[threading.Thread] = None
        self._epoch = 0    # guarded by: self._lock
        self._inflight = 0  # guarded by: self._lock
        # health counters (ktpu status + bench legs report these) — shared
        # between the stager thread, the dispatch thread, and status readers
        self.swaps = 0        # guarded by: self._lock
        self.fallbacks = 0    # guarded by: self._lock
        self.submits = 0      # guarded by: self._lock
        self.bytes_staged = 0  # guarded by: self._lock

    # ---- lifecycle -------------------------------------------------------

    def _ensure_thread(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            t = threading.Thread(target=self._loop, daemon=True,
                                 name="batch-stager")
            self._thread = t
            t.start()

    def _loop(self) -> None:
        import os
        from kubernetes_tpu.parallel.mesh import (presplit_stack,
                                                  stack_shardings)
        # KTPU_PRESPLIT=1: slice every partitioned leaf host-side and
        # assemble from per-device shards (SNIPPETS [1]/[3] — wins on
        # runtimes whose device_put re-lays-out against a NamedSharding,
        # e.g. remote-attached TPU). Default: ONE batched sharded put —
        # on backends with layout-free transfers (CPU sim) the slicing
        # overhead exceeds the savings, and the arena's real win is that
        # either variant runs HERE, off the dispatch thread.
        presplit = os.environ.get("KTPU_PRESPLIT", "0") == "1"
        while True:
            item = self._q.get()
            if item is None:  # poison pill from close()
                return
            ticket, pb_stack = item
            try:
                import jax
                if presplit:
                    staged = presplit_stack(ticket.mesh, pb_stack)
                else:
                    staged = jax.device_put(
                        pb_stack, stack_shardings(ticket.mesh, pb_stack))
                jax.block_until_ready(staged)
                ticket.nbytes = _tree_nbytes(pb_stack)
                ticket.staged = staged
            except BaseException as e:  # noqa: BLE001 — redeem reports it
                ticket.error = e
                _LOG.warning("batch staging upload failed; dispatch will "
                             "stage inline", exc_info=True)
            finally:
                # the depth slot frees when the UPLOAD finishes, not at
                # redeem: a ticket a failed cycle never redeems must not
                # pin a slot forever (two leaks would silently disable
                # the arena for the process lifetime) — its staged
                # buffers are freed by GC when the ticket ref unwinds
                with self._lock:
                    self._inflight = max(0, self._inflight - 1)
                ticket.done.set()

    def close(self) -> None:
        t = self._thread
        if t is not None:
            self._q.put(None)
            self._thread = None
            t.join(timeout=2.0)  # drains the poison pill; uploads are short

    # ---- submit / redeem -------------------------------------------------

    def submit(self, pb_stack, mesh) -> Optional[StageTicket]:
        """Enqueue a pre-sharded upload of ``pb_stack``; returns a ticket to
        redeem at dispatch, or None when the double buffer is full (caller
        stages inline — never queues unboundedly behind a slow link)."""
        if mesh is None:
            return None
        with self._lock:
            if self._inflight >= self.depth:
                return None
            self._inflight += 1
            self.submits += 1
            ticket = StageTicket(self._epoch, mesh)
        self._ensure_thread()
        self._q.put((ticket, pb_stack))
        return ticket

    def redeem(self, ticket: Optional[StageTicket], mesh,
               timeout: float = REDEEM_WAIT_S):
        """The staged device buffers, or None (caller falls back to the
        legacy inline path). Declines when the arena was invalidated since
        submit (mesh install/reshape), the upload failed, the stager thread
        died, or the bounded wait expired."""
        if ticket is None:
            return None
        try:
            deadline = timeout
            while not ticket.done.wait(min(0.25, deadline)):
                deadline -= 0.25
                t = self._thread
                if deadline <= 0 or t is None or not t.is_alive():
                    _LOG.warning("batch-stager %s; staging inline",
                                 "died" if (t is None or not t.is_alive())
                                 else f"silent for {timeout:.0f}s")
                    with self._lock:
                        self.fallbacks += 1
                    return None
            with self._lock:
                stale = (ticket.epoch != self._epoch
                         or ticket.mesh is not mesh)
                if stale or ticket.error is not None \
                        or ticket.staged is None:
                    self.fallbacks += 1
                    return None
                self.swaps += 1
                self.bytes_staged += ticket.nbytes
                swaps = self.swaps
            from kubernetes_tpu.metrics.registry import (STAGE_BUFFER_REUSE,
                                                         STAGE_BYTES)
            STAGE_BYTES.inc({"path": "arena"}, by=ticket.nbytes)
            STAGE_BUFFER_REUSE.set(swaps)
            return ticket.staged
        finally:
            ticket.staged = None  # the arena never aliases redeemed buffers

    def invalidate(self) -> None:
        """Drop every in-flight ticket's validity (mesh install/reshape):
        redeems after this fall back to the inline path, which stages
        against the CURRENT mesh — a stale-layout swap can never happen."""
        with self._lock:
            self._epoch += 1

    def stats(self) -> dict:
        with self._lock:
            return {"submits": self.submits, "swaps": self.swaps,
                    "fallbacks": self.fallbacks,
                    "bytesStaged": self.bytes_staged,
                    "inflight": self._inflight}


class ResidentShadow:
    """Host mirror of the resident encoding's [N,R] totals (int64 numpy).

    Fed from three host-side sources that are exact mirrors of what the
    device program does to the resident arrays:

    - winner folds: ``drain_step`` adds each committed pod's request row
      into ``requested`` — the resolve loop appends (pod, node row) here
      and the vectors are computed LAZILY (``catch_up``) only when a
      preemption wave actually reads the totals;
    - churn patches: ``_apply_patch`` zeroes reset rows, adds
      ``req_delta``, and rewrites ``allocatable`` rows — ``apply_patch``
      replays the same numpy arrays the patch compile produced;
    - rebuilds: a fresh shadow is cut from the host encoding that staged
      the context.

    Any exception poisons the shadow (``ok`` False) and the wave falls
    back to the device readback — drift degrades to a fetch, never to a
    wrong answer. Parity with the device arrays is pinned by test.

    Thread contract: ``fold_winners`` runs on the RESOLVER thread while
    ``catch_up``/``apply_patch``/``arrays`` run on the scheduling thread —
    an unserialized ``pending`` swap could drop a resolve's winner folds
    on the floor (and a dropped fold is exactly the silent drift the
    poison discipline exists to prevent), so every access holds the lock.
    """

    def __init__(self, allocatable, requested):
        self._lock = threading.Lock()
        self.alloc = np.asarray(allocatable).astype(np.int64).copy()  # guarded by: self._lock
        self.req = np.asarray(requested).astype(np.int64).copy()  # guarded by: self._lock
        self.pending: list[tuple[Any, int]] = []  # guarded by: self._lock
        self.ok = True  # guarded by: self._lock

    def fold_winners(self, pairs: list) -> None:
        """Record winners mirrored at resolve: [(Pod, node_row)]."""
        with self._lock:
            self.pending.extend(pairs)

    def catch_up(self, vec_fn) -> None:
        """Fold pending winners' request vectors into ``requested``.
        ``vec_fn(pod) -> [R] int vector`` on the RESIDENT resource axis
        (the same ``_request_vector`` the encode and the device fold's
        batch rows use, so the mirror is bit-consistent)."""
        with self._lock:
            if not self.pending:
                return
            pending, self.pending = self.pending, []
            try:
                for pod, row in pending:
                    self.req[row] += np.asarray(vec_fn(pod), np.int64)
            except Exception:
                self.ok = False
                _LOG.exception("resident shadow catch-up failed; waves "
                               "fall back to the device readback")

    def apply_patch(self, patch: dict) -> None:
        """Mirror ``_apply_patch``'s requested/allocatable writes.

        ORDER CONTRACT: pending winner folds must be caught up FIRST (the
        scheduler calls ``catch_up`` before this) — on device the folds
        happened in earlier dispatches, strictly before this patch, so a
        patch that resets a row the device already folded a winner into
        must zero the winner's contribution too. Applying the patch with
        folds still pending would re-add that contribution to a reused
        row afterward. Un-caught-up pending entries poison the shadow
        rather than silently mis-mirroring."""
        with self._lock:
            if self.pending:
                self.ok = False
                _LOG.error("resident shadow patch applied with %d winner "
                           "folds pending; poisoning the shadow (waves "
                           "fall back to the device readback)",
                           len(self.pending))
                return
            try:
                rows = np.asarray(patch["node_row"])
                live = rows >= 0
                if live.any():
                    idx = rows[live]
                    self.alloc[idx] = np.asarray(patch["n_alloc"])[live]
                    reset = np.asarray(patch["n_reset"], bool) & live
                    if reset.any():
                        self.req[rows[reset]] = 0
                self.req += np.asarray(patch["req_delta"])
            except Exception:
                self.ok = False
                _LOG.exception("resident shadow patch mirror failed; "
                               "waves fall back to the device readback")

    def arrays(self):
        """(allocatable, requested) or None when the shadow is poisoned or
        still behind (pending winners not yet caught up). The returned
        arrays are the live mirrors (not copies): the wave encodes them
        on the scheduling thread, the same thread every mutator runs on —
        only ``fold_winners`` is foreign, and it never touches these."""
        with self._lock:
            if not self.ok or self.pending:
                return None
            return self.alloc, self.req
