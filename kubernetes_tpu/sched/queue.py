"""Scheduling queue — three-tier activeQ / backoffQ / unschedulable map.

Reference: ``pkg/scheduler/internal/queue/scheduling_queue.go``
(``PriorityQueue``: Add, Pop, AddUnschedulableIfNotPresent,
MoveAllToActiveOrBackoffQueue). Two deliberate departures for the TPU design:

- ``pop_batch``: the gang batcher wants P pods per device step, so Pop drains
  up to ``max_batch`` pods at once (priority order preserved). The reference
  pops exactly one.
- Queueing hints are event-kind coarse (node-add/pod-delete/...) rather than
  per-plugin closures; precision hints can layer on later.
"""

from __future__ import annotations

import heapq
import itertools
import threading
import time
from dataclasses import dataclass, field
from typing import Optional

from kubernetes_tpu.api.types import Pod
from kubernetes_tpu.utils.tracing import FLIGHT

# Cluster events that can make unschedulable pods schedulable again
# (events.go ClusterEvent analog).
EVENT_NODE_ADD = "NodeAdd"
EVENT_NODE_UPDATE = "NodeUpdate"
EVENT_POD_DELETE = "PodDelete"
EVENT_POD_UPDATE = "PodUpdate"
EVENT_UNSCHEDULABLE_TIMEOUT = "UnschedulableTimeout"


@dataclass(order=True)
class _QueuedPod:
    sort_key: tuple
    pod: Pod = field(compare=False)
    attempts: int = field(default=0, compare=False)
    timestamp: float = field(default=0.0, compare=False)


class SchedulingQueue:
    """Thread-safe 3-tier queue with exponential per-pod backoff."""

    def __init__(self, backoff_initial: float = 1.0, backoff_max: float = 10.0,
                 unschedulable_timeout: float = 60.0):
        self._lock = threading.Condition()
        self._active: list[_QueuedPod] = []  # guarded by: self._lock (heap: (-priority, seq))
        self._backoff: list[tuple[float, _QueuedPod]] = []  # guarded by: self._lock (heap: (expiry, item))
        self._unschedulable: dict[str, _QueuedPod] = {}  # guarded by: self._lock
        self._keys_queued: set[str] = set()  # guarded by: self._lock
        # key -> CURRENT queued item. Deletion is lazy: delete() drops the
        # entry and consumers skip heap items that are no longer current —
        # eager deletion rebuilt the whole activeQ heap per call, which is
        # O(queue) work per binding-confirmation event (10k bound pods while
        # 10k more sit queued = O(n^2) on the watch thread).
        self._entries: dict[str, _QueuedPod] = {}  # guarded by: self._lock
        self._seq = itertools.count()
        self.backoff_initial = backoff_initial
        self.backoff_max = backoff_max
        self.unschedulable_timeout = unschedulable_timeout
        self.closed = False

    def _key(self, pod: Pod) -> str:
        return pod.key

    def _sort_key(self, pod: Pod):
        # PrioritySort: priority desc, then FIFO arrival.
        return (-pod.spec.priority, next(self._seq))

    # ---- producers -------------------------------------------------------

    def add(self, pod: Pod, attempts: int = 0):
        """New pod (or update making it schedulable): into activeQ.
        ``attempts`` carries prior attempt history through re-adds (e.g.
        scheduler restarts re-queueing parked pods) so backoff does not
        reset."""
        with self._lock:
            k = self._key(pod)
            if k in self._keys_queued:
                return
            item = _QueuedPod(self._sort_key(pod), pod, attempts=attempts,
                              timestamp=time.time())
            self._entries[k] = item
            self._keys_queued.add(k)
            if pod.spec.scheduling_gates:
                # SchedulingGates PreEnqueue: hold until gates cleared.
                self._unschedulable[k] = item
                return
            heapq.heappush(self._active, item)
            self._lock.notify_all()
        FLIGHT.record(k, "queue_add")

    def add_unschedulable(self, pod: Pod, attempts: int):
        """Failed scheduling attempt: backoffQ (will retry), mirroring
        AddUnschedulableIfNotPresent with moveRequestCycle semantics folded in."""
        with self._lock:
            k = self._key(pod)
            if k in self._keys_queued and k not in self._unschedulable:
                return
            item = _QueuedPod(self._sort_key(pod), pod, attempts=attempts,
                              timestamp=time.time())
            delay = min(self.backoff_initial * (2 ** max(attempts - 1, 0)),
                        self.backoff_max)
            self._entries[k] = item
            self._unschedulable.pop(k, None)
            heapq.heappush(self._backoff, (time.time() + delay, item))
            self._keys_queued.add(k)
            self._lock.notify_all()
        FLIGHT.record(k, "requeue", attempts=attempts)

    def park_unschedulable(self, pod: Pod, attempts: int):
        """No event expected to help soon: unschedulable map (event-driven requeue)."""
        with self._lock:
            k = self._key(pod)
            item = _QueuedPod(self._sort_key(pod), pod, attempts=attempts,
                              timestamp=time.time())
            self._entries[k] = item
            self._unschedulable[k] = item
            self._keys_queued.add(k)
        FLIGHT.record(k, "park", attempts=attempts)

    def delete(self, pod: Pod):
        self.delete_key(self._key(pod))

    def delete_key(self, k: str):
        # Lazy: drop the membership records; stale heap entries are skipped
        # by consumers when they surface (O(1) here instead of O(queue)).
        with self._lock:
            self._keys_queued.discard(k)
            self._unschedulable.pop(k, None)
            self._entries.pop(k, None)

    def _current_locked(self, item: _QueuedPod) -> bool:
        return self._entries.get(item.pod.key) is item

    def move_all_to_active_or_backoff(self, event: str):
        """Cluster event: unschedulable pods get another chance
        (MoveAllToActiveOrBackoffQueue)."""
        with self._lock:
            for k, item in list(self._unschedulable.items()):
                if item.pod.spec.scheduling_gates:
                    continue  # still gated; activate_gated handles gate removal
                del self._unschedulable[k]
                if self._current_locked(item):
                    heapq.heappush(self._active, item)
            self._lock.notify_all()

    def activate_gated(self, pod: Pod):
        """Gates removed (pod update): move from unschedulable to activeQ."""
        with self._lock:
            k = self._key(pod)
            item = self._unschedulable.pop(k, None)
            if (item is not None and not pod.spec.scheduling_gates
                    and self._current_locked(item)):
                item.pod = pod
                heapq.heappush(self._active, item)
                self._lock.notify_all()

    # ---- consumer --------------------------------------------------------

    def _flush_backoff_locked(self):
        now = time.time()
        moved = False
        while self._backoff and self._backoff[0][0] <= now:
            _, item = heapq.heappop(self._backoff)
            if self._current_locked(item):
                heapq.heappush(self._active, item)
                moved = True
        # unschedulable timeout sweep
        for k, item in list(self._unschedulable.items()):
            if (not item.pod.spec.scheduling_gates
                    and now - item.timestamp > self.unschedulable_timeout):
                del self._unschedulable[k]
                if self._current_locked(item):
                    heapq.heappush(self._active, item)
                    moved = True
        return moved

    def _active_has_current_locked(self) -> bool:
        # drop stale heap heads so waiters don't wake for deleted pods
        while self._active and not self._current_locked(self._active[0]):
            heapq.heappop(self._active)
        return bool(self._active)

    def _wait_for_work_locked(self, deadline: float) -> bool:
        """Block (under the lock) until >=1 current pod is in activeQ, the
        queue closes, or ``deadline`` passes with nothing available.
        Returns True when work is available — shared by pop_batch and the
        FleetQueue's fairness-aware override, so the wait/close semantics
        can never drift between them."""
        while not self.closed:
            self._flush_backoff_locked()
            if self._active_has_current_locked():
                return True
            timeout = min(0.05, max(deadline - time.time(), 0.01))
            self._lock.wait(timeout)
            if time.time() > deadline \
                    and not self._active_has_current_locked():
                return False
        return self._active_has_current_locked()

    def pop_batch(self, max_batch: int = 256, wait: float = 0.5
                  ) -> list[tuple[Pod, int]]:
        """Block until >=1 pod is available, then drain up to max_batch in
        priority order. Returns [(pod, attempts)]."""
        deadline = time.time() + wait
        with self._lock:
            if not self._wait_for_work_locked(deadline):
                return []
            out = []
            while self._active and len(out) < max_batch:
                item = heapq.heappop(self._active)
                if not self._current_locked(item):
                    continue  # lazily-deleted or superseded entry
                self._keys_queued.discard(item.pod.key)
                self._entries.pop(item.pod.key, None)
                out.append((item.pod, item.attempts))
            return out

    def close(self):
        with self._lock:
            self.closed = True
            self._lock.notify_all()

    def unschedulable_pods(self) -> list[Pod]:
        """Snapshot of the unschedulable map's pods — the cluster
        autoscaler's scale-up signal (the reference reads the analogous
        list through its unschedulablePods lister)."""
        with self._lock:
            return [item.pod for item in self._unschedulable.values()]

    def stats(self) -> dict[str, int]:
        with self._lock:
            nb = sum(1 for _, it in self._backoff if self._current_locked(it))
            nu = len(self._unschedulable)
            na = max(len(self._keys_queued) - nb - nu, 0)
            return {"active": na, "backoff": nb, "unschedulable": nu}
