"""Connected scheduler — informers in, bindings out.

Reference: ``cmd/kube-scheduler/app/server.go`` (Run: informers + event
handlers feeding the queue/cache, then the scheduling loop) and the event
registration in ``pkg/scheduler/eventhandlers.go``. Optionally wraps the loop
in leader election (active-passive HA, SURVEY §5).
"""

from __future__ import annotations

import logging
import threading
from typing import Optional

_LOG = logging.getLogger("kubernetes_tpu.sched.runner")

from kubernetes_tpu.api.types import Node, Pod
from kubernetes_tpu.client.clientset import ApiError
from kubernetes_tpu.client.informer import InformerFactory, meta_namespace_key
from kubernetes_tpu.client.leaderelection import LeaderElectionConfig, LeaderElector
from kubernetes_tpu.config.types import SchedulerConfiguration
from kubernetes_tpu.metrics.registry import BIND_RESULTS
from kubernetes_tpu.sched.cache import SchedulerCache
from kubernetes_tpu.sched.queue import (
    EVENT_NODE_ADD,
    EVENT_NODE_UPDATE,
    EVENT_POD_DELETE,
    SchedulingQueue,
)
from kubernetes_tpu.sched.scheduler import Scheduler
from kubernetes_tpu.store.store import ADDED, DELETED, MODIFIED

# Published like the autoscaler's cluster-autoscaler-status: one ConfigMap
# other components (and ``ktpu status``) read for the live deployment shape
# — most importantly the active device mesh.
STATUS_CONFIGMAP = "kubernetes-tpu-scheduler-status"


class SchedulerRunner:
    """Owns informers, cache, queue, scheduler; drives the loop."""

    def __init__(self, client, cfg: Optional[SchedulerConfiguration] = None,
                 identity: str = "kubernetes-tpu-scheduler", registry=None,
                 status_namespace: str = "default"):
        self.client = client
        # where publish_status writes its ConfigMap (same shape as the
        # autoscaler's status_namespace: RBAC commonly restricts writes to
        # the component's own namespace; ktpu -n <ns> status must match)
        self.status_namespace = status_namespace
        if hasattr(client, "default_user_agent"):
            client.default_user_agent("kube-scheduler")
        # GIL tuning for the connected deployment shape: informer bursts
        # (thousands of JSON decodes) and the device tunnel share one
        # interpreter; a finer switch interval caps how long either side
        # can starve the other between checks. Opt-in via env so library
        # embedders keep the interpreter default.
        import os
        import sys
        si = os.environ.get("KTPU_SWITCH_INTERVAL")
        if si:
            sys.setswitchinterval(float(si))

        self.cfg = cfg or SchedulerConfiguration()
        self.cache = SchedulerCache(assume_ttl=self.cfg.assume_ttl_s)
        self.queue = SchedulingQueue(backoff_initial=self.cfg.backoff_initial_s,
                                     backoff_max=self.cfg.backoff_max_s)
        self.scheduler = Scheduler(self.cfg, self.cache, self.queue, self._bind,
                                   registry=registry,
                                   bulk_binder=self._bind_many)
        from kubernetes_tpu.utils.events import EventRecorder
        self.scheduler.recorder = EventRecorder(client, "default-scheduler")
        self.scheduler._evict = self._evict  # preemption deletes via API
        self.factory = InformerFactory(client)
        self.identity = identity
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        # Per-leadership-term scheduling loop: a lost lease stops the loop (no
        # split-brain binding), a re-acquired one starts a fresh term instead
        # of stacking a second concurrent loop.
        self._loop_stop: Optional[threading.Event] = None
        self._loop_thread: Optional[threading.Thread] = None
        self._scheduler_names = {p.scheduler_name for p in self.cfg.profiles}

    # ---- event handlers (pkg/scheduler/eventhandlers.go analog) ----------

    def _on_pod(self, type_, obj, old):
        if type_ != DELETED:
            # Fast path for bind confirmations: a gang bind storm is one
            # MODIFIED per pod whose only news is the nodeName the cache
            # already assumed — confirm from the raw dict and skip the full
            # Pod.from_dict (a first-order cost at 10k events/s).
            spec = obj.get("spec") or {}
            nn = spec.get("nodeName")
            if nn and (obj.get("status") or {}).get("phase") \
                    not in ("Succeeded", "Failed"):
                md = obj.get("metadata") or {}
                key = f"{md.get('namespace', 'default')}/{md.get('name', '')}"
                if self.cache.confirm(key, nn, md.get("labels") or {},
                                      spec=spec):
                    self.queue.delete_key(key)
                    return
        try:
            pod = Pod.from_dict(obj)
        except Exception:
            return
        if type_ == DELETED or pod.status.phase in ("Succeeded", "Failed"):
            # Terminal pods release their node's resources immediately; the
            # reference filters them out of the scheduler's informer entirely
            # (eventhandlers.go assignedNonTerminatedPod FilterFunc).
            self.queue.delete(pod)
            self.cache.remove_pod(pod.key)
            self.queue.move_all_to_active_or_backoff(EVENT_POD_DELETE)
            return
        if pod.spec.node_name:
            # bound (or assumed-confirmed) pod — also drop it from the queue:
            # a pod bound by another party while sitting in backoffQ would
            # otherwise be double-counted (pending in the batch AND bound in
            # the cache) and retried in a 409 loop forever. Mirrors the
            # reference's addPodToCache -> SchedulingQueue.AssignedPodAdded.
            # Order matters: cache BEFORE queue. The scheduler's failure
            # paths requeue only if not cache.is_bound, then re-check; with
            # this order, an is_bound=False re-check guarantees our
            # queue.delete below still lies ahead and will clean up.
            self.cache.add_pod(pod)
            self.queue.delete(pod)
            return
        if pod.spec.scheduler_name not in self._scheduler_names:
            return
        if pod.status.nominated_node_name:
            # another component reserved capacity for this pending pod via
            # the API (descheduler gang defrag); honor it like our own
            # preemption nominations (eventhandlers.go addNominatedPod)
            self.scheduler.nominate_external(
                pod, pod.status.nominated_node_name)
        elif type_ == MODIFIED and ((old or {}).get("status") or {}) \
                .get("nominatedNodeName"):
            # field removed (aborted gang plan): clear the API-origin
            # reservation instead of pinning the node for the full TTL.
            # Only when the PREVIOUS object carried one — most pending-pod
            # MODIFIED events never had a nomination, and staging a
            # tombstone for each would take the staging lock on every such
            # event just for the fold to discard it (ADDED pods are skipped
            # for the same reason).
            self.scheduler.nominate_external(pod, "")
        # incremental encode: compile the pod's encode record NOW, on the
        # watch thread, so the drain's encode_pods is array-fill only by
        # the time this pod pops (sched/cache.py precompile_pod never
        # blocks behind an in-progress encode)
        self.cache.precompile_pod(pod)
        if type_ == MODIFIED and not pod.spec.scheduling_gates:
            self.queue.activate_gated(pod)
        self.queue.add(pod)

    def _on_node(self, type_, obj, old):
        try:
            node = Node.from_dict(obj)
        except Exception:
            return
        if type_ == DELETED:
            self.cache.remove_node(node.metadata.name)
        else:
            self.cache.update_node(node)
            self.queue.move_all_to_active_or_backoff(
                EVENT_NODE_ADD if type_ == ADDED else EVENT_NODE_UPDATE)

    # ---- event handler: volume objects -----------------------------------

    def _on_volume(self, kind: str):
        def handler(type_, obj, old):
            self.cache.update_volume_object(kind, obj, deleted=type_ == DELETED)
            # a new/changed PV or PVC can unblock pending pods
            self.queue.move_all_to_active_or_backoff(EVENT_NODE_UPDATE)
        return handler

    def _on_dra(self, kind: str):
        def handler(type_, obj, old):
            self.cache.update_dra_object(kind, obj, deleted=type_ == DELETED)
            # a new slice/claim (or a released allocation) can unblock pods
            self.queue.move_all_to_active_or_backoff(EVENT_NODE_UPDATE)
        return handler

    # ---- binding via API (DefaultBinder analog) --------------------------

    def _bind(self, pod: Pod, node_name: str) -> bool:
        # PreBind: claim allocations (dynamicresources.go bindClaim), then
        # volumes (volumebinding.go BindPodVolumes), then the binding itself.
        # Any later failure must UNRESERVE the claims we just allocated
        # (the plugin's Unreserve hook) or the pod stays pinned to a node it
        # never bound to.
        allocated: list[dict] = []
        dra = self.cache.dra_catalog
        if dra is not None and pod.spec.resource_claims:
            from kubernetes_tpu.sched.dra import allocation_patch
            for claim in dra.pod_claims(pod):
                if ((claim.get("status") or {}).get("allocation")):
                    continue  # already allocated (shared or re-bind)
                ns = (claim.get("metadata") or {}).get("namespace", "default")
                patched = allocation_patch(claim, node_name, pod)
                try:
                    self.client.resource("resourceclaims", ns).update_status(
                        patched)
                    allocated.append(patched)
                except ApiError as e:
                    if e.code != 409:
                        _LOG.warning("claim allocation for %s failed: %s",
                                     pod.key, e)
                        self._unreserve(allocated)
                        return False
        catalog = self.cache.volume_catalog
        if catalog is not None and pod.pvc_names():
            from kubernetes_tpu.sched.volumebinding import VolumeBinder
            node = self.cache.get_node(node_name)
            labels = node.metadata.labels if node is not None else {}
            if not VolumeBinder(self.client).bind_pod_volumes(
                    pod, node, catalog, labels, node_name):
                self._unreserve(allocated)
                return False
        try:
            self.client.pods(pod.metadata.namespace).bind(pod.metadata.name, node_name)
            return True
        except ApiError as e:
            self._unreserve(allocated)
            if e.code == 404:
                # pod deleted while the binding was in flight (churn): not a
                # failure — tell the scheduler there is nothing to requeue,
                # and keep the expected noise out of the logs
                BIND_RESULTS.inc({"result": "gone"})
                _LOG.debug("bind %s -> %s: pod gone", pod.key, node_name)
                return None
            # 409 = another party bound it first (expected race); anything
            # else is a systemic failure worth surfacing, not swallowing.
            label = "conflict" if e.code == 409 else "error"
            BIND_RESULTS.inc({"result": label})
            if e.code != 409:
                _LOG.warning("bind %s -> %s failed: %s", pod.key, node_name, e)
            return False
        except Exception as e:
            self._unreserve(allocated)
            BIND_RESULTS.inc({"result": "connection"})
            _LOG.warning("bind %s -> %s: API unreachable: %s", pod.key, node_name, e)
            return False

    def _bind_many(self, pairs) -> list:
        """Bulk DefaultBinder: one POST pods/-/binding for a whole gang
        batch. Only plain pods reach this (the scheduler routes DRA/volume/
        lifecycle pods through _bind); per-item 409s are expected races.
        Per-item result: True (bound), False (failed — requeue), None (pod
        vanished mid-flight — nothing to requeue, e.g. a churn delete)."""
        try:
            errs = self.client.pods("default").bind_many(
                [(p.metadata.namespace, p.metadata.name, node)
                 for p, node in pairs])
        except ApiError as e:
            BIND_RESULTS.inc({"result": "error"}, by=len(pairs))
            _LOG.warning("bulk bind of %d pods failed: %s", len(pairs), e)
            return [False] * len(pairs)
        except Exception as e:
            BIND_RESULTS.inc({"result": "connection"}, by=len(pairs))
            _LOG.warning("bulk bind: API unreachable: %s", e)
            return [False] * len(pairs)
        out = []
        for (pod, node), err in zip(pairs, errs):
            if err is None:
                out.append(True)
            elif "not found" in err:
                # deleted while in flight (churn teardown races the gang
                # step's binding every cycle): expected, debug-level only
                BIND_RESULTS.inc({"result": "gone"})
                _LOG.debug("bind %s -> %s: pod gone", pod.key, node)
                out.append(None)
            else:
                label = "conflict" if "bound" in err else "error"
                BIND_RESULTS.inc({"result": label})
                if label != "conflict":
                    _LOG.warning("bind %s -> %s failed: %s",
                                 pod.key, node, err)
                out.append(False)
        return out

    def _unreserve(self, allocated: list[dict]) -> None:
        """Roll back claim allocations written by a failed bind attempt."""
        from kubernetes_tpu.sched.dra import release_patch
        for claim in allocated:
            ns = (claim.get("metadata") or {}).get("namespace", "default")
            try:
                self.client.resource("resourceclaims", ns).update_status(
                    release_patch(claim))
            except Exception as e:
                # the claim controller's release sweep is the backstop
                _LOG.warning("claim unreserve failed (sweep will catch): %s", e)

    def _evict(self, victim: Pod):
        # Preemption DELETEs the victim directly (schedule_one.go preempts
        # via clientset Pods().Delete, not the Eviction API): victim
        # selection already preferred PDB-safe victims, and upstream allows
        # violating a budget as a last resort. The Eviction subresource —
        # which 429s on exhausted budgets — is for voluntary disruption
        # (drain), not preemption.
        try:
            self.client.pods(victim.metadata.namespace).delete(victim.metadata.name)
        except ApiError as e:
            if e.code != 404:  # already gone is fine
                _LOG.warning("evict %s failed: %s", victim.key, e)
        except Exception as e:
            _LOG.warning("evict %s: API unreachable: %s", victim.key, e)
        self.cache.remove_pod(victim.key)

    # ---- lifecycle -------------------------------------------------------

    def start(self, wait_sync: float = 10.0, start_loop: bool = True):
        """Start informers (+ scheduling loop). ``start_loop=False`` starts
        only the informer layer — callers that need to warm caches/JIT
        against synced state first (benchmarks, tests) call ``start_loop()``
        afterwards."""
        return self._start(wait_sync, start_loop)

    def start_loop(self):
        """Start the scheduling loop (after a start(start_loop=False))."""
        if self.cfg.leader_elect:
            raise RuntimeError("leader election owns the loop lifecycle")
        self._start_loop()

    def _start(self, wait_sync: float, start_loop: bool):
        pods = self.factory.informer("pods", None)
        pods.add_event_handler(self._on_pod)
        nodes = self.factory.informer("nodes", None)
        nodes.add_event_handler(self._on_node)
        for plural, kind in (("persistentvolumeclaims", "PersistentVolumeClaim"),
                             ("persistentvolumes", "PersistentVolume"),
                             ("storageclasses", "StorageClass")):
            inf = self.factory.informer(plural, None)
            inf.add_event_handler(self._on_volume(kind))
        for plural, kind in (("resourceclaims", "ResourceClaim"),
                             ("deviceclasses", "DeviceClass"),
                             ("resourceslices", "ResourceSlice")):
            inf = self.factory.informer(plural, None)
            inf.add_event_handler(self._on_dra(kind))
        ns_inf = self.factory.informer("namespaces", None)
        ns_inf.add_event_handler(
            lambda type_, obj, old: self.cache.update_namespace(
                obj, deleted=(type_ == "DELETED")))
        # PDBs feed preemption's victim selection (default_preemption.go
        # checks budgets when picking victims)
        pdb_inf = self.factory.informer("poddisruptionbudgets", None)
        self.scheduler.pdb_lister = lambda: list(pdb_inf.store.list())
        self.factory.start_all()
        self.factory.wait_for_cache_sync(wait_sync)

        if self.cfg.leader_elect:
            elector = LeaderElector(self.client.leases(), LeaderElectionConfig(
                lock_name="kubernetes-tpu-scheduler", identity=self.identity,
                on_started_leading=self._start_loop,
                on_stopped_leading=self._stop_loop))
            t = threading.Thread(target=elector.run, args=(self._stop,), daemon=True)
            t.start()
            self._threads.append(t)
        elif start_loop:
            self._start_loop()
        self.publish_status()
        return self

    def publish_status(self) -> None:
        """Publish the deployment-shape status ConfigMap (``ktpu status``
        reads it): active mesh shape/devices and the batching knobs. Best
        effort — status must never take the scheduler down."""
        import json
        mesh = self.scheduler._mesh
        status = {
            "identity": self.identity,
            "mesh": ({"shape": dict(zip(mesh.axis_names,
                                        (int(s) for s in mesh.devices.shape))),
                      "devices": int(mesh.devices.size),
                      "deviceIds": [int(d.id) for d in mesh.devices.flat]}
                     if mesh is not None else None),
            "batchSize": self.cfg.batch_size,
            "maxDrainBatches": self.cfg.max_drain_batches,
            "pipelineDepth": self.cfg.pipeline_depth,
            "profiles": [p.scheduler_name for p in self.cfg.profiles],
        }
        body = {
            "apiVersion": "v1", "kind": "ConfigMap",
            "metadata": {"name": STATUS_CONFIGMAP,
                         "namespace": self.status_namespace},
            "data": {"status": json.dumps(status, indent=1)},
        }
        cms = self.client.resource("configmaps", self.status_namespace)
        try:
            current = cms.get(STATUS_CONFIGMAP)
            current["data"] = body["data"]
            cms.update(current)
        except ApiError as e:
            if e.code != 404:
                return
            try:
                cms.create(body)
            except ApiError:
                pass
        except Exception:
            pass

    def _start_loop(self):
        # Chain terms: if the previous term's loop is still draining (e.g.
        # stuck in a long run_once/JIT compile when the lease bounced), the
        # new term's thread waits for it rather than stacking a concurrent
        # loop — and rather than silently not starting one, which would leave
        # a leader that schedules nothing until the next transition.
        prev_t, prev_s = self._loop_thread, self._loop_stop
        stop = threading.Event()

        def term():
            if prev_t is not None and prev_t.is_alive():
                if prev_s is not None:
                    prev_s.set()
                prev_t.join()
            self.scheduler.run(stop)

        self._loop_stop = stop
        self._loop_thread = threading.Thread(target=term, daemon=True)
        self._loop_thread.start()

    def _stop_loop(self):
        if self._loop_stop is not None:
            self._loop_stop.set()
        if self._loop_thread is not None:
            self._loop_thread.join(timeout=5.0)

    def stop(self):
        self._stop.set()
        self._stop_loop()
        self.queue.close()
        self.scheduler.close()
        self.factory.stop_all()
