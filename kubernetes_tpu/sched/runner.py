"""Connected scheduler — informers in, bindings out.

Reference: ``cmd/kube-scheduler/app/server.go`` (Run: informers + event
handlers feeding the queue/cache, then the scheduling loop) and the event
registration in ``pkg/scheduler/eventhandlers.go``. Optionally wraps the loop
in leader election (active-passive HA, SURVEY §5).
"""

from __future__ import annotations

import logging
import threading
from typing import Optional

_LOG = logging.getLogger("kubernetes_tpu.sched.runner")

from kubernetes_tpu.api.types import Node, Pod
from kubernetes_tpu.client.clientset import ApiError
from kubernetes_tpu.client.informer import InformerFactory, meta_namespace_key
from kubernetes_tpu.client.leaderelection import LeaderElectionConfig, LeaderElector
from kubernetes_tpu.config.types import SchedulerConfiguration
from kubernetes_tpu.metrics.registry import (
    BIND_RESULTS,
    BIND_RETRIES,
    LOOP_ERRORS,
    NODE_LIVENESS_SKIPS,
)
from kubernetes_tpu.sched.cache import SchedulerCache
from kubernetes_tpu.sched.resilience import ThreadWatchdog
from kubernetes_tpu.utils.retry import with_retries
from kubernetes_tpu.sched.queue import (
    EVENT_NODE_ADD,
    EVENT_NODE_UPDATE,
    EVENT_POD_DELETE,
    SchedulingQueue,
)
from kubernetes_tpu.sched.scheduler import Scheduler
from kubernetes_tpu.store.store import ADDED, DELETED, MODIFIED

# Published like the autoscaler's cluster-autoscaler-status: one ConfigMap
# other components (and ``ktpu status``) read for the live deployment shape
# — most importantly the active device mesh.
STATUS_CONFIGMAP = "kubernetes-tpu-scheduler-status"
# Decision provenance: per-pod unschedulability explanations (the
# explainer's verdicts), read by ``ktpu why <pod>``.
EXPLAIN_CONFIGMAP = "scheduler-explanations"
# Flight-recorder export: the newest window of batch spans + per-pod
# lifecycle tracks as Chrome trace-event JSON, read by ``ktpu trace dump``
# (loads directly in Perfetto). Bounded — see _publish_trace.
TRACE_CONFIGMAP = "kubernetes-tpu-scheduler-trace"
# span events / pod tracks kept in the published trace ConfigMap (the
# full in-process ring is TRACER.max_spans and FLIGHT.max_pods; the
# ConfigMap is a bounded API object rewritten on the audit cadence)
TRACE_PUBLISH_EVENTS = 1000
TRACE_PUBLISH_PODS = 200


class SchedulerRunner:
    """Owns informers, cache, queue, scheduler; drives the loop."""

    def __init__(self, client, cfg: Optional[SchedulerConfiguration] = None,
                 identity: str = "kubernetes-tpu-scheduler", registry=None,
                 status_namespace: str = "default",
                 status_name: str = STATUS_CONFIGMAP,
                 explain_name: str = EXPLAIN_CONFIGMAP,
                 trace_name: str = TRACE_CONFIGMAP):
        self.client = client
        # where publish_status writes its ConfigMap (same shape as the
        # autoscaler's status_namespace: RBAC commonly restricts writes to
        # the component's own namespace; ktpu -n <ns> status must match)
        self.status_namespace = status_namespace
        # Per-INSTANCE ConfigMap names: two scheduler identities sharing
        # one apiserver (fleet tenants, A/B runners) used to clobber each
        # other's status/explanations/trace through the module-level
        # constants — publish_status always assumed ONE scheduler per
        # apiserver. The constants stay the defaults ktpu reads.
        self.status_name = status_name
        self.explain_name = explain_name
        self.trace_name = trace_name
        if hasattr(client, "default_user_agent"):
            client.default_user_agent("kube-scheduler")
        # GIL tuning for the connected deployment shape: informer bursts
        # (thousands of JSON decodes) and the device tunnel share one
        # interpreter; a finer switch interval caps how long either side
        # can starve the other between checks. Opt-in via env so library
        # embedders keep the interpreter default.
        import os
        import sys
        si = os.environ.get("KTPU_SWITCH_INTERVAL")
        if si:
            sys.setswitchinterval(float(si))

        self.cfg = cfg or SchedulerConfiguration()
        # durable AOT executable cache: armed BEFORE the Scheduler exists so
        # every jit this process ever compiles — warm ladder, staging
        # helpers, first-touch programs — persists, and a restarted
        # scheduler boots warm from disk (sched/aotcache.py). Activation
        # never raises on cache damage; a cache too broken to use degrades
        # to plain recompiles.
        self.aot_cache = None
        from kubernetes_tpu.sched.aotcache import (AotExecutableCache,
                                                   cache_knobs,
                                                   resolve_cache_dir)
        cache_dir = resolve_cache_dir(self.cfg)
        if cache_dir:
            try:
                self.aot_cache = AotExecutableCache(
                    cache_dir, knobs=cache_knobs(self.cfg),
                    max_bytes=self.cfg.aot_cache_max_mb * 1024 * 1024)
                self.aot_cache.activate()
            except Exception:
                # the cache is an accelerant, never a dependency: a scheduler
                # that cannot arm it runs cold, it does not stay down
                from kubernetes_tpu.metrics.registry import AOT_CACHE_ERRORS
                AOT_CACHE_ERRORS.inc({"reason": "activate"})
                _LOG.exception("AOT cache activation failed at %s; "
                               "running without executable persistence",
                               cache_dir)
                self.aot_cache = None
        self.cache = SchedulerCache(assume_ttl=self.cfg.assume_ttl_s)
        self.queue = self._build_queue(self.cfg)
        self.scheduler = Scheduler(self.cfg, self.cache, self.queue, self._bind,
                                   registry=registry,
                                   bulk_binder=self._bind_many)
        if (self.aot_cache is not None and self.aot_cache.boot.get("entries")
                and self.scheduler.sentinel is not None):
            # warm-from-cache canary: the FIRST drain answer produced by a
            # deserialized executable is parity-judged regardless of the
            # every-Kth modulus — a wrong program trips the breaker
            # (reason="parity") before a second batch trusts it
            self.scheduler.sentinel.force_next()
        from kubernetes_tpu.utils.events import EventRecorder
        self.scheduler.recorder = EventRecorder(client, "default-scheduler")
        self.scheduler._evict = self._evict  # preemption deletes via API
        # decision provenance: the explainer publishes its verdicts as the
        # scheduler-explanations ConfigMap (ktpu why reads it; events ride
        # the recorder wired above)
        if self.scheduler.explainer is not None:
            self.scheduler.explainer.publisher = self._publish_explanations
        self.factory = InformerFactory(client)
        self.identity = identity
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        # Per-leadership-term scheduling loop: a lost lease stops the loop (no
        # split-brain binding), a re-acquired one starts a fresh term instead
        # of stacking a second concurrent loop.
        self._loop_stop: Optional[threading.Event] = None
        self._loop_thread: Optional[threading.Thread] = None
        self._loop_expected = False
        # serializes loop lifecycle transitions between the elector thread
        # (start/stop on leadership changes) and the watchdog's revive —
        # without it a revive racing a lost lease could restart a
        # non-leader's loop
        self._loop_lock = threading.Lock()
        self._scheduler_names = {p.scheduler_name for p in self.cfg.profiles}
        # liveness-only node MODIFIEDs skipped before decode (_on_node);
        # written from the single informer dispatch thread, mirrored into
        # the NODE_LIVENESS_SKIPS gauge
        self._node_skips = 0
        # thread watchdog (sched/resilience.py): restarts a dead or
        # stalled scheduling loop / drain resolver instead of letting the
        # runner hang with a live process and a dead brain
        self._watchdog = ThreadWatchdog(
            interval_s=self.cfg.watchdog_interval_s,
            stall_s=self.cfg.watchdog_stall_s)
        self.scheduler.heartbeat = lambda: self._watchdog.beat("loop")
        self.scheduler.resolver_heartbeat = \
            lambda: self._watchdog.beat("resolver")
        # continuous invariant auditor (kubernetes_tpu/audit/): background
        # sweeps over a consistent apiserver list + the scheduler's cache/
        # resident-ctx views. The stale-nomination GC rides the same
        # cadence as the pre-sweep hook, so every sweep judges the
        # post-GC state; relist counting gates cache-parity (an informer
        # healing from a watch outage is lagging, not wrong).
        from kubernetes_tpu.audit.auditor import InvariantAuditor
        self.auditor = InvariantAuditor(
            client=client, cache=self.cache, scheduler=self.scheduler,
            interval_s=self.cfg.audit_interval_s,
            fail_fast=self.cfg.audit_fail_fast,
            pre_sweep=self.sweep_stale_nominations,
            post_sweep=self.publish_status,
            relists=self._total_relists)

    def _build_queue(self, cfg: SchedulerConfiguration) -> SchedulingQueue:
        """Queue factory hook — the FleetRunner (sched/fleet.py) swaps in
        the fairness-aware FleetQueue here."""
        return SchedulingQueue(backoff_initial=cfg.backoff_initial_s,
                               backoff_max=cfg.backoff_max_s)

    def _all_informers(self):
        """Every SharedInformer this runner owns (the FleetRunner overrides
        with N tenant factories' worth)."""
        return list(self.factory._informers.values())

    # ---- event handlers (pkg/scheduler/eventhandlers.go analog) ----------

    def _on_pod(self, type_, obj, old):
        if type_ != DELETED:
            # Fast path for bind confirmations: a gang bind storm is one
            # MODIFIED per pod whose only news is the nodeName the cache
            # already assumed — confirm from the raw dict and skip the full
            # Pod.from_dict (a first-order cost at 10k events/s).
            spec = obj.get("spec") or {}
            nn = spec.get("nodeName")
            if nn and (obj.get("status") or {}).get("phase") \
                    not in ("Succeeded", "Failed"):
                md = obj.get("metadata") or {}
                key = f"{md.get('namespace', 'default')}/{md.get('name', '')}"
                if self.cache.confirm(key, nn, md.get("labels") or {},
                                      spec=spec):
                    self.queue.delete_key(key)
                    return
        try:
            pod = Pod.from_dict(obj)
        except Exception:
            # a pod we cannot decode is a pod we silently never schedule:
            # count + log it loudly (chaos runs assert no silent swallow)
            LOOP_ERRORS.inc({"site": "pod_decode"})
            _LOG.warning("dropping undecodable pod event %s: %s", type_,
                         (obj.get("metadata") or {}).get("name", "?"),
                         exc_info=True)
            return
        if type_ == DELETED or pod.status.phase in ("Succeeded", "Failed"):
            # Terminal pods release their node's resources immediately; the
            # reference filters them out of the scheduler's informer entirely
            # (eventhandlers.go assignedNonTerminatedPod FilterFunc).
            self.queue.delete(pod)
            self.cache.remove_pod(pod.key)
            self.queue.move_all_to_active_or_backoff(EVENT_POD_DELETE)
            return
        if pod.spec.node_name:
            # bound (or assumed-confirmed) pod — also drop it from the queue:
            # a pod bound by another party while sitting in backoffQ would
            # otherwise be double-counted (pending in the batch AND bound in
            # the cache) and retried in a 409 loop forever. Mirrors the
            # reference's addPodToCache -> SchedulingQueue.AssignedPodAdded.
            # Order matters: cache BEFORE queue. The scheduler's failure
            # paths requeue only if not cache.is_bound, then re-check; with
            # this order, an is_bound=False re-check guarantees our
            # queue.delete below still lies ahead and will clean up.
            self.cache.add_pod(pod)
            self.queue.delete(pod)
            return
        if pod.spec.scheduler_name not in self._scheduler_names:
            return
        if pod.status.nominated_node_name:
            # another component reserved capacity for this pending pod via
            # the API (descheduler gang defrag); honor it like our own
            # preemption nominations (eventhandlers.go addNominatedPod)
            self.scheduler.nominate_external(
                pod, pod.status.nominated_node_name)
        elif type_ == MODIFIED and ((old or {}).get("status") or {}) \
                .get("nominatedNodeName"):
            # field removed (aborted gang plan): clear the API-origin
            # reservation instead of pinning the node for the full TTL.
            # Only when the PREVIOUS object carried one — most pending-pod
            # MODIFIED events never had a nomination, and staging a
            # tombstone for each would take the staging lock on every such
            # event just for the fold to discard it (ADDED pods are skipped
            # for the same reason).
            self.scheduler.nominate_external(pod, "")
        from kubernetes_tpu.utils.tracing import FLIGHT
        FLIGHT.record(pod.key, "informer", event=type_)
        # incremental encode: compile the pod's encode record NOW, on the
        # watch thread, so the drain's encode_pods is array-fill only by
        # the time this pod pops (sched/cache.py precompile_pod never
        # blocks behind an in-progress encode)
        self.cache.precompile_pod(pod)
        FLIGHT.record(pod.key, "precompile")
        if type_ == MODIFIED and not pod.spec.scheduling_gates:
            self.queue.activate_gated(pod)
        self.queue.add(pod)

    @staticmethod
    def _node_liveness_only(obj: dict, old: dict) -> bool:
        """True when a node MODIFIED carries only liveness news — heartbeat
        condition timestamps, kubelet endpoint/address re-assertions — and
        nothing scheduling-relevant (spec/taints, labels, allocatable,
        capacity, images, condition STATUS transitions). At 10k-node fleet
        scale the bulk heartbeat/lease paths emit one such MODIFIED per
        node per period; decoding each and waking the scheduling queue for
        it was pure informer-thread burn (the PR-8 bound-pod
        status-MODIFIED fingerprint skip, applied to nodes)."""
        if obj.get("spec") != old.get("spec"):
            return False
        if ((obj.get("metadata") or {}).get("labels")
                != (old.get("metadata") or {}).get("labels")):
            return False
        st, ost = obj.get("status") or {}, old.get("status") or {}
        for k in ("allocatable", "capacity", "images"):
            if st.get(k) != ost.get(k):
                return False
        return ({(c.get("type"), c.get("status"))
                 for c in st.get("conditions") or []}
                == {(c.get("type"), c.get("status"))
                    for c in ost.get("conditions") or []})

    def _on_node(self, type_, obj, old):
        if type_ == MODIFIED and old is not None \
                and self._node_liveness_only(obj, old):
            # liveness-only refresh: no decode, no cache delta, no queue
            # wake. (The cache's own fingerprint would have kept the
            # ENCODING valid, but the Node.from_dict + requeue storm is
            # what melts the informer thread at fleet scale.)
            self._node_skips += 1
            NODE_LIVENESS_SKIPS.set(self._node_skips)
            return
        try:
            node = Node.from_dict(obj)
        except Exception:
            LOOP_ERRORS.inc({"site": "node_decode"})
            _LOG.warning("dropping undecodable node event %s: %s", type_,
                         (obj.get("metadata") or {}).get("name", "?"),
                         exc_info=True)
            return
        if type_ == DELETED:
            self.cache.remove_node(node.metadata.name)
        else:
            self.cache.update_node(node)
            self.queue.move_all_to_active_or_backoff(
                EVENT_NODE_ADD if type_ == ADDED else EVENT_NODE_UPDATE)

    # ---- event handler: volume objects -----------------------------------

    def _on_volume(self, kind: str):
        def handler(type_, obj, old):
            self.cache.update_volume_object(kind, obj, deleted=type_ == DELETED)
            # a new/changed PV or PVC can unblock pending pods
            self.queue.move_all_to_active_or_backoff(EVENT_NODE_UPDATE)
        return handler

    def _on_dra(self, kind: str):
        def handler(type_, obj, old):
            self.cache.update_dra_object(kind, obj, deleted=type_ == DELETED)
            # a new slice/claim (or a released allocation) can unblock pods
            self.queue.move_all_to_active_or_backoff(EVENT_NODE_UPDATE)
        return handler

    # ---- binding via API (DefaultBinder analog) --------------------------

    def _retry(self, fn):
        """Jittered bounded retries for bind/status writes (utils/retry):
        a transient API failure (connection reset, 5xx, 429) retries
        in-request instead of failing straight through to a requeue —
        semantic outcomes (404 gone, 409 conflict) still surface
        immediately to the callers' existing handling."""
        return with_retries(
            fn, attempts=self.cfg.bind_retries + 1,
            base_s=self.cfg.bind_retry_backoff_s,
            on_retry=lambda e: BIND_RETRIES.inc())

    def _bind(self, pod: Pod, node_name: str) -> bool:
        # PreBind: claim allocations (dynamicresources.go bindClaim), then
        # volumes (volumebinding.go BindPodVolumes), then the binding itself.
        # Any later failure must UNRESERVE the claims we just allocated
        # (the plugin's Unreserve hook) or the pod stays pinned to a node it
        # never bound to.
        allocated: list[dict] = []
        dra = self.cache.dra_catalog
        if dra is not None and pod.spec.resource_claims:
            from kubernetes_tpu.sched.dra import allocation_patch
            from kubernetes_tpu.topology.slicing import (coords_of_labels,
                                                         shape_of_labels)
            # carved-slice provenance: the allocation records the torus
            # coordinate the member landed on (node labels first, the
            # slice inventory's attributes as fallback) + requested shape
            node = self.cache.get_node(node_name)
            coords = (coords_of_labels(node.metadata.labels)
                      if node is not None else None)
            if coords is None:
                coords = dra.node_topology(node_name)
            shape = (shape_of_labels(pod.metadata.labels)
                     or dra.pod_slice_shape(pod))
            for claim in dra.pod_claims(pod):
                if ((claim.get("status") or {}).get("allocation")):
                    continue  # already allocated (shared or re-bind)
                ns = (claim.get("metadata") or {}).get("namespace", "default")
                patched = allocation_patch(
                    claim, node_name, pod,
                    coords=coords if shape is not None else None,
                    shape=shape)
                try:
                    self._retry(lambda: self.client.resource(
                        "resourceclaims", ns).update_status(patched))
                    allocated.append(patched)
                except ApiError as e:
                    if e.code != 409:
                        _LOG.warning("claim allocation for %s failed: %s",
                                     pod.key, e)
                        self._unreserve(allocated)
                        return False
        catalog = self.cache.volume_catalog
        if catalog is not None and pod.pvc_names():
            from kubernetes_tpu.sched.volumebinding import VolumeBinder
            node = self.cache.get_node(node_name)
            labels = node.metadata.labels if node is not None else {}
            if not VolumeBinder(self.client).bind_pod_volumes(
                    pod, node, catalog, labels, node_name):
                self._unreserve(allocated)
                return False
        try:
            self._retry(lambda: self.client.pods(pod.metadata.namespace)
                        .bind(pod.metadata.name, node_name))
            return True
        except ApiError as e:
            self._unreserve(allocated)
            if e.code == 404:
                # pod deleted while the binding was in flight (churn): not a
                # failure — tell the scheduler there is nothing to requeue,
                # and keep the expected noise out of the logs
                BIND_RESULTS.inc({"result": "gone"})
                _LOG.debug("bind %s -> %s: pod gone", pod.key, node_name)
                return None
            # 409 = another party bound it first (expected race); anything
            # else is a systemic failure worth surfacing, not swallowing.
            label = "conflict" if e.code == 409 else "error"
            BIND_RESULTS.inc({"result": label})
            if e.code != 409:
                _LOG.warning("bind %s -> %s failed: %s", pod.key, node_name, e)
            return False
        except Exception as e:
            self._unreserve(allocated)
            BIND_RESULTS.inc({"result": "connection"})
            _LOG.warning("bind %s -> %s: API unreachable: %s", pod.key, node_name, e)
            return False

    def _bind_many(self, pairs) -> list:
        """Bulk DefaultBinder: one POST pods/-/binding for a whole gang
        batch. Only plain pods reach this (the scheduler routes DRA/volume/
        lifecycle pods through _bind); per-item 409s are expected races.
        Per-item result: True (bound), False (failed — requeue), None (pod
        vanished mid-flight — nothing to requeue, e.g. a churn delete)."""
        try:
            bindings = [(p.metadata.namespace, p.metadata.name, node)
                        for p, node in pairs]
            errs = self._retry(
                lambda: self.client.pods("default").bind_many(bindings))
        except ApiError as e:
            BIND_RESULTS.inc({"result": "error"}, by=len(pairs))
            _LOG.warning("bulk bind of %d pods failed: %s", len(pairs), e)
            return [False] * len(pairs)
        except Exception as e:
            BIND_RESULTS.inc({"result": "connection"}, by=len(pairs))
            _LOG.warning("bulk bind: API unreachable: %s", e)
            return [False] * len(pairs)
        out = []
        for (pod, node), err in zip(pairs, errs):
            if err is None:
                out.append(True)
            elif "not found" in err:
                # deleted while in flight (churn teardown races the gang
                # step's binding every cycle): expected, debug-level only
                BIND_RESULTS.inc({"result": "gone"})
                _LOG.debug("bind %s -> %s: pod gone", pod.key, node)
                out.append(None)
            else:
                label = "conflict" if "bound" in err else "error"
                BIND_RESULTS.inc({"result": label})
                if label != "conflict":
                    _LOG.warning("bind %s -> %s failed: %s",
                                 pod.key, node, err)
                out.append(False)
        return out

    def _unreserve(self, allocated: list[dict]) -> None:
        """Roll back claim allocations written by a failed bind attempt."""
        from kubernetes_tpu.sched.dra import release_patch
        for claim in allocated:
            ns = (claim.get("metadata") or {}).get("namespace", "default")
            try:
                self.client.resource("resourceclaims", ns).update_status(
                    release_patch(claim))
            except Exception as e:
                # the claim controller's release sweep is the backstop
                _LOG.warning("claim unreserve failed (sweep will catch): %s", e)

    def _total_relists(self) -> int:
        return sum(getattr(inf, "relists", 0)
                   for inf in self._all_informers())

    def sweep_stale_nominations(self) -> int:
        """Periodic GC: clear ``status.nominatedNodeName`` from bound or
        terminal pods. A nomination's job ends the moment its pod binds
        (or dies); the field surviving past that — a preemption nominee
        bound elsewhere, a descheduler gang plan that half-executed —
        pins a node's capacity in every consumer that honors nominations
        and is exactly what the auditor's nomination_consistency invariant
        flags. Runs as the auditor's pre-sweep hook; returns pods cleared.
        Best effort per pod: 404/409 mean the pod moved on and the next
        sweep re-judges it."""
        cleared = 0
        try:
            pods = self.client.resource("pods", None).list()
        except Exception:
            LOOP_ERRORS.inc({"site": "nomination_gc"})
            _LOG.warning("stale-nomination sweep: pod list failed",
                         exc_info=True)
            return 0
        for p in pods:
            st = p.get("status") or {}
            if not st.get("nominatedNodeName"):
                continue
            bound = bool((p.get("spec") or {}).get("nodeName"))
            terminal = st.get("phase") in ("Succeeded", "Failed")
            if not (bound or terminal):
                continue
            md = p.get("metadata") or {}
            q = dict(p)
            q["status"] = {k: v for k, v in st.items()
                           if k != "nominatedNodeName"}
            try:
                self.client.pods(md.get("namespace", "default")) \
                    .update_status(q)
                cleared += 1
                _LOG.info("cleared stale nomination on %s pod %s/%s",
                          "bound" if bound else "terminal",
                          md.get("namespace", "default"), md.get("name"))
            except ApiError as e:
                if e.code not in (404, 409):
                    LOOP_ERRORS.inc({"site": "nomination_gc"})
                    _LOG.warning("stale-nomination clear for %s failed: %s",
                                 md.get("name"), e)
            except Exception:
                LOOP_ERRORS.inc({"site": "nomination_gc"})
                _LOG.warning("stale-nomination clear for %s failed",
                             md.get("name"), exc_info=True)
        return cleared

    def _evict(self, victim: Pod):
        # Preemption DELETEs the victim directly (schedule_one.go preempts
        # via clientset Pods().Delete, not the Eviction API): victim
        # selection already preferred PDB-safe victims, and upstream allows
        # violating a budget as a last resort. The Eviction subresource —
        # which 429s on exhausted budgets — is for voluntary disruption
        # (drain), not preemption.
        try:
            self.client.pods(victim.metadata.namespace).delete(victim.metadata.name)
        except ApiError as e:
            if e.code != 404:  # already gone is fine
                LOOP_ERRORS.inc({"site": "evict"})
                _LOG.warning("evict %s failed: %s", victim.key, e)
        except Exception as e:
            LOOP_ERRORS.inc({"site": "evict"})
            _LOG.warning("evict %s: API unreachable: %s", victim.key, e)
        self.cache.remove_pod(victim.key)

    # ---- lifecycle -------------------------------------------------------

    def start(self, wait_sync: float = 10.0, start_loop: bool = True):
        """Start informers (+ scheduling loop). ``start_loop=False`` starts
        only the informer layer — callers that need to warm caches/JIT
        against synced state first (benchmarks, tests) call ``start_loop()``
        afterwards."""
        return self._start(wait_sync, start_loop)

    def start_loop(self):
        """Start the scheduling loop (after a start(start_loop=False))."""
        if self.cfg.leader_elect:
            raise RuntimeError("leader election owns the loop lifecycle")
        self._start_loop()

    def _wire_informers(self, factory: InformerFactory, wrap=None):
        """Register every watched resource's handlers on ``factory`` —
        THE single list of what the scheduler watches. ``wrap(handler,
        plural)`` adapts handlers (the FleetRunner re-keys each tenant's
        events through it); a new watched resource added here reaches
        fleet tenants automatically. Returns the PDB informer (its store
        feeds preemption's victim selection)."""
        w = wrap if wrap is not None else (lambda h, _plural: h)
        factory.informer("pods", None).add_event_handler(
            w(self._on_pod, "pods"))
        factory.informer("nodes", None).add_event_handler(
            w(self._on_node, "nodes"))
        for plural, kind in (("persistentvolumeclaims", "PersistentVolumeClaim"),
                             ("persistentvolumes", "PersistentVolume"),
                             ("storageclasses", "StorageClass")):
            factory.informer(plural, None).add_event_handler(
                w(self._on_volume(kind), plural))
        for plural, kind in (("resourceclaims", "ResourceClaim"),
                             ("deviceclasses", "DeviceClass"),
                             ("resourceslices", "ResourceSlice")):
            factory.informer(plural, None).add_event_handler(
                w(self._on_dra(kind), plural))
        factory.informer("namespaces", None).add_event_handler(
            w(lambda type_, obj, old: self.cache.update_namespace(
                obj, deleted=(type_ == "DELETED")), "namespaces"))
        # PDBs feed preemption's victim selection (default_preemption.go
        # checks budgets when picking victims)
        return factory.informer("poddisruptionbudgets", None)

    def _start(self, wait_sync: float, start_loop: bool):
        pdb_inf = self._wire_informers(self.factory)
        self.scheduler.pdb_lister = lambda: list(pdb_inf.store.list())
        self.factory.start_all()
        self.factory.wait_for_cache_sync(wait_sync)
        # Boot resync: a predecessor that died mid-cycle leaves stale
        # nominations (and half-executed gang plans) in the API. Sweeping
        # HERE — after the informers synced, before the loop binds anything
        # — means the first scheduling cycle judges clean state instead of
        # waiting for the first 30s audit cadence to GC it. Bound-pod state
        # needs no sweep: the informer sync itself rebuilt the cache from
        # the API's nodeName truth, so duplicate binds are structurally
        # impossible (_on_pod confirms, never re-binds).
        try:
            cleared = self.sweep_stale_nominations()
            if cleared:
                _LOG.info("boot resync: cleared %d stale nomination(s) "
                          "left by a prior incarnation", cleared)
        except Exception:
            LOOP_ERRORS.inc({"site": "nomination_gc"})
            _LOG.warning("boot-resync nomination sweep failed; the audit "
                         "cadence retries", exc_info=True)

        if self.cfg.leader_elect:
            elector = LeaderElector(self.client.leases(), LeaderElectionConfig(
                lock_name="kubernetes-tpu-scheduler", identity=self.identity,
                on_started_leading=self._start_loop,
                on_stopped_leading=self._stop_loop))
            self._elector = elector
            # elector.run self-heals per term (ApiError storms are missed
            # renewals, callback failures drop leadership and re-contend),
            # so the thread body needs no further wrapping
            t = threading.Thread(target=elector.run, args=(self._stop,),
                                 daemon=True)
            t.start()
            self._threads.append(t)
        elif start_loop:
            self._start_loop()
        self.auditor.start()
        self.publish_status()
        return self

    def _resilience_status(self) -> dict:
        """Live self-healing state for the status ConfigMap: degraded mode
        (mesh/single/oracle), breaker trip/restore counts, watchdog
        restarts, and the informer layer's relist totals."""
        from kubernetes_tpu.utils.clock import rfc3339_from_epoch
        breaker = self.scheduler.breaker
        relists = 0
        last_relist = None
        for inf in self._all_informers():
            relists += getattr(inf, "relists", 0)
            lr = getattr(inf, "last_relist", None)
            if lr and (last_relist is None or lr > last_relist):
                last_relist = lr
        return {
            "degradedMode": breaker.mode,
            "degradedIndex": breaker.index,
            "breakerTrips": breaker.trips,
            "breakerTripReasons": dict(breaker.trip_reasons),
            "lastTripReason": breaker.last_trip_reason,
            "breakerRestores": breaker.restores,
            "watchdogRestarts": self._watchdog.restarts,
            "watchRelists": relists,
            "lastRelist": (rfc3339_from_epoch(last_relist)
                           if last_relist else None),
        }

    def _audit_status(self) -> dict:
        """Auditor + parity-sentinel state for the status ConfigMap
        (``ktpu audit status`` reads this block)."""
        status = self.auditor.status()
        sentinel = self.scheduler.sentinel
        status["parity"] = sentinel.stats() if sentinel is not None else None
        return status

    def _copy_reasons(self) -> dict:
        """Copy ctx_stats['reasons'] from the status thread while the
        scheduling thread may be inserting a first-seen reason key."""
        for _ in range(3):
            try:
                return dict(self.scheduler.ctx_stats["reasons"])
            except RuntimeError:  # resized mid-iteration; rare — retry
                continue
        return {}

    def publish_status(self) -> None:
        """Publish the deployment-shape status ConfigMap (``ktpu status``
        reads it): active mesh shape/devices, the batching knobs, and the
        resilience state. Best effort — status must never take the
        scheduler down."""
        import json
        mesh = self.scheduler._mesh
        status = {
            "identity": self.identity,
            "mesh": ({"shape": dict(zip(mesh.axis_names,
                                        (int(s) for s in mesh.devices.shape))),
                      "devices": int(mesh.devices.size),
                      "deviceIds": [int(d.id) for d in mesh.devices.flat]}
                     if mesh is not None else None),
            "batchSize": self.cfg.batch_size,
            "maxDrainBatches": self.cfg.max_drain_batches,
            "pipelineDepth": self.cfg.pipeline_depth,
            # live pipeline depth + resident-context lifecycle counters:
            # degraded fusion (patches climbing instead of folds, rebuild
            # reasons piling up) is visible from ktpu status without a
            # bench run. Momentarily stale is fine for a status surface;
            # the reasons dict is the one piece that GROWS on the
            # scheduling thread (new reason keys), so its copy retries —
            # dict() over a concurrently-resizing dict raises RuntimeError.
            "pipelineInflight": len(self.scheduler._pending),
            "fusedFold": self.scheduler._fused_fold,
            # zero-copy staging health: swaps tracking dispatches 1:1 with
            # fallbacks ~0 means the dispatch path pays buffer swaps, not
            # device_puts (sched/staging.py)
            "staging": self.cache.staging_stats(),
            "ctx": dict(self.scheduler.ctx_stats,
                        reasons=self._copy_reasons()),
            "profiles": [p.scheduler_name for p in self.cfg.profiles],
            "resilience": self._resilience_status(),
            "audit": self._audit_status(),
            "pending": self.queue.stats(),
            "e2e": self._e2e_status(),
            "explain": (self.scheduler.explainer.stats()
                        if self.scheduler.explainer is not None else None),
            "flight": self._flight_status(),
            "aotCache": self._aot_cache_status(),
            # topology/ slice-carving surface: grid extent, carveable
            # origins per requested shape, fragmentation %, carve counters
            "topology": self.scheduler.topology_status(),
        }
        self._publish_configmap(self.status_name,
                                {"status": json.dumps(status, indent=1)})
        self._publish_trace()

    def _e2e_status(self) -> dict:
        """End-to-end scheduling SLI (flight-recorder-derived histogram)
        for the status ConfigMap: ktpu status shows the whole-pipeline
        latency next to the pending-pod gauges."""
        from kubernetes_tpu.metrics.registry import E2E_SCHEDULING
        return {"count": E2E_SCHEDULING.count(),
                "p50Seconds": E2E_SCHEDULING.percentile(0.50),
                "p99Seconds": E2E_SCHEDULING.percentile(0.99)}

    def _flight_status(self) -> dict:
        from kubernetes_tpu.utils.tracing import FLIGHT, TRACER
        st = FLIGHT.stats()
        st["spanDrops"] = TRACER.dropped
        return st

    def _aot_cache_status(self):
        """Executable-cache block for the status ConfigMap (``ktpu status``
        renders the "Compile cache:" line from it). Publishing rides the
        audit cadence, so seal here too: entries jax wrote since the last
        seal become checksum-verifiable at the next boot (cheap no-op when
        the entry set is unchanged)."""
        if self.aot_cache is None:
            return {"enabled": False}
        try:
            self.aot_cache.seal()
            return self.aot_cache.stats()
        except Exception:
            LOOP_ERRORS.inc({"site": "publish_status"})
            _LOG.debug("AOT cache status failed", exc_info=True)
            return {"enabled": True, "error": "stats unavailable"}

    def _publish_configmap(self, name: str, data: dict) -> None:
        """Create-or-update one of the runner's published ConfigMaps.
        Best effort — publishing must never take the scheduler down."""
        from kubernetes_tpu.utils.configmap import upsert_configmap
        upsert_configmap(self.client, self.status_namespace, name, data,
                         site="publish_status")

    def _publish_explanations(self, explanations: dict) -> None:
        """Explainer-thread callback: the scheduler-explanations ConfigMap
        ``ktpu why <pod>`` reads. One JSON blob keyed by pod key."""
        import json
        import time as _time
        self._publish_configmap(
            self.explain_name,
            {"explanations": json.dumps(explanations),
             "updated": str(_time.time())})

    def publish_trace(self) -> None:
        """Publish the flight-recorder export NOW (``ktpu trace dump``
        freshness; publish_status also refreshes it on the audit cadence)."""
        self._publish_trace()

    def _publish_trace(self) -> None:
        import json
        import time as _time
        from kubernetes_tpu.utils.tracing import TRACER
        try:
            doc = TRACER.export_chrome(max_events=TRACE_PUBLISH_EVENTS,
                                       max_flight_pods=TRACE_PUBLISH_PODS)
        except Exception:
            LOOP_ERRORS.inc({"site": "publish_status"})
            _LOG.debug("trace export failed", exc_info=True)
            return
        self._publish_configmap(
            self.trace_name,
            {"trace": json.dumps(doc), "updated": str(_time.time())})

    def _start_loop(self):
        with self._loop_lock:
            self._start_loop_locked()

    def _start_loop_locked(self):
        # Chain terms: if the previous term's loop is still draining (e.g.
        # stuck in a long run_once/JIT compile when the lease bounced), the
        # new term's thread waits for it rather than stacking a concurrent
        # loop — and rather than silently not starting one, which would leave
        # a leader that schedules nothing until the next transition.
        prev_t, prev_s = self._loop_thread, self._loop_stop
        stop = threading.Event()

        def term():
            if prev_t is not None and prev_t.is_alive():
                if prev_s is not None:
                    prev_s.set()
                prev_t.join()
            self.scheduler.run(stop)

        self._loop_expected = True
        self._loop_stop = stop
        self._loop_thread = threading.Thread(target=term, daemon=True)
        self._loop_thread.start()
        self._watch_threads()

    def _watch_threads(self) -> None:
        """Arm the watchdog over the loop + resolver threads (idempotent).
        ``_loop_expected`` distinguishes 'a loop should be running' from an
        intentional stop (lost lease, shutdown) — a watchdog-signaled term
        stays expected, so the sweep after the wedged thread finally exits
        restarts it."""
        self._watchdog.register(
            "loop",
            is_alive=lambda: (not getattr(self, "_loop_expected", False)
                              or self._stop.is_set()
                              or (self._loop_thread is not None
                                  and self._loop_thread.is_alive())),
            restart=self._revive_loop,
            # an intentionally-stopped loop (standby replica after a lost
            # lease) has no heartbeat to give; stall detection applies
            # only while a loop is supposed to be running
            busy=lambda: (getattr(self, "_loop_expected", False)
                          and not self._stop.is_set()))
        sch = self.scheduler
        self._watchdog.register(
            "resolver",
            is_alive=lambda: (sch._resolver_thread is None
                              or sch._resolver_thread.is_alive()
                              or self._stop.is_set()),
            restart=self._revive_resolver,
            # a resolver with no in-flight drains has nothing to beat about
            busy=lambda: bool(sch._pending))
        self._watchdog.start()

    def _revive_loop(self):
        """Watchdog path. A DEAD loop thread (BaseException, chaos kill)
        restarts immediately: the resident drain context is tainted —
        whatever the dead thread was mid-way through left the device state
        unaccountable — and a fresh term begins. A STALLED-but-alive
        thread is only SIGNALED to stop: two loops would mutate the
        scheduler's unsynchronized state concurrently (a Python thread
        cannot be killed), so the restart happens on the sweep after the
        wedged thread actually exits — and a thread merely stuck in a
        long first-touch compile resumes its (now stopping) term
        harmlessly. Returns False when no restart actually happened (the
        watchdog then doesn't count one). Runs under the loop lock so a
        revive can never race a leadership-change start/stop."""
        with self._loop_lock:
            if not self._loop_expected or self._stop.is_set():
                # leadership was lost (or the runner is stopping) between
                # the sweep and this call: a non-leader must not schedule
                return False
            t = self._loop_thread
            if t is not None and t.is_alive():
                if self._loop_stop is not None:
                    self._loop_stop.set()
                self.scheduler.taint_ctx()
                return False  # signaled only; restart on a later sweep
            self.scheduler.taint_ctx()
            self._start_loop_locked()
        self.publish_status()
        return True

    def _revive_resolver(self) -> None:
        self.scheduler.restart_resolver()
        self.publish_status()

    def _stop_loop(self):
        with self._loop_lock:
            # intentional stop: the watchdog must not revive
            self._loop_expected = False
            if self._loop_stop is not None:
                self._loop_stop.set()
            t = self._loop_thread
        if t is not None:
            t.join(timeout=5.0)

    def stop(self):
        self._stop.set()
        self._watchdog.stop()
        self.auditor.stop()
        self._stop_loop()
        self.queue.close()
        self.scheduler.close()
        self.factory.stop_all()

    def kill(self):
        """Crash simulation (recovery tests): tear the runner down WITHOUT
        the graceful-drain discipline — no resolve of in-flight device
        work, no binder flush, no status publish. Everything the dead
        incarnation assumed-but-never-bound or nominated must be
        reconstructable by a fresh runner from apiserver state alone;
        tests/test_chaos.py proves it is."""
        self._stop.set()
        self._watchdog.stop()
        self.auditor.stop()
        self._loop_expected = False
        if self._loop_stop is not None:
            self._loop_stop.set()
        self.queue.close()
        self.factory.stop_all()
