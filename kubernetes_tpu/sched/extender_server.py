"""Extender server — expose the TPU tensor scheduler to a foreign control
plane via the scheduler-extender webhook protocol.

The reference's precedent is the other direction only (``extender.go`` calls
out); here the same wire shapes (``ExtenderArgs`` in,
``ExtenderFilterResult``/``HostPriorityList`` out —
``staging/src/k8s.io/kube-scheduler/extender/v1/types.go``) make the
tensorized filter/score pipeline consumable by ANY scheduler that supports
extenders: point a stock kube-scheduler's ``extenders:`` config at this
server and its pods are filtered/scored by the one-shot [1,N] device program.

Cluster state: the caller either wires a clientset (nodes + bound pods are
listed per request) or pushes state via ``set_cluster`` (tests, embedding).

SUPERSEDED for new integrations by the gRPC sidecar
(``kubernetes_tpu/sidecar/``): the extender re-ships the full node list and
re-lists cluster state per request and has no staleness protocol, while the
sidecar holds a generation-tokened resident snapshot kept current by
deltas. This module remains as the compatibility path for stock schedulers
that only speak ``extenders:`` config.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

import numpy as np

from kubernetes_tpu.api.types import Node, Pod
from kubernetes_tpu.encode.snapshot import SnapshotEncoder
from kubernetes_tpu.sched.extender import MAX_EXTENDER_PRIORITY


class TPUExtenderServer:
    def __init__(self, client=None, host: str = "127.0.0.1", port: int = 0):
        self._client = client
        self._nodes: list[Node] = []
        self._bound: list[Pod] = []
        self._lock = threading.Lock()
        self._httpd = ThreadingHTTPServer((host, port), self._handler())
        self._httpd.daemon_threads = True
        self._thread: Optional[threading.Thread] = None

    # -- state -------------------------------------------------------------

    def set_cluster(self, nodes: list[Node], bound_pods: list[Pod]) -> None:
        with self._lock:
            self._nodes = list(nodes)
            self._bound = list(bound_pods)

    def _cluster(self):
        if self._client is not None:
            nodes = [Node.from_dict(n) for n in self._client.nodes().list()]
            bound = [p for p in (Pod.from_dict(d)
                                 for d in self._client.pods(None).list())
                     if p.spec.node_name]
            return nodes, bound
        with self._lock:
            return list(self._nodes), list(self._bound)

    # -- the one-pod device program ---------------------------------------

    def _evaluate(self, pod: Pod, node_names: Optional[list[str]]):
        """-> (names, feasible [N] bool, scores [N] f32) over the requested
        node subset (None = every known node)."""
        from kubernetes_tpu.models.schedule_step import evaluate
        nodes, bound = self._cluster()
        if node_names is not None:
            allow = set(node_names)
            nodes = [n for n in nodes if n.metadata.name in allow]
        names = [n.metadata.name for n in nodes]
        if not nodes:
            return [], np.zeros(0, bool), np.zeros(0, np.float32)
        enc = SnapshotEncoder()
        ct, meta = enc.encode_cluster(nodes, bound, pending_pods=[pod])
        pb = enc.encode_pods([pod], meta)
        res = evaluate(ct, pb, topo_keys=meta.topo_keys)
        feas = np.asarray(res.feasible)[0, :len(nodes)]
        scores = np.asarray(res.scores)[0, :len(nodes)]
        return names, feas, scores

    @staticmethod
    def _parse_args(payload: dict):
        """-> (pod, node names | None, request node items | None).
        The response must mirror the request shape: nodeCacheCapable callers
        send/read ``nodenames``; everyone else (including a stock
        kube-scheduler with the default nodeCacheCapable=false) sends full
        node objects and reads ``nodes.items`` back."""
        pod = Pod.from_dict(payload.get("pod") or {})
        if payload.get("nodenames") is not None:
            return pod, list(payload["nodenames"]), None
        items = ((payload.get("nodes") or {}).get("items"))
        if items is not None:
            return pod, [(n.get("metadata") or {}).get("name", "")
                         for n in items], list(items)
        return pod, None, None

    def _filter(self, payload: dict) -> dict:
        pod, node_names, req_items = self._parse_args(payload)
        names, feas, _ = self._evaluate(pod, node_names)
        ok = {n for n, f in zip(names, feas) if f}
        failed = {n: "node is not feasible for pod (TPU filter pipeline)"
                  for n, f in zip(names, feas) if not f}
        if req_items is not None:  # mirror the full-objects request shape
            keep = [it for it in req_items
                    if (it.get("metadata") or {}).get("name", "") in ok]
            return {"nodes": {"items": keep}, "failedNodes": failed}
        return {"nodenames": [n for n in names if n in ok],
                "failedNodes": failed}

    def _prioritize(self, payload: dict) -> list:
        pod, node_names, _req_items = self._parse_args(payload)
        names, feas, scores = self._evaluate(pod, node_names)
        # rescale feasible scores to the extender's 0..10 contract
        vals = np.where(feas, scores, -np.inf)
        finite = vals[np.isfinite(vals)]
        out = []
        for n, v in zip(names, vals):
            if not np.isfinite(v):
                out.append({"host": n, "score": 0})
                continue
            if finite.size and finite.max() > finite.min():
                s = (v - finite.min()) / (finite.max() - finite.min())
            else:
                s = 1.0
            out.append({"host": n, "score": int(round(
                float(s) * MAX_EXTENDER_PRIORITY))})
        return out

    # -- http --------------------------------------------------------------

    def _handler(self):
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_POST(self):
                n = int(self.headers.get("Content-Length") or 0)
                try:
                    payload = json.loads(self.rfile.read(n) or b"{}")
                    if self.path.rstrip("/").endswith("filter"):
                        body = server._filter(payload)
                    elif self.path.rstrip("/").endswith("prioritize"):
                        body = server._prioritize(payload)
                    else:
                        self.send_error(404)
                        return
                    data = json.dumps(body).encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(data)))
                    self.end_headers()
                    self.wfile.write(data)
                except Exception as e:  # wire errors into the protocol shape
                    data = json.dumps({"error": str(e)}).encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(data)))
                    self.end_headers()
                    self.wfile.write(data)
        return Handler

    @property
    def url(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"http://{host}:{port}"

    def start(self) -> "TPUExtenderServer":
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True, name="tpu-extender")
        self._thread.start()
        return self

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread:
            self._thread.join(timeout=2.0)
