"""BackgroundPlanner — three planners, one cluster image.

One cadence drives the autoscaler's scale-up/scale-down simulation, the
descheduler's eviction planning, and gang defrag against the scheduler's
device-resident cluster encoding. The shared ``ResidentPlanner``
(encode/overlay.py) hands each planner a row-permuted overlay VIEW of the
live image — zero cold full encodes while the image is fresh — and every
staleness/taint/mesh-epoch/in-flight condition declines into the planner's
existing cold-encode path, which produces a bit-identical plan.

What this loop owns beyond calling the planners:

catalog sync
    The planners' cold-fallback encoders are pointed at the cache
    encoder's live DRA/volume catalogs each cycle (identity-compared:
    ``set_dra``/``set_volumes`` bump the encoder's pod epoch, so rewiring
    only happens on an actual catalog swap). A resident overlay and its
    cold baseline then gate claims identically.

compile accounting
    A ``CompileCounter`` window brackets every cycle past warmup; XLA
    ``backend_compile`` events landing inside the window count into
    ``scheduler_planner_compiles_total`` and the published status. The
    PlannerLoop bench fails if this stays non-zero in the steady window.

status
    Per-planner overlay hit/decline tallies, cycle spans, and the
    steady-window compile count publish to the
    ``kubernetes-tpu-planner-status`` ConfigMap (``ktpu status`` renders
    the "Planners:" line from it).
"""

from __future__ import annotations

import json
import logging
import threading
from typing import Optional

from kubernetes_tpu.encode.overlay import CompileCounter, ResidentPlanner
from kubernetes_tpu.metrics.registry import (
    SCHEDULER_PLANNER_COMPILES,
    SCHEDULER_PLANNER_CYCLE_DURATION,
)
from kubernetes_tpu.utils.clock import REAL_CLOCK, rfc3339_from_epoch

_LOG = logging.getLogger(__name__)

PLANNER_CONFIGMAP = "kubernetes-tpu-planner-status"


class BackgroundPlanner:
    """The background planning cadence over one resident cluster image.

    ``scheduler`` is the live sched/scheduler.Scheduler (its
    ``resident_plan_view`` + cache feed the shared ResidentPlanner);
    ``autoscaler``/``descheduler`` are wired to that planner at
    construction — their own loops must NOT also be started, this cadence
    replaces them (gang defrag rides the descheduler's plan every cycle).
    """

    def __init__(self, client, scheduler, autoscaler=None, descheduler=None,
                 clock=None, status_namespace: str = "default",
                 descheduler_dry_run: bool = False, warmup_cycles: int = 2,
                 compile_counter: Optional[CompileCounter] = None):
        self.client = client
        self.scheduler = scheduler
        self.autoscaler = autoscaler
        self.descheduler = descheduler
        self.clock = clock or REAL_CLOCK
        self.status_namespace = status_namespace
        self.descheduler_dry_run = descheduler_dry_run
        self.warmup_cycles = warmup_cycles
        self.resident = ResidentPlanner(scheduler.resident_plan_view,
                                        scheduler.cache)
        if autoscaler is not None:
            autoscaler.resident = self.resident
        if descheduler is not None:
            descheduler.resident = self.resident
        self.compiles = compile_counter or CompileCounter()
        self.cycles = 0
        self.steady_compiles = 0
        self.interval: Optional[float] = None
        self._spans: dict[str, float] = {}
        self._last: dict = {"cycle": None}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ---- catalog sync ----------------------------------------------------

    def _sync_catalogs(self) -> None:
        cache = self.scheduler.cache
        dra = cache.dra_catalog
        vols = cache.volume_catalog
        for planner in (self.autoscaler, self.descheduler):
            enc = getattr(planner, "encoder", None)
            if enc is None:
                continue
            if dra is not None and enc.dra is not dra:
                enc.set_dra(dra)
            if vols is not None and enc.volumes is not vols:
                enc.set_volumes(vols)

    # ---- one cycle -------------------------------------------------------

    def run_once(self) -> dict:
        """One planning cycle: autoscaler RunOnce then descheduler RunOnce
        (which includes gang defrag), with the steady-window compile gate
        armed once past warmup. Returns a cycle summary."""
        summary: dict = {"cycle": self.cycles}
        self._sync_catalogs()
        steady = self.cycles >= self.warmup_cycles
        before = self.compiles.take()
        if steady:
            self.compiles.arm()
        try:
            if self.autoscaler is not None:
                t0 = self.clock.now()
                with SCHEDULER_PLANNER_CYCLE_DURATION.time(
                        {"planner": "autoscaler"}):
                    summary["autoscaler"] = self.autoscaler.run_once()
                self._spans["autoscaler"] = self.clock.now() - t0
            if self.descheduler is not None:
                t0 = self.clock.now()
                with SCHEDULER_PLANNER_CYCLE_DURATION.time(
                        {"planner": "descheduler"}):
                    summary["descheduler"] = self.descheduler.run_once(
                        dry_run=self.descheduler_dry_run)
                self._spans["descheduler"] = self.clock.now() - t0
        finally:
            if steady:
                self.compiles.disarm()
                fresh = self.compiles.take() - before
                if fresh:
                    SCHEDULER_PLANNER_COMPILES.inc(by=fresh)
                    self.steady_compiles += fresh
                summary["steadyCompiles"] = fresh
        self.cycles += 1
        self._last["cycle"] = {
            "at": rfc3339_from_epoch(self.clock.now()),
            "steady": steady,
            "spans": dict(self._spans),
        }
        self._publish_status(summary)
        return summary

    # ---- status ----------------------------------------------------------

    def status(self) -> dict:
        stats = self.resident.stats()
        planners = {}
        for name in ("autoscaler", "descheduler", "gangDefrag"):
            planners[name] = {
                "hits": stats["hits"].get(name, 0),
                "declines": sum(stats["declines"].get(name, {}).values()),
                "declineReasons": dict(stats["declines"].get(name, {})),
                "lastCycleSeconds": self._spans.get(name),
            }
        return {
            "cycles": self.cycles,
            "warmupCycles": self.warmup_cycles,
            "intervalSeconds": self.interval,
            "steadyCompiles": self.steady_compiles,
            "planners": planners,
            "lastCycle": self._last["cycle"],
        }

    def _publish_status(self, summary: dict) -> None:
        from kubernetes_tpu.utils.configmap import upsert_configmap
        upsert_configmap(
            self.client, self.status_namespace, PLANNER_CONFIGMAP,
            {"status": json.dumps(self.status(), indent=1),
             "lastProbeTime": rfc3339_from_epoch(self.clock.now())},
            site="planner_publish")

    # ---- loop ------------------------------------------------------------

    def start(self, interval: float = 2.0) -> "BackgroundPlanner":
        self.interval = interval

        def loop():
            while not self._stop.is_set():
                try:
                    self.run_once()
                except Exception:
                    _LOG.exception("background planner cycle failed")
                self._stop.wait(interval)

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="background-planner")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
