"""Scheduler cache — cluster state aggregation + assume/expire + snapshots.

Reference: ``pkg/scheduler/internal/cache/cache.go`` (``cacheImpl``:
AssumePod/FinishBinding/ForgetPod/UpdateSnapshot with generation counters).

The TPU twist: the expensive artifact is not per-node NodeInfo structs but the
encoded ClusterTensors. ``snapshot()`` re-encodes only when the cluster
generation moved (any node/pod add/update/remove or assume/forget), and the
persistent SnapshotEncoder keeps intern tables stable across snapshots so
re-encoding is allocation-churn only, not dictionary churn.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from kubernetes_tpu.api.types import Node, Pod, deep_copy
from kubernetes_tpu.encode.snapshot import ClusterTensors, SnapshotEncoder, SnapshotMeta


class SchedulerCache:
    def __init__(self, assume_ttl: float = 30.0):
        self._lock = threading.Lock()
        # Serializes ENCODER work (snapshot/encode_pods/overlay): the state
        # lock above stays cheap for informer handlers, while concurrent
        # snapshot() callers (scheduling loop + binder workers' volume path)
        # must not interleave delta pops/encodes on the shared encoder.
        self._encode_lock = threading.Lock()
        self._nodes: dict[str, Node] = {}  # guarded by: self._lock
        self._pods: dict[str, Pod] = {}  # guarded by: self._lock
        self._assumed: dict[str, tuple[Pod, float]] = {}  # guarded by: self._lock
        self._generation = 0  # guarded by: self._lock
        self._encoder = SnapshotEncoder()
        # churn headroom: free node rows absorb node ADDs as device patches,
        # spare label-value ids absorb the new values they intern (every
        # node interns its own name) — without these any node event would
        # overflow its bucket and force a rebuild
        import os
        self._encoder.node_headroom = int(
            os.environ.get("KTPU_NODE_HEADROOM", "64"))
        self._encoder.value_headroom = int(
            os.environ.get("KTPU_VALUE_HEADROOM", "256"))
        # fresh namespaces (e.g. churn traffic) must not widen the NSB
        # bucket mid-stream: that recompiles the drain inside the window
        self._encoder.ns_headroom = int(
            os.environ.get("KTPU_NS_HEADROOM", "16"))
        self._cached: Optional[tuple[int, ClusterTensors, SnapshotMeta]] = None  # guarded by: self._lock
        self.assume_ttl = assume_ttl
        self._volumes = None  # guarded by: self._lock (VolumeCatalog once any PVC/PV/SC appears)
        self._dra = None      # guarded by: self._lock (DraCatalog once any resource.k8s.io object appears)
        self._namespace_labels: dict[str, dict] = {}  # guarded by: self._lock
        # incremental-snapshot delta tracking (Cache.UpdateSnapshot analog):
        # pod churn accumulates here and patches the cached encoding in place;
        # anything structural (node add/remove, volumes) forces a full encode.
        self._delta_upserts: dict[str, Pod] = {}  # guarded by: self._lock
        self._delta_deletes: set[str] = set()  # guarded by: self._lock
        self._needs_full = True  # guarded by: self._lock
        # ---- ordered delta LOG for the device-resident drain context ----
        # Every encoding-relevant mutation appends (seq, op, payload); the
        # drain context replays entries since its last-consumed seq as
        # device-side patches (encode/patch.py) instead of dying on any
        # foreign change. Bounded; a consumer older than the window rebuilds.
        self._dlog: list[tuple] = []  # guarded by: self._lock
        self._dlog_start = 0   # guarded by: self._lock (seq of _dlog[0])
        self._dlog_seq = 0     # guarded by: self._lock (seq of the NEXT entry)
        self._snap_seq = 0     # guarded by: self._lock (log seq captured with the last snapshot)
        self._dlog_max = 100_000
        # encode-relevant node fingerprints: heartbeats that only touch
        # status/conditions must not invalidate the encoding at all
        self._node_fps: dict[str, tuple] = {}  # guarded by: self._lock
        # observability: full re-encodes performed by snapshot() (the
        # autoscaler's overlay path depends on snapshot freshness)
        self._full_encodes = 0  # guarded by: self._lock
        # active ("pods","nodes") scheduling mesh, or None (single-device).
        # The scheduler installs it (Scheduler.set_mesh); staging helpers
        # below then device_put encodings SHARDED so the drain programs run
        # under GSPMD instead of on one chip.
        self._mesh = None
        # pre-sharded double-buffered batch staging (sched/staging.py):
        # batch K+1 uploads on the background stager thread while batch K
        # runs; dispatch redeems a buffer swap. KTPU_STAGE_ARENA=0 (or
        # SchedulerConfiguration.staging_arena via configure_staging)
        # restores the legacy inline device_put path everywhere.
        from kubernetes_tpu.sched.staging import StagingArena
        self._arena = StagingArena()
        self._staging_enabled = os.environ.get(
            "KTPU_STAGE_ARENA", "1") != "0"

    # ---- device mesh -----------------------------------------------------

    def set_mesh(self, mesh) -> None:
        self._mesh = mesh
        # layout change: in-flight staged buffers carry the OLD shardings
        self._arena.invalidate()

    @property
    def mesh(self):
        return self._mesh

    def configure_staging(self, enabled: bool) -> None:
        """Config-level arena switch (the KTPU_STAGE_ARENA env read at
        construction still overrides OFF for bench A/Bs)."""
        import os as _os
        if _os.environ.get("KTPU_STAGE_ARENA") == "0":
            enabled = False
        self._staging_enabled = bool(enabled)

    def stage_submit(self, pb_stack):
        """Hand the final stacked drain batch to the staging arena: the
        background thread uploads it PRE-SHARDED while the scheduling
        thread finishes the cycle's host work (patch compile, sentinel
        capture) and the previous drain still executes. Returns a ticket
        for stage_redeem, or None (arena off / single-device / buffer
        full) — the dispatch then stages inline as before."""
        if not self._staging_enabled or self._mesh is None:
            return None
        return self._arena.submit(pb_stack, self._mesh)

    def stage_redeem(self, ticket):
        """Redeem a stage_submit ticket: the pre-staged device buffers, or
        None (invalidated/failed/timed out — caller stages inline)."""
        if ticket is None:
            return None
        return self._arena.redeem(ticket, self._mesh)

    def close_staging(self) -> None:
        self._arena.close()

    def stage_drain_batch(self, pb_stack):
        """INLINE staging of a stacked drain batch [B,P,...] — the
        fallback half of the staging pair (the steady state redeems a
        stage_submit ticket via stage_redeem instead; the scheduler's
        _stage_batch owns that flow and its span attribution). Under a
        mesh: one device_put split over "pods". Single-device: one
        EXPLICIT device_put so the drain dispatch performs zero implicit
        transfers (the transfer-guard invariant) at the same cost the
        jit's implicit staging paid."""
        import jax
        from kubernetes_tpu.metrics.registry import STAGE_BYTES
        from kubernetes_tpu.sched.staging import _tree_nbytes
        if self._mesh is None:
            staged = jax.device_put(pb_stack)
        else:
            from kubernetes_tpu.parallel.mesh import stack_shardings
            staged = jax.device_put(pb_stack,
                                    stack_shardings(self._mesh, pb_stack))
        STAGE_BYTES.inc({"path": "inline"}, by=_tree_nbytes(pb_stack))
        return staged

    def stage_patch(self, patch):
        """Explicitly stage a compiled churn patch's host arrays (~KB)
        before the dispatch that consumes them: replicated under a mesh,
        one device_put single-device — the fused drain then receives ONLY
        device-resident inputs (zero implicit transfers at dispatch)."""
        if patch is None:
            return None
        import jax
        if self._mesh is None:
            return jax.device_put(patch)
        from kubernetes_tpu.parallel.mesh import replicated
        rep = replicated(self._mesh)
        return jax.device_put(
            patch, jax.tree_util.tree_map(lambda _l: rep, patch))

    def staging_stats(self) -> dict:
        """Arena health for ktpu status / bench legs."""
        return dict(self._arena.stats(), enabled=self._staging_enabled)

    def request_vector(self, pod: Pod, resources: list) -> "np.ndarray":
        """One pod's scaled request vector on ``resources`` (the resident
        shadow's catch-up source) — same ``_request_vector`` the encode
        and patch paths use, under the encode lock (DRA catalog reads)."""
        with self._encode_lock:
            return self._encoder._request_vector(pod, resources)

    def with_encoder(self, fn):
        """Run ``fn(encoder)`` under the encode lock — the resident
        planners (encode/overlay.py) encode derived pod batches and build
        template planes against the LIVE encoder's intern tables, which
        must not interleave with snapshot/overlay work on other threads."""
        with self._encode_lock:
            return fn(self._encoder)

    # ---- delta log (drain-context patch feed) ----------------------------

    def _log_locked(self, op: str, payload):
        self._dlog.append((self._dlog_seq, op, payload))
        self._dlog_seq += 1
        if len(self._dlog) > self._dlog_max:
            drop = len(self._dlog) // 2
            del self._dlog[:drop]
            self._dlog_start += drop

    def deltas_since(self, seq: int):
        """Log entries with sequence >= ``seq`` in order, or None when the
        window no longer reaches back that far (consumer must rebuild)."""
        with self._lock:
            if seq < self._dlog_start:
                return None
            return self._dlog[seq - self._dlog_start:]

    def log_seq(self) -> int:
        with self._lock:
            return self._dlog_seq

    def last_snapshot_seq(self) -> int:
        """The log seq captured atomically with the last snapshot's state:
        a context built from that snapshot starts consuming here."""
        with self._lock:
            return self._snap_seq

    # ---- volume catalog (PVC/PV/StorageClass informers feed this) --------

    def update_volume_object(self, kind: str, obj: dict, deleted: bool = False):
        """Track PVC/PV/StorageClass state for the VolumeBinding tensors."""
        from kubernetes_tpu.sched.volumebinding import VolumeCatalog
        with self._lock:
            if self._volumes is None:
                self._volumes = VolumeCatalog()
            md = obj.get("metadata") or {}
            if kind == "PersistentVolumeClaim":
                key = (md.get("namespace", "default"), md.get("name", ""))
                space = self._volumes.pvcs
            elif kind == "PersistentVolume":
                key = md.get("name", "")
                space = self._volumes.pvs
            else:
                key = md.get("name", "")
                space = self._volumes.storage_classes
            if deleted:
                space.pop(key, None)
            else:
                space[key] = obj
            self._encoder.set_volumes(self._volumes)
            self._generation += 1
            self._needs_full = True
            self._log_locked("full", None)

    @property
    def volume_catalog(self):
        with self._lock:
            return self._volumes

    # ---- DRA objects (resource.k8s.io informers feed this) ---------------

    def update_dra_object(self, kind: str, obj: dict, deleted: bool = False):
        """Track ResourceClaim/DeviceClass/ResourceSlice state; device
        classes become dra:<class> resources in the next encoding.

        Claim STATUS churn (allocation/reservedFor — which the scheduler
        itself writes on every bind of a claimed pod) must not invalidate
        the cluster encoding: pod batches read the live catalog at encode
        time, and the cluster tensors only depend on claim SPECS (bound
        pods' demands), slices, and the class set."""
        from kubernetes_tpu.sched.dra import DraCatalog
        with self._lock:
            if self._dra is None:
                self._dra = DraCatalog()
            md = obj.get("metadata") or {}
            if kind == "ResourceClaim":
                key = (md.get("namespace", "default"), md.get("name", ""))
                space = self._dra.claims
            elif kind == "DeviceClass":
                key = md.get("name", "")
                space = self._dra.classes
            elif kind == "ResourceSlice":
                key = md.get("name", "")
                space = self._dra.slices
            else:
                return
            old = space.get(key)
            if deleted:
                if space.pop(key, None) is None:
                    return
            else:
                space[key] = obj
            if (kind == "ResourceClaim" and old is not None and not deleted
                    and DraCatalog.claim_demands(old)
                    == DraCatalog.claim_demands(obj)):
                # status-only change: encoding-neutral. Checked BEFORE
                # set_dra — the scheduler writes claim status on every bind
                # of a claimed pod, and letting that bump the encoder's pod
                # epoch would invalidate the whole precompile cache per
                # bind (the catalog object is shared and already mutated
                # in place above, so skipping set_dra loses nothing).
                return
            self._encoder.set_dra(self._dra)
            self._generation += 1
            self._needs_full = True
            self._log_locked("full", None)

    @property
    def dra_catalog(self):
        with self._lock:
            return self._dra

    # ---- namespace labels (Namespace informer feeds this) ----------------

    def update_namespace(self, obj: dict, deleted: bool = False):
        """Track namespace labels so affinity terms' namespaceSelector
        resolves at encode time (GetNamespaceLabelsSnapshot analog)."""
        from kubernetes_tpu.encode.snapshot import TENANT_LABEL
        with self._lock:
            md = obj.get("metadata") or {}
            name = md.get("name", "")
            if deleted:
                old = self._namespace_labels.pop(name, None)
                if old is None:
                    return
                tenants = {(old or {}).get(TENANT_LABEL)}
            else:
                new = dict(md.get("labels") or {})
                old = self._namespace_labels.get(name)
                if old == new:
                    return  # label-neutral churn: keep the encoding valid
                self._namespace_labels[name] = new
                # per-tenant catalog-epoch discipline: nsSelector resolution
                # is tenant-scoped, so only the touched tenants' precompiled
                # pod records go stale (old AND new tenant when relabelled)
                tenants = {new.get(TENANT_LABEL),
                           (old or {}).get(TENANT_LABEL)}
            self._encoder.set_namespaces(self._namespace_labels,
                                         changed_tenants=tenants)
            self._generation += 1
            # Pod batches always read the fresh snapshot at encode time; the
            # CLUSTER encoding only goes stale if an existing pod's anti term
            # actually resolved a namespaceSelector against the old labels.
            if self._encoder.cluster_depends_on_namespace_labels:
                self._needs_full = True
                self._log_locked("full", None)

    # ---- node events -----------------------------------------------------

    @staticmethod
    def _node_fp(node: Node) -> tuple:
        """Fingerprint of the encode-relevant node fields; status-only churn
        (heartbeat conditions) leaves it unchanged."""
        return (
            tuple(sorted(node.status.allocatable.items())),
            tuple(sorted(node.metadata.labels.items())),
            tuple((t.key, t.value, t.effect) for t in node.spec.taints),
            node.spec.unschedulable,
            tuple((tuple(i.names[:1]), i.size_bytes)
                  for i in node.status.images),
        )

    def add_node(self, node: Node):
        with self._lock:
            fp = self._node_fp(node)
            prev = self._node_fps.get(node.metadata.name)
            self._nodes[node.metadata.name] = node
            if prev == fp:
                return  # heartbeat-only update: encoding unaffected
            self._node_fps[node.metadata.name] = fp
            self._generation += 1
            self._needs_full = True
            self._log_locked("node", node)

    def update_node(self, node: Node):
        self.add_node(node)

    def remove_node(self, name: str):
        with self._lock:
            if self._nodes.pop(name, None) is not None:
                self._node_fps.pop(name, None)
                self._generation += 1
                self._needs_full = True
                self._log_locked("nodedel", name)

    # ---- pod events ------------------------------------------------------

    def add_pod(self, pod: Pod):
        """Bound pod observed (informer). Confirms an assume if present.

        Confirmation of an assume on the SAME node is encoding-neutral: the
        assume already patched this pod into the tensors, and nothing the
        encoder reads (node, namespace, labels, requests) changes between
        the assumed copy and the watch-confirmed object — so the cached
        encoding stays valid and the confirm costs a dict move, not a
        tensor patch. Under a binding storm this removes one incremental
        patch per bound pod (the whole fleet confirms within seconds).

        STATUS-only churn on an already-bound pod is encoding-neutral too
        (the pod twin of the node-fingerprint check): kubelets rewrite
        ``status`` on every sync, and each such MODIFIED used to append a
        ``pod`` delta — at fleet scale that made nearly every drain cycle
        compile a patch over hundreds of unchanged pods (and cross patch
        write-buckets, recompiling the fold program mid-window; the bulk
        of MULTICHIP_r06's 1.4-1.9s ctx_patch_apply was exactly this).
        The encoder reads labels + spec only, so equality there keeps the
        encoding valid; the stored object still refreshes."""
        with self._lock:
            if not pod.spec.node_name:
                return
            prior = self._assumed.pop(pod.key, None)
            old = self._pods.get(pod.key)
            self._pods[pod.key] = pod
            if prior is not None:
                ap = prior[0]
                if (ap.spec.node_name == pod.spec.node_name
                        and ap.metadata.labels == pod.metadata.labels
                        and pod.key not in self._delta_deletes):
                    return  # pure confirmation: encoding unaffected
            elif (old is not None and pod.key not in self._delta_deletes
                    and old.metadata.labels == pod.metadata.labels
                    and old.spec.to_dict() == pod.spec.to_dict()):
                return  # status-only update: encoding unaffected
            self._generation += 1
            self._delta_upserts[pod.key] = pod
            self._delta_deletes.discard(pod.key)
            self._log_locked("pod", pod)
            # bound: it will never pass through encode_pods again
            self._encoder.pod_cache_discard(pod.key)

    def update_pod(self, pod: Pod):
        self.add_pod(pod)

    def confirm(self, pod_key: str, node_name: str, labels: dict,
                spec: Optional[dict] = None) -> bool:
        """Fast-path bind confirmation: promote the assumed copy to bound
        when the watch event matches it — the dict-level twin of add_pod's
        pure-confirmation branch. Lets the informer skip a full
        Pod.from_dict per binding event: under a gang bind storm every bound
        pod produces exactly one MODIFIED whose only news is the node the
        cache already assumed.

        ``spec``: the event's raw spec dict; when given, it must equal the
        assumed copy's spec (nodeName aside) or the promotion is refused —
        a spec PUT racing the bind would otherwise install the stale assumed
        copy as bound with no later event to heal it (add_pod stores the
        fresh watch object instead, so the fallback self-heals). Returns
        False when there is nothing to confirm (caller falls back)."""
        with self._lock:
            prior = self._assumed.get(pod_key)
            if prior is None or pod_key in self._delta_deletes:
                return False
            ap = prior[0]
            if ap.spec.node_name != node_name or ap.metadata.labels != labels:
                return False
            if spec is not None:
                mine = ap.spec.to_dict()
                mine.pop("nodeName", None)
                theirs = {k: v for k, v in spec.items() if k != "nodeName"}
                if mine != theirs:
                    return False
            del self._assumed[pod_key]
            self._pods[pod_key] = ap
            self._encoder.pod_cache_discard(pod_key)
            return True

    def is_bound(self, pod_key: str) -> bool:
        """True if the pod is recorded as bound (confirmed via watch)."""
        with self._lock:
            return pod_key in self._pods

    def is_assumed_or_bound(self, pod_key: str) -> bool:
        """True if the pod holds capacity (assumed OR confirmed) — the
        mid-cycle rescue path must not requeue a pod whose placement this
        very cycle already committed."""
        with self._lock:
            return pod_key in self._pods or pod_key in self._assumed

    def remove_pod(self, pod_key: str):
        with self._lock:
            existed = self._pods.pop(pod_key, None) or self._assumed.pop(pod_key, None)
            self._encoder.pod_cache_discard(pod_key)
            if existed:
                self._generation += 1
                self._delta_upserts.pop(pod_key, None)
                self._delta_deletes.add(pod_key)
                self._log_locked("poddel", pod_key)

    # ---- optimistic binding ---------------------------------------------

    def assume(self, pod: Pod, node_name: str):
        """Optimistically treat the pod as bound NOW (AssumePod); the binding
        confirms via add_pod or expires after assume_ttl. Stores a copy — the
        caller's pod object stays unbound so a failed binding can requeue it
        cleanly (the reference deep-copies into the cache for the same
        reason). The copy is two-level (new Pod + new spec, shared leaves):
        nothing mutates pod subtrees in place — informers build a fresh Pod
        per event — so a structural deep copy (~30us/pod, the old path) only
        burned time on the hot batch loop."""
        import dataclasses
        with self._lock:
            p = dataclasses.replace(
                pod, spec=dataclasses.replace(pod.spec, node_name=node_name))
            self._assumed[p.key] = (p, time.time() + self.assume_ttl)
            self._generation += 1
            self._delta_upserts[p.key] = p
            self._delta_deletes.discard(p.key)
            self._log_locked("assume", (p.key, node_name, p))
            # placed: the record is dead unless the binding fails, and a
            # rare bind-failure retry recompiling one pod beats keeping
            # every placed pod's record alive (forget() keeps nothing)
            self._encoder.pod_cache_discard(p.key)

    def assume_many(self, pairs: list) -> None:
        """assume() for a whole drain's winners in ONE lock pass — the gang
        step commits thousands of placements per resolve, and a lock
        round-trip per pod was measurable against the connected window.
        ``pairs``: [(Pod, node_name)]. Advances the generation by exactly
        len(pairs), which the drain context's resolve-side currency check
        (scheduler._resolve_pending) counts on."""
        import dataclasses
        with self._lock:
            deadline = time.time() + self.assume_ttl
            for pod, node_name in pairs:
                p = dataclasses.replace(
                    pod, spec=dataclasses.replace(pod.spec,
                                                  node_name=node_name))
                self._assumed[p.key] = (p, deadline)
                self._delta_upserts[p.key] = p
                self._delta_deletes.discard(p.key)
                self._log_locked("assume", (p.key, node_name, p))
                self._encoder.pod_cache_discard(p.key)
            self._generation += len(pairs)

    def finish_binding(self, pod_key: str):
        """Binding RPC done; keep assumed until the watch confirms (TTL holds)."""

    def forget(self, pod_key: str):
        """Binding failed: drop the assumption (ForgetPod)."""
        with self._lock:
            if self._assumed.pop(pod_key, None):
                self._generation += 1
                self._delta_upserts.pop(pod_key, None)
                self._delta_deletes.add(pod_key)
                self._log_locked("poddel", pod_key)

    def _expire_assumed_locked(self):
        now = time.time()
        expired = [k for k, (_, dl) in self._assumed.items() if dl < now]
        for k in expired:
            del self._assumed[k]
            self._delta_upserts.pop(k, None)
            self._delta_deletes.add(k)
            self._log_locked("poddel", k)
        if expired:
            self._generation += 1

    # ---- snapshot --------------------------------------------------------

    def snapshot(self, pending_pods: Optional[list[Pod]] = None,
                 slot_headroom: int = 0):
        """-> (nodes list, ClusterTensors, SnapshotMeta).

        Three paths, mirroring ``Cache.UpdateSnapshot``:
          clean     — nothing changed: return the cached encoding.
          pod delta — only pod binds/unbinds since the last snapshot: patch
                      the cached tensors in place (apply_pod_deltas).
          full      — structural change (node add/remove/relabel, volumes,
                      bucket overflow, new resource kind): re-encode.

        ``pending_pods`` widen the resource axis; passing a batch with a new
        extended resource forces the full path (rare).

        Locking: state is COLLECTED under the state lock, then the encode
        runs under the ENCODE lock only — the state lock is shared with
        every informer handler, and holding it across a multi-hundred-ms
        encode made each watch event (add_pod) stall behind the batch cycle
        (lock-convoy, not useful work). Deltas that arrive mid-encode simply
        stay queued for the next snapshot; if a structural change lands
        mid-encode, _needs_full survives (we only clear flags captured
        before the encode began). The encode lock serializes concurrent
        snapshot() callers (scheduling loop + binder workers) so delta pops
        can't interleave on the shared encoder.
        """
        with self._encode_lock:
            return self._snapshot_serialized(pending_pods, slot_headroom)

    def _export_gauges_locked(self):
        from kubernetes_tpu.metrics.registry import (
            CACHE_FULL_ENCODES,
            CACHE_GENERATION,
            ENCODE_POD_CACHE_HITS,
            ENCODE_POD_CACHE_MISSES,
            ENCODE_POD_ROWS_FILLED,
            ENCODE_POD_ROWS_STACKED,
        )
        CACHE_GENERATION.set(self._generation)
        CACHE_FULL_ENCODES.set(self._full_encodes)
        ENCODE_POD_CACHE_HITS.set(self._encoder.pod_cache_hits)
        ENCODE_POD_CACHE_MISSES.set(self._encoder.pod_cache_misses)
        ENCODE_POD_ROWS_STACKED.set(self._encoder.pod_rows_stacked)
        ENCODE_POD_ROWS_FILLED.set(self._encoder.pod_rows_filled)

    def _snapshot_serialized(self, pending_pods, slot_headroom):
        with self._lock:
            self._expire_assumed_locked()
            self._export_gauges_locked()
            self._snap_seq = self._dlog_seq
            nodes = list(self._nodes.values())
            gen = self._generation
            cached = self._cached
            needs_full = self._needs_full
            upserts = deletes = None
            bound = None
            if cached is not None and not needs_full:
                _, ct0, meta0 = cached
                known = set(meta0.resources)
                widen = any(r not in known for p in (pending_pods or [])
                            for r in p.resource_requests())
                if not widen:
                    if not self._delta_upserts and not self._delta_deletes:
                        return nodes, ct0, meta0
                    upserts = list(self._delta_upserts.values())
                    deletes = list(self._delta_deletes)
                    self._delta_upserts.clear()
                    self._delta_deletes.clear()
            if upserts is None:
                bound = (list(self._pods.values())
                         + [p for p, _ in self._assumed.values()])
                self._delta_upserts.clear()
                self._delta_deletes.clear()

        # ---- encode outside the lock (scheduler thread only) -------------
        if upserts is not None:
            _, ct0, meta0 = cached
            patched = self._encoder.apply_pod_deltas(ct0, meta0, upserts,
                                                     deletes)
            if patched is not None:
                with self._lock:
                    self._cached = (gen, patched, meta0)
                return nodes, patched, meta0
            # patch didn't fit the buckets: fall through to a full encode,
            # folding the popped deltas back into the bound view
            with self._lock:
                bound = (list(self._pods.values())
                         + [p for p, _ in self._assumed.values()])
                self._delta_upserts.clear()
                self._delta_deletes.clear()
        ct, meta = self._encoder.encode_cluster(nodes, bound,
                                                pending_pods=pending_pods,
                                                slot_headroom=slot_headroom)
        with self._lock:
            self._cached = (gen, ct, meta)
            if self._generation == gen:
                self._needs_full = False
            self._full_encodes += 1
            self._export_gauges_locked()
        return nodes, ct, meta

    def patch_state_fork(self):
        """CtxPatchState forked from the encoder's post-encode bookkeeping
        (encode/patch.py) — the drain context's private slot/row maps."""
        from kubernetes_tpu.encode.patch import fork_patch_state
        with self._encode_lock:
            return fork_patch_state(self._encoder._patch)

    def compile_ctx_patch(self, meta, cs, entries, nom_target: dict,
                          nom_bucket: int, fold_floor: int = 0):
        """compile_patch under the encode lock (interning is shared with
        snapshot/encode_pods and must not interleave)."""
        from kubernetes_tpu.encode.patch import compile_patch
        with self._encode_lock:
            return compile_patch(self._encoder, meta, cs, entries,
                                 nom_target, nom_bucket,
                                 fold_floor=fold_floor)

    def encode_pods(self, pods: list[Pod], meta: SnapshotMeta,
                    min_p: int = 1, cache_rows: bool = True):
        with self._encode_lock:
            return self._encoder.encode_pods(pods, meta, min_p=min_p,
                                             cache_rows=cache_rows)

    def precompile_pod(self, pod: Pod) -> None:
        """Informer-event-time half of the incremental encode: compile the
        pod's encode record NOW (watch thread) so the drain's encode_pods
        later is array-fill only. NON-BLOCKING on the encode lock — if the
        scheduling loop is mid-encode, skipping is strictly better than
        convoying the watch thread behind a multi-hundred-ms encode (the
        pod simply compiles on the hot path as before)."""
        if not self._encode_lock.acquire(blocking=False):
            return
        try:
            self._encoder.precompile_pod(pod)
        except Exception:  # ktpu-lint: disable=KTL002 -- best-effort warm-up; encode_pods recompiles this pod authoritatively on the hot path, so a precompile failure costs latency, never correctness
            pass
        finally:
            self._encode_lock.release()

    def encode_cache_stats(self) -> dict[str, int]:
        """Hit/miss counters of the pod compile cache plus the row-pack
        assembly split (benchmarks report these: a healthy connected run
        shows hits >> misses and rows_stacked >> rows_filled — fill-only
        cycles do no per-pod fill work at all)."""
        return {"hits": self._encoder.pod_cache_hits,
                "misses": self._encoder.pod_cache_misses,
                "rows_stacked": self._encoder.pod_rows_stacked,
                "rows_filled": self._encoder.pod_rows_filled}

    def overlay_nominated(self, ct, meta, entries, min_m: int = 0):
        """ct with nominated-pod reservations applied (encoder.with_nominated);
        entries: [(node_name, priority, Pod)]."""
        with self._encode_lock:
            return self._encoder.with_nominated(ct, meta, entries,
                                                min_m=min_m)

    def get_node(self, name: str) -> Optional[Node]:
        """Cheap single-node lookup (binder-side volume labels); avoids a
        full snapshot from non-scheduling threads."""
        with self._lock:
            return self._nodes.get(name)

    def list_nodes(self) -> list[Node]:
        """Plain node list WITHOUT an encode pass — the oracle fallback
        path reads typed objects only, so a broken device layer never
        stands between it and the cluster state."""
        with self._lock:
            return list(self._nodes.values())

    def namespace_labels(self) -> dict[str, dict]:
        """Namespace -> labels view (the oracle's namespaceSelector
        resolution source)."""
        with self._lock:
            return dict(self._namespace_labels)

    def delta_info(self) -> tuple[int, set, bool, bool]:
        """-> (generation, pending upsert keys, any deletes pending,
        needs_full). The device-resident drain uses this to prove its HBM
        replica of the encoding is still exactly one fold behind the cache
        (every pending delta is an assume it already folded device-side)."""
        with self._lock:
            return (self._generation, set(self._delta_upserts),
                    bool(self._delta_deletes), self._needs_full)

    def bound_pods(self, include_assumed: bool = True) -> list[Pod]:
        with self._lock:
            out = list(self._pods.values())
            if include_assumed:
                out += [p for p, _ in self._assumed.values()]
            return out

    def stats(self) -> dict[str, int]:
        with self._lock:
            return {"nodes": len(self._nodes), "pods": len(self._pods),
                    "assumed": len(self._assumed),
                    "generation": self._generation,
                    "full_encodes": self._full_encodes}

    def audit_view(self) -> dict:
        """One-lock-pass consistent view for the invariant auditor:
        confirmed-bound and assumed placements (key -> node), the node-name
        set, and the generation. Plain values only — the auditor runs on
        its own thread and must never hold references that alias the
        cache's mutable state."""
        with self._lock:
            return {
                "bound": {k: p.spec.node_name
                          for k, p in self._pods.items()},
                "assumed": {k: p.spec.node_name
                            for k, (p, _dl) in self._assumed.items()},
                "nodes": set(self._nodes),
                "generation": self._generation,
            }
