"""Scheduler cache — cluster state aggregation + assume/expire + snapshots.

Reference: ``pkg/scheduler/internal/cache/cache.go`` (``cacheImpl``:
AssumePod/FinishBinding/ForgetPod/UpdateSnapshot with generation counters).

The TPU twist: the expensive artifact is not per-node NodeInfo structs but the
encoded ClusterTensors. ``snapshot()`` re-encodes only when the cluster
generation moved (any node/pod add/update/remove or assume/forget), and the
persistent SnapshotEncoder keeps intern tables stable across snapshots so
re-encoding is allocation-churn only, not dictionary churn.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from kubernetes_tpu.api.types import Node, Pod, deep_copy
from kubernetes_tpu.encode.snapshot import ClusterTensors, SnapshotEncoder, SnapshotMeta


class SchedulerCache:
    def __init__(self, assume_ttl: float = 30.0):
        self._lock = threading.Lock()
        self._nodes: dict[str, Node] = {}
        self._pods: dict[str, Pod] = {}          # bound (confirmed) pods by key
        self._assumed: dict[str, tuple[Pod, float]] = {}  # key -> (pod, deadline)
        self._generation = 0
        self._encoder = SnapshotEncoder()
        self._cached: Optional[tuple[int, ClusterTensors, SnapshotMeta]] = None
        self.assume_ttl = assume_ttl
        self._volumes = None  # VolumeCatalog once any PVC/PV/SC appears

    # ---- volume catalog (PVC/PV/StorageClass informers feed this) --------

    def update_volume_object(self, kind: str, obj: dict, deleted: bool = False):
        """Track PVC/PV/StorageClass state for the VolumeBinding tensors."""
        from kubernetes_tpu.sched.volumebinding import VolumeCatalog
        with self._lock:
            if self._volumes is None:
                self._volumes = VolumeCatalog()
            md = obj.get("metadata") or {}
            if kind == "PersistentVolumeClaim":
                key = (md.get("namespace", "default"), md.get("name", ""))
                space = self._volumes.pvcs
            elif kind == "PersistentVolume":
                key = md.get("name", "")
                space = self._volumes.pvs
            else:
                key = md.get("name", "")
                space = self._volumes.storage_classes
            if deleted:
                space.pop(key, None)
            else:
                space[key] = obj
            self._encoder.set_volumes(self._volumes)
            self._generation += 1

    @property
    def volume_catalog(self):
        with self._lock:
            return self._volumes

    # ---- node events -----------------------------------------------------

    def add_node(self, node: Node):
        with self._lock:
            self._nodes[node.metadata.name] = node
            self._generation += 1

    def update_node(self, node: Node):
        self.add_node(node)

    def remove_node(self, name: str):
        with self._lock:
            self._nodes.pop(name, None)
            self._generation += 1

    # ---- pod events ------------------------------------------------------

    def add_pod(self, pod: Pod):
        """Bound pod observed (informer). Confirms an assume if present."""
        with self._lock:
            if not pod.spec.node_name:
                return
            self._assumed.pop(pod.key, None)
            self._pods[pod.key] = pod
            self._generation += 1

    def update_pod(self, pod: Pod):
        self.add_pod(pod)

    def is_bound(self, pod_key: str) -> bool:
        """True if the pod is recorded as bound (confirmed via watch)."""
        with self._lock:
            return pod_key in self._pods

    def remove_pod(self, pod_key: str):
        with self._lock:
            existed = self._pods.pop(pod_key, None) or self._assumed.pop(pod_key, None)
            if existed:
                self._generation += 1

    # ---- optimistic binding ---------------------------------------------

    def assume(self, pod: Pod, node_name: str):
        """Optimistically treat the pod as bound NOW (AssumePod); the binding
        confirms via add_pod or expires after assume_ttl. Stores a COPY — the
        caller's pod object stays unbound so a failed binding can requeue it
        cleanly (the reference deep-copies into the cache for the same reason)."""
        with self._lock:
            p = deep_copy(pod)
            p.spec.node_name = node_name
            self._assumed[p.key] = (p, time.time() + self.assume_ttl)
            self._generation += 1

    def finish_binding(self, pod_key: str):
        """Binding RPC done; keep assumed until the watch confirms (TTL holds)."""

    def forget(self, pod_key: str):
        """Binding failed: drop the assumption (ForgetPod)."""
        with self._lock:
            if self._assumed.pop(pod_key, None):
                self._generation += 1

    def _expire_assumed_locked(self):
        now = time.time()
        expired = [k for k, (_, dl) in self._assumed.items() if dl < now]
        for k in expired:
            del self._assumed[k]
        if expired:
            self._generation += 1

    # ---- snapshot --------------------------------------------------------

    def snapshot(self, pending_pods: Optional[list[Pod]] = None):
        """-> (nodes list, ClusterTensors, SnapshotMeta). Cached by generation.

        ``pending_pods`` widen the resource axis; passing a batch with a new
        extended resource invalidates the cached encoding (rare).
        """
        with self._lock:
            self._expire_assumed_locked()
            nodes = list(self._nodes.values())
            bound = list(self._pods.values()) + [p for p, _ in self._assumed.values()]
            gen = self._generation
            if self._cached is not None and self._cached[0] == gen:
                _, ct, meta = self._cached
                known = set(meta.resources)
                if not any(r not in known for p in (pending_pods or [])
                           for r in p.resource_requests()):
                    return nodes, ct, meta
            ct, meta = self._encoder.encode_cluster(nodes, bound,
                                                    pending_pods=pending_pods)
            self._cached = (gen, ct, meta)
            return nodes, ct, meta

    def encode_pods(self, pods: list[Pod], meta: SnapshotMeta):
        with self._lock:
            return self._encoder.encode_pods(pods, meta)

    def bound_pods(self, include_assumed: bool = True) -> list[Pod]:
        with self._lock:
            out = list(self._pods.values())
            if include_assumed:
                out += [p for p, _ in self._assumed.values()]
            return out

    def stats(self) -> dict[str, int]:
        with self._lock:
            return {"nodes": len(self._nodes), "pods": len(self._pods),
                    "assumed": len(self._assumed), "generation": self._generation}
