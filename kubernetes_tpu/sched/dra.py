"""Dynamic Resource Allocation (DRA) — device claims as scheduling inputs.

Reference: ``pkg/scheduler/framework/plugins/dynamicresources/`` with the
structured-parameters model (resource.k8s.io/v1): ``ResourceSlice`` publishes
each node's device inventory, ``DeviceClass`` names a class of devices,
``ResourceClaim`` requests devices (``spec.devices.requests[]`` with
``deviceClassName`` + ``count``), pods reference claims via
``spec.resourceClaims``, and the scheduler allocates devices during the
scheduling cycle, recording the result in ``claim.status.allocation``.

TPU-first design: instead of a bespoke allocator plugin, device classes ride
the EXISTING resource axis as synthetic resources named ``dra:<class>`` —
a node's slice inventory extends its allocatable vector and a pod's claim
demands extend its request vector. The jitted fit filter, the gang batcher's
capacity-contention acceptance, and preemption then all handle devices with
zero new tensor code, which is exactly the property the reference's
NodeResources machinery lacks and its DRA plugin re-implements host-side.
The claim OBJECTS keep full API semantics: allocation is written on bind
(``SchedulerRunner``), ``reservedFor`` tracks the consumer, and the claim
controller releases allocations when consumers disappear.

Simplifications (documented, not silent): devices within a class are
fungible (counts, not per-device attributes/selectors), and a claim has a
single consumer (``reservedFor`` of one — the common template-per-pod
shape).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from kubernetes_tpu.api.types import Pod

DRA_PREFIX = "dra:"


@dataclass
class DraCatalog:
    """Indexed view of the resource.k8s.io objects (informer-fed)."""

    # (namespace, name) -> ResourceClaim dict
    claims: dict[tuple, dict] = field(default_factory=dict)
    # name -> DeviceClass dict
    classes: dict[str, dict] = field(default_factory=dict)
    # name -> ResourceSlice dict
    slices: dict[str, dict] = field(default_factory=dict)

    @classmethod
    def from_lists(cls, claims=(), classes=(), slices=()) -> "DraCatalog":
        cat = cls()
        for c in claims:
            md = c.get("metadata") or {}
            cat.claims[(md.get("namespace", "default"), md.get("name", ""))] = c
        for c in classes:
            cat.classes[(c.get("metadata") or {}).get("name", "")] = c
        for s in slices:
            cat.slices[(s.get("metadata") or {}).get("name", "")] = s
        return cat

    # ---- claim-side resolution ------------------------------------------

    def pod_claims(self, pod: Pod) -> list[dict]:
        """Resolve the pod's referenced ResourceClaim objects (template
        references resolve to the generated per-pod claim named
        ``<pod>-<ref name>`` — the resourceclaim controller's convention)."""
        out = []
        ns = pod.metadata.namespace
        for ref in pod.spec.resource_claims:
            name = ref.get("resourceClaimName") or (
                f"{pod.metadata.name}-{ref.get('name', '')}"
                if ref.get("resourceClaimTemplateName") else "")
            claim = self.claims.get((ns, name))
            if claim is not None:
                out.append(claim)
        return out

    @staticmethod
    def claim_demands(claim: dict) -> dict[str, int]:
        """class name -> device count requested by the claim."""
        out: dict[str, int] = {}
        devices = ((claim.get("spec") or {}).get("devices") or {})
        for req in devices.get("requests") or []:
            cls_name = req.get("deviceClassName", "")
            if not cls_name:
                continue
            out[cls_name] = out.get(cls_name, 0) + int(req.get("count", 1))
        return out

    def pod_claims_ready(self, pod: Pod) -> bool:
        """Every referenced claim resolves to an existing ResourceClaim.
        A pod whose template-generated claim hasn't been created yet must be
        held unschedulable (dynamicresources PreFilter returns Unschedulable)
        — NOT scheduled with its device demand silently dropped."""
        ns = pod.metadata.namespace
        for ref in pod.spec.resource_claims:
            name = ref.get("resourceClaimName") or (
                f"{pod.metadata.name}-{ref.get('name', '')}"
                if ref.get("resourceClaimTemplateName") else "")
            if not name or (ns, name) not in self.claims:
                return False
        return True

    def pod_demands(self, pod: Pod) -> dict[str, int]:
        """Synthetic request vector extension: ``dra:<class>`` -> count."""
        out: dict[str, int] = {}
        for claim in self.pod_claims(pod):
            for cls_name, n in self.claim_demands(claim).items():
                key = DRA_PREFIX + cls_name
                out[key] = out.get(key, 0) + n
        return out

    @staticmethod
    def claim_slice_shape(claim: dict) -> Optional[tuple]:
        """A SLICE-SHAPED claim: ``spec.devices.requests[].sliceShape``
        ("2x2x4") asks for a contiguous ICI sub-slice instead of count
        fungible devices — the claims-bridge half of topology/ (the label
        route is kubernetes-tpu.io/slice-shape). First parseable shape
        wins; a claim may carry ordinary count requests besides it."""
        from kubernetes_tpu.topology.slicing import parse_shape
        devices = ((claim.get("spec") or {}).get("devices") or {})
        for req in devices.get("requests") or []:
            shape = parse_shape(req.get("sliceShape"))
            if shape is not None:
                return shape
        return None

    def pod_slice_shape(self, pod: Pod) -> Optional[tuple]:
        """The slice shape requested by any of the pod's claims (routes
        the pod into the carver exactly like the slice-shape label)."""
        for claim in self.pod_claims(pod):
            shape = self.claim_slice_shape(claim)
            if shape is not None:
                return shape
        return None

    def pod_allocated_node(self, pod: Pod) -> Optional[str]:
        """If any referenced claim is already allocated, the pod is pinned
        to that node (the allocation's node selector)."""
        for claim in self.pod_claims(pod):
            alloc = ((claim.get("status") or {}).get("allocation")) or {}
            node = alloc.get("nodeName", "")
            if node:
                return node
        return None

    # ---- node-side resolution -------------------------------------------

    def node_capacity(self, node_name: str) -> dict[str, int]:
        """``dra:<class>`` -> total devices this node publishes via slices."""
        out: dict[str, int] = {}
        for s in self.slices.values():
            spec = s.get("spec") or {}
            if spec.get("nodeName", "") != node_name:
                continue
            for dev in spec.get("devices") or []:
                cls_name = dev.get("deviceClassName", "")
                if not cls_name:
                    continue
                count = int(dev.get("count", 1))
                key = DRA_PREFIX + cls_name
                out[key] = out.get(key, 0) + count
        return out

    def node_topology(self, node_name: str) -> Optional[tuple]:
        """(x, y, z) published by the node's ResourceSlice device
        attributes (``topology-x/y/z`` ints — topology/slicing.TOPO_ATTRS),
        the inventory-side mirror of the node labels. First device carrying
        all three axes wins."""
        from kubernetes_tpu.topology.slicing import TOPO_ATTRS
        for s in self.slices.values():
            spec = s.get("spec") or {}
            if spec.get("nodeName", "") != node_name:
                continue
            for dev in spec.get("devices") or []:
                attrs = dev.get("attributes") or {}
                try:
                    coord = tuple(int(attrs[a].get("int")
                                      if isinstance(attrs[a], dict)
                                      else attrs[a]) for a in TOPO_ATTRS)
                except (KeyError, TypeError, ValueError):
                    continue
                if all(c >= 0 for c in coord):
                    return coord
        return None

    def class_names(self) -> set[str]:
        """Every device class referenced by any slice or claim (defines
        which synthetic resources exist this snapshot)."""
        names: set[str] = set()
        for s in self.slices.values():
            for dev in ((s.get("spec") or {}).get("devices")) or []:
                if dev.get("deviceClassName"):
                    names.add(dev["deviceClassName"])
        for c in self.claims.values():
            names.update(self.claim_demands(c))
        return names


def allocation_patch(claim: dict, node_name: str, pod: Pod,
                     coords: Optional[tuple] = None,
                     shape: Optional[tuple] = None) -> dict:
    """The claim object with allocation + reservedFor recorded (what the
    scheduler writes in PreBind — dynamicresources.go bindClaim). For a
    carved slice member the allocation also records WHERE in the torus the
    pod landed (``topology.coordinates``) and the gang's requested shape —
    the provenance the audit invariant and operators read back."""
    out = dict(claim)
    status = dict(claim.get("status") or {})
    allocation: dict = {"nodeName": node_name}
    if coords is not None:
        from kubernetes_tpu.topology.slicing import shape_str
        topo: dict = {"coordinates": list(coords)}
        if shape is not None:
            topo["sliceShape"] = shape_str(shape)
        allocation["topology"] = topo
    status["allocation"] = allocation
    status["reservedFor"] = [{"resource": "pods",
                              "name": pod.metadata.name,
                              "uid": pod.metadata.uid}]
    out["status"] = status
    return out


def release_patch(claim: dict) -> dict:
    """The claim with its allocation dropped (deallocate — the claim
    controller applies this when the consuming pod is gone)."""
    out = dict(claim)
    status = dict(claim.get("status") or {})
    status.pop("allocation", None)
    status.pop("reservedFor", None)
    out["status"] = status
    return out
