"""Durable AOT executable cache — zero-compile *cold start*.

PR 14 proved zero XLA compiles in the fleet steady window; this module
makes the property survive the scheduler process dying. Every program
the warm ladder compiles (drain_step at each shape bucket and donated
layout, gang_schedule, preempt_wave, the fused-fold patch variants, the
tiny staging jits) is persisted as an XLA-serialized executable in a
cache directory next to the WAL; a restarted scheduler deserializes
instead of compiling, so the ~10–20s warm_drain ladder becomes a
sub-second disk load and the rolling-upgrade outage window collapses.

Mechanism: the entries themselves ride jax's persistent compilation
cache (one ``<name>-<sha256 of HLO+compile options+toolchain>-cache``
file per program), which both ``lower().compile()`` AND live jit
dispatch consult — the only seam that covers every variant, including
programs a bench never warms explicitly. What this module adds around
that directory is the durability discipline the WAL established:

  fingerprint   ``FINGERPRINT.json`` pins (jax/jaxlib versions, backend
                platform + device population, XLA flags, declared config
                knobs) via parallel/aot.lowering_fingerprint. A mismatch
                at boot invalidates the cache WHOLESALE (counted) — a
                new toolchain must never even get the chance to
                misinterpret an old toolchain's bytes.
  integrity     ``MANIFEST.json`` records each entry's size + sha256 at
                seal time. The boot scan deletes (and counts, under
                ``scheduler_aot_cache_errors_total``) any truncated,
                bit-flipped or unmanifested entry BEFORE jax can read it
                — a rejected entry degrades to a recompile, never a
                crash, never a wrong program.
  atomicity     fingerprint and manifest commit through
                utils/atomicio.atomic_write (temp file + fsync + rename
                — the WAL's commit discipline; ktpu-lint KTL008 enforces
                the helper).
  bound         a size/rotation GC evicts oldest-read entries past
                ``max_bytes`` (counted as ``reason="rotation"``).

Correctness backstop: a loaded executable is canary-checked on first
use — the runner forces the ParitySentinel to sample the FIRST drain
dispatch after a warm-from-cache boot, so a wrong program trips the
device circuit breaker with ``reason="parity"`` before a second batch
is judged by it.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import time
from typing import Optional

from kubernetes_tpu.metrics.registry import (
    AOT_CACHE_BOOT_MS,
    AOT_CACHE_BYTES,
    AOT_CACHE_ENTRIES,
    AOT_CACHE_ERRORS,
    AOT_CACHE_INVALIDATIONS,
)
from kubernetes_tpu.parallel.aot import compile_meter, lowering_fingerprint
from kubernetes_tpu.utils.atomicio import atomic_write_json

_LOG = logging.getLogger(__name__)

FINGERPRINT_FILE = "FINGERPRINT.json"
MANIFEST_FILE = "MANIFEST.json"
ENTRY_SUFFIX = "-cache"          # jax file_system_cache entry files
ATIME_SUFFIX = "-atime"          # jax's read-time sidecars (not entries)
DEFAULT_MAX_BYTES = 512 * 1024 * 1024


def _sha256_file(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


class AotExecutableCache:
    """One managed executable-cache directory (``root/entries`` +
    fingerprint + manifest). ``activate()`` arms it process-wide;
    ``seal()`` commits the manifest after the warm ladder has populated
    new entries."""

    def __init__(self, root: str, knobs: Optional[dict] = None,
                 max_bytes: int = DEFAULT_MAX_BYTES):
        self.root = os.path.abspath(root)
        self.entries_dir = os.path.join(self.root, "entries")
        self.knobs = dict(knobs or {})
        self.max_bytes = int(max_bytes)
        self.fingerprint = lowering_fingerprint(self.knobs)
        self.active = False
        # counted degrades (mirrored into the registry metrics; kept as
        # plain ints too so one cache instance's stats don't read another
        # incarnation's process-wide counters)
        self.errors = 0          # corrupt/unreadable entries deleted
        self.invalidations = 0   # fingerprint wholesale + rotation GC
        self.boot: dict = {}     # last activate() report
        self._meter_base: Optional[dict] = None
        self._sealed_sig: Optional[tuple] = None

    # ---- boot ------------------------------------------------------------

    def activate(self) -> dict:
        """Fingerprint-check, integrity-scan, GC and ARM the cache (points
        jax's persistent compilation cache at ``entries/``). Returns the
        boot report also kept as ``self.boot``. Never raises on cache
        damage — every rejected entry is a counted recompile, and a
        cache too broken to scan is invalidated wholesale."""
        t0 = time.monotonic()  # ktpu-lint: disable=KTL003 -- boot-duration measurement (reported ms), not time-window logic a FakeClock would need to advance
        os.makedirs(self.entries_dir, exist_ok=True)
        stale = self._fingerprint_stale()
        if stale:
            self._invalidate_all(reason="fingerprint")
        manifest = self._load_manifest()
        kept, swept = self._integrity_scan(manifest)
        rotated = self._gc(kept)
        for name in rotated:
            kept.pop(name, None)
        self._commit_meta(kept)
        self._arm_jax()
        self._meter_base = compile_meter().snapshot()
        n_bytes = sum(e["bytes"] for e in kept.values())
        self.boot = {
            "entries": len(kept),
            "bytes": n_bytes,
            "loadMs": round((time.monotonic() - t0) * 1000.0, 1),  # ktpu-lint: disable=KTL003 -- same boot-duration measurement as t0 above
            "fingerprintStale": stale,
            "corruptSwept": swept,
            "rotated": len(rotated),
        }
        AOT_CACHE_ENTRIES.set(len(kept))
        AOT_CACHE_BYTES.set(n_bytes)
        AOT_CACHE_BOOT_MS.set(self.boot["loadMs"])
        self.active = True
        _LOG.info(
            "AOT executable cache armed at %s: %d entries (%.1f KB) in "
            "%sms%s%s", self.root, len(kept), n_bytes / 1e3,
            self.boot["loadMs"],
            f", {swept} corrupt swept" if swept else "",
            " after WHOLESALE fingerprint invalidation" if stale else "")
        return self.boot

    def _fingerprint_stale(self) -> bool:
        path = os.path.join(self.root, FINGERPRINT_FILE)
        try:
            with open(path) as f:
                recorded = json.load(f).get("fingerprint")
        except FileNotFoundError:
            return False  # first boot: nothing to distrust
        except (OSError, ValueError):
            return True   # unreadable fingerprint = unverifiable cache
        return recorded != self.fingerprint

    def _invalidate_all(self, reason: str) -> None:
        """Wholesale: every entry (and sidecar) goes; the manifest goes
        with them. A stale-toolchain cache is dead bytes at best and a
        miscompile risk at worst — partial salvage is not worth it."""
        n = 0
        for name in self._listdir():
            try:
                os.unlink(os.path.join(self.entries_dir, name))
                if name.endswith(ENTRY_SUFFIX):
                    n += 1
            except OSError:
                pass
        try:
            os.unlink(os.path.join(self.root, MANIFEST_FILE))
        except OSError:
            pass
        self.invalidations += n
        AOT_CACHE_INVALIDATIONS.inc({"reason": reason}, by=max(n, 1))
        _LOG.warning("AOT cache %s: %d entries invalidated wholesale "
                     "(%s)", self.root, n, reason)

    def _load_manifest(self) -> dict:
        path = os.path.join(self.root, MANIFEST_FILE)
        try:
            with open(path) as f:
                doc = json.load(f)
            return dict(doc.get("entries") or {})
        except FileNotFoundError:
            return {}
        except (OSError, ValueError):
            # an unreadable manifest means NO entry is verifiable; treat
            # every present entry as unmanifested (the scan sweeps them)
            AOT_CACHE_ERRORS.inc({"reason": "manifest"})
            self.errors += 1
            return {}

    def _integrity_scan(self, manifest: dict) -> tuple[dict, int]:
        """Every on-disk entry either matches its manifest checksum or is
        deleted before jax can deserialize it. Unmanifested entries (a
        crash between entry write and seal) are kept but re-hashed — jax
        wrote them through its own temp+rename, and its zstd framing
        self-checks; the manifest exists to catch the torn/flipped bytes
        that framing can miss and to pin what seal() saw."""
        kept: dict = {}
        swept = 0
        for name in self._listdir(ENTRY_SUFFIX):
            path = os.path.join(self.entries_dir, name)
            try:
                digest = _sha256_file(path)
                size = os.path.getsize(path)
            except OSError:
                self._sweep_entry(name, "unreadable")
                swept += 1
                continue
            want = manifest.get(name)
            if want is not None and (want.get("sha256") != digest
                                     or want.get("bytes") != size):
                self._sweep_entry(name, "corrupt")
                swept += 1
                continue
            kept[name] = {"sha256": digest, "bytes": size,
                          "sealed": (want or {}).get("sealed", False)}
        return kept, swept

    def _sweep_entry(self, name: str, reason: str) -> None:
        self.errors += 1
        AOT_CACHE_ERRORS.inc({"reason": reason})
        for victim in (name, name[:-len(ENTRY_SUFFIX)] + ATIME_SUFFIX):
            try:
                os.unlink(os.path.join(self.entries_dir, victim))
            except OSError:
                pass
        _LOG.warning("AOT cache entry %s rejected (%s) — deleted; the "
                     "program recompiles on first use", name, reason)

    def _gc(self, kept: dict) -> list[str]:
        """Size bound: evict oldest-read entries (jax's -atime sidecar,
        falling back to mtime) until under ``max_bytes``."""
        total = sum(e["bytes"] for e in kept.values())
        if total <= self.max_bytes:
            return []

        def read_ts(name: str) -> float:
            base = os.path.join(self.entries_dir,
                                name[:-len(ENTRY_SUFFIX)])
            for p in (base + ATIME_SUFFIX,
                      os.path.join(self.entries_dir, name)):
                try:
                    return os.path.getmtime(p)
                except OSError:
                    continue
            return 0.0

        rotated: list[str] = []
        for name in sorted(kept, key=read_ts):
            if total <= self.max_bytes:
                break
            total -= kept[name]["bytes"]
            for victim in (name, name[:-len(ENTRY_SUFFIX)] + ATIME_SUFFIX):
                try:
                    os.unlink(os.path.join(self.entries_dir, victim))
                except OSError:
                    pass
            rotated.append(name)
        if rotated:
            self.invalidations += len(rotated)
            AOT_CACHE_INVALIDATIONS.inc({"reason": "rotation"},
                                        by=len(rotated))
            _LOG.info("AOT cache rotated %d entries past the %d-byte "
                      "bound", len(rotated), self.max_bytes)
        return rotated

    def _commit_meta(self, entries: dict) -> None:
        atomic_write_json(os.path.join(self.root, FINGERPRINT_FILE),
                          {"fingerprint": self.fingerprint,
                           "knobs": self.knobs}, indent=1, default=str)
        atomic_write_json(os.path.join(self.root, MANIFEST_FILE),
                          {"entries": entries}, indent=1)
        self._sealed_sig = self._dir_sig()

    def _arm_jax(self) -> None:
        import jax
        try:
            # a prior activation in this process (tests, A/B benches) may
            # have armed a different directory; drop its handle first
            from jax._src import compilation_cache as _cc
            _cc.reset_cache()
        except Exception:  # ktpu-lint: disable=KTL002 -- private-module best effort: absent reset just means first activation wins for already-open handles
            pass
        jax.config.update("jax_compilation_cache_dir", self.entries_dir)
        # every warmed program must persist, however small/fast it
        # compiled — the zero-compile gate counts the tiny staging jits too
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)

    @staticmethod
    def disarm() -> None:
        """Detach jax from any cache directory (tests restore the
        process-global default)."""
        import jax
        try:
            from jax._src import compilation_cache as _cc
            _cc.reset_cache()
        except Exception:  # ktpu-lint: disable=KTL002 -- private-module best effort mirror of _arm_jax's reset
            pass
        jax.config.update("jax_compilation_cache_dir", None)

    # ---- steady state ----------------------------------------------------

    def _listdir(self, suffix: str = "") -> list[str]:
        try:
            return sorted(n for n in os.listdir(self.entries_dir)
                          if n.endswith(suffix))
        except OSError:
            return []

    def _dir_sig(self) -> tuple:
        return tuple((n, self._size(n)) for n in self._listdir(ENTRY_SUFFIX))

    def _size(self, name: str) -> int:
        try:
            return os.path.getsize(os.path.join(self.entries_dir, name))
        except OSError:
            return 0

    def seal(self, force: bool = False) -> int:
        """Re-hash and commit the manifest for the CURRENT entry set —
        called after the warm ladder (and on the status cadence) so
        entries jax wrote since the last seal become verifiable at the
        next boot. Cheap no-op when the entry set hasn't changed.
        Returns the number of manifested entries."""
        if not self.active:
            return 0
        if not force and self._dir_sig() == self._sealed_sig:
            return len(self._sealed_sig or ())
        entries: dict = {}
        for name in self._listdir(ENTRY_SUFFIX):
            path = os.path.join(self.entries_dir, name)
            try:
                entries[name] = {"sha256": _sha256_file(path),
                                 "bytes": os.path.getsize(path),
                                 "sealed": True}
            except OSError:
                continue  # racing eviction; next seal re-judges
        try:
            self._commit_meta(entries)
        except OSError:
            self.errors += 1
            AOT_CACHE_ERRORS.inc({"reason": "io"})
            _LOG.warning("AOT cache manifest commit failed", exc_info=True)
            return len(entries)
        AOT_CACHE_ENTRIES.set(len(entries))
        AOT_CACHE_BYTES.set(sum(e["bytes"] for e in entries.values()))
        return len(entries)

    def stats(self) -> dict:
        """Status-surface block (``ktpu status`` renders it; the
        scheduler-kill bench gates on ``realCompiles``). Hits/misses are
        THIS activation's persistent-cache traffic; ``realCompiles`` is
        genuine XLA work since activation — 0 after a warm boot is the
        zero-compile-cold-start property itself."""
        entries = self._listdir(ENTRY_SUFFIX)
        stats = {"enabled": True, "dir": self.root,
                 "entries": len(entries),
                 "bytes": sum(self._size(n) for n in entries),
                 "errors": self.errors,
                 "invalidations": self.invalidations,
                 "bootEntries": self.boot.get("entries"),
                 "bootLoadMs": self.boot.get("loadMs")}
        if self._meter_base is not None:
            now = compile_meter().snapshot()
            base = self._meter_base
            stats["hits"] = now["cacheHits"] - base["cacheHits"]
            stats["misses"] = now["cacheMisses"] - base["cacheMisses"]
            stats["realCompiles"] = compile_meter().real_compiles(base, now)
        return stats


def resolve_cache_dir(cfg) -> Optional[str]:
    """The effective cache directory: ``KTPU_AOT_CACHE`` overrides
    config (``"0"``/``"off"`` disable; any other value is a path), else
    ``cfg.aot_cache_dir``; None = disabled (the tier-1 default)."""
    env = os.environ.get("KTPU_AOT_CACHE")
    if env is not None:
        s = env.strip()
        if s.lower() in ("", "0", "off", "none", "false"):
            return None
        return s
    return getattr(cfg, "aot_cache_dir", None)


def cache_knobs(cfg) -> dict:
    """Config knobs that change lowering enough to distrust old entries
    wholesale. jax's own entry keys already cover the HLO and compile
    options, so this list is the coarse outer guard, not the dedup key."""
    return {"meshShape": list(cfg.mesh_shape) if cfg.mesh_shape else None,
            "fusedFold": bool(cfg.fused_fold),
            "batchSize": int(cfg.batch_size),
            "maxDrainBatches": int(cfg.max_drain_batches)}
