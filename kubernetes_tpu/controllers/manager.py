"""Controller manager — run all controllers off one informer factory.

Reference: ``cmd/kube-controller-manager/app/controllermanager.go``
(``NewControllerDescriptors`` + ``StartControllers`` sharing a
SharedInformerFactory; active-passive via leader election).
"""

from __future__ import annotations

import logging
import threading
from typing import Optional

from kubernetes_tpu.client.informer import InformerFactory
from kubernetes_tpu.client.leaderelection import LeaderElectionConfig, LeaderElector
from kubernetes_tpu.controllers.cronjob import CronJobController
from kubernetes_tpu.controllers.daemonset import DaemonSetController
from kubernetes_tpu.controllers.deployment import DeploymentController
from kubernetes_tpu.controllers.disruption import DisruptionController
from kubernetes_tpu.controllers.endpoints import EndpointsController
from kubernetes_tpu.controllers.endpointslice import EndpointSliceController
from kubernetes_tpu.controllers.endpointslicemirroring import (
    EndpointSliceMirroringController)
from kubernetes_tpu.controllers.garbagecollector import GarbageCollector
from kubernetes_tpu.controllers.hpa import HorizontalPodAutoscalerController
from kubernetes_tpu.controllers.job import JobController
from kubernetes_tpu.controllers.namespace import NamespaceController
from kubernetes_tpu.controllers.nodelifecycle import NodeLifecycleController
from kubernetes_tpu.controllers.pvbinder import PersistentVolumeController
from kubernetes_tpu.controllers.podgc import PodGCController
from kubernetes_tpu.controllers.replicaset import (
    ReplicaSetController,
    ReplicationControllerController,
)
from kubernetes_tpu.controllers.resourceclaim import ResourceClaimController
from kubernetes_tpu.controllers.serviceaccount import (
    ServiceAccountController,
    TokenController,
)
from kubernetes_tpu.controllers.certificates import CSRSigningController
from kubernetes_tpu.controllers.clusterroleaggregation import (
    ClusterRoleAggregationController,
)
from kubernetes_tpu.controllers.resourcequota import ResourceQuotaController
from kubernetes_tpu.controllers.statefulset import StatefulSetController
from kubernetes_tpu.controllers.attachdetach import AttachDetachController
from kubernetes_tpu.controllers.ephemeral import EphemeralVolumeController
from kubernetes_tpu.controllers.nodeipam import NodeIpamController
from kubernetes_tpu.controllers.csrlifecycle import (CSRApprovingController,
                                                     CSRCleanerController)
from kubernetes_tpu.controllers.rootca import RootCAPublisher
from kubernetes_tpu.controllers.volumeprotection import (
    PVCProtectionController, PVProtectionController)
from kubernetes_tpu.controllers.route import RouteController
from kubernetes_tpu.controllers.servicelb import ServiceLBController
from kubernetes_tpu.controllers.ttl import TTLController
from kubernetes_tpu.controllers.ttlafterfinished import TTLAfterFinishedController

_LOG = logging.getLogger(__name__)

DEFAULT_CONTROLLERS = ("deployment", "replicaset", "job", "daemonset",
                       "statefulset", "endpoints", "endpointslice",
                       "nodelifecycle", "pvbinder", "disruption", "cronjob",
                       "ttlafterfinished", "horizontalpodautoscaler",
                       "namespace", "serviceaccount", "serviceaccount-token",
                       "resourceclaim", "replicationcontroller", "podgc",
                       "resourcequota", "ttl", "clusterroleaggregation",
                       "csrsigning", "ephemeral", "attachdetach",
                       "root-ca-cert-publisher", "endpointslicemirroring",
                       "pvc-protection", "pv-protection", "csrapproving",
                       "csrcleaner")
# Cloud-provider loops (upstream: cloud-controller-manager / kcm flags):
# opt-in by name — "nodeipam" needs --cluster-cidr semantics, "route" and
# "service-lb" a cloud. cli/cluster.py enables them for cluster-up.
CLOUD_CONTROLLERS = ("nodeipam", "route", "service-lb")


class ControllerManager:
    def __init__(self, client, controllers=DEFAULT_CONTROLLERS,
                 leader_elect: bool = False,
                 identity: str = "kube-controller-manager",
                 resync_period: float = 10.0,
                 gc_enabled: bool = True):
        self.client = client
        if hasattr(client, "default_user_agent"):
            client.default_user_agent("kube-controller-manager")
        self.factory = InformerFactory(client)
        self.resync_period = resync_period
        ctors = {
            "deployment": DeploymentController,
            "replicaset": ReplicaSetController,
            "replicationcontroller": ReplicationControllerController,
            "podgc": PodGCController,
            "job": JobController,
            "daemonset": DaemonSetController,
            "statefulset": StatefulSetController,
            "endpoints": EndpointsController,
            "nodelifecycle": NodeLifecycleController,
            "pvbinder": PersistentVolumeController,
            "disruption": DisruptionController,
            "cronjob": CronJobController,
            "ttlafterfinished": TTLAfterFinishedController,
            "horizontalpodautoscaler": HorizontalPodAutoscalerController,
            "namespace": NamespaceController,
            "endpointslice": EndpointSliceController,
            "serviceaccount": ServiceAccountController,
            "resourceclaim": ResourceClaimController,
            "serviceaccount-token": TokenController,
            "resourcequota": ResourceQuotaController,
            "ttl": TTLController,
            "clusterroleaggregation": ClusterRoleAggregationController,
            "csrsigning": CSRSigningController,
            "attachdetach": AttachDetachController,
            "nodeipam": NodeIpamController,
            "ephemeral": EphemeralVolumeController,
            "root-ca-cert-publisher": RootCAPublisher,
            "endpointslicemirroring": EndpointSliceMirroringController,
            "pvc-protection": PVCProtectionController,
            "pv-protection": PVProtectionController,
            "csrapproving": CSRApprovingController,
            "csrcleaner": CSRCleanerController,
            "service-lb": ServiceLBController,
            "route": RouteController,
        }
        from kubernetes_tpu.controllers.certificates import HAVE_CRYPTOGRAPHY
        if not HAVE_CRYPTOGRAPHY:
            # X.509-backed loops need the optional ``cryptography`` package;
            # run the rest of the manager rather than refusing to start
            # (upstream kcm likewise runs with individual loops disabled)
            needs_x509 = {"csrsigning", "root-ca-cert-publisher"}
            dropped = [n for n in controllers if n in needs_x509]
            if dropped:
                _LOG.warning("cryptography not installed; disabling "
                             "controllers: %s", ", ".join(dropped))
            controllers = [n for n in controllers if n not in needs_x509]
        self.controllers = [ctors[n](client) for n in controllers]
        self.gc = GarbageCollector(client) if gc_enabled else None
        self.leader_elect = leader_elect
        self.identity = identity
        self._stop = threading.Event()
        self._resync_thread: Optional[threading.Thread] = None
        self._started = False

    def start(self, wait_sync: float = 10.0):
        for c in self.controllers:
            c.register(self.factory)
        if self.gc is not None:
            self.gc.register(self.factory)
        self.factory.start_all()
        self.factory.wait_for_cache_sync(wait_sync)
        if self.leader_elect:
            elector = LeaderElector(self.client.leases(), LeaderElectionConfig(
                lock_name="kube-controller-manager", identity=self.identity,
                on_started_leading=self._start_controllers,
                on_stopped_leading=self._noop))
            threading.Thread(target=elector.run, args=(self._stop,),
                             daemon=True).start()
        else:
            self._start_controllers()
        return self

    def _noop(self):
        pass

    def _start_controllers(self):
        if self._started:
            return
        self._started = True
        for c in self.controllers:
            c.start()
        self._resync_thread = threading.Thread(target=self._resync_loop, daemon=True)
        self._resync_thread.start()

    def _resync_loop(self):
        """Periodic full re-enqueue (informer resync analog) + GC sweep —
        converges anything a missed/raced event left behind."""
        while not self._stop.wait(self.resync_period):
            for c in self.controllers:
                inf = getattr(c, f"{_informer_attr(c)}", None)
                if inf is not None:
                    for key in inf.store.keys():
                        c.queue.add(key)
            if self.gc is not None:
                try:
                    self.gc.sweep()
                except Exception:
                    _LOG.exception("garbage-collector sweep failed; "
                                   "retrying next interval")

    def stop(self):
        self._stop.set()
        for c in self.controllers:
            c.stop()
        self.factory.stop_all()


def _informer_attr(c) -> str:
    return {
        "deployment": "dep_informer",
        "replicaset": "rs_informer",
        "replicationcontroller": "rs_informer",
        "job": "job_informer",
        "daemonset": "ds_informer",
        "statefulset": "ss_informer",
        "endpoints": "svc_informer",
        "endpointslice": "svc_informer",
        "nodelifecycle": "node_informer",
        "pvbinder": "pvc_informer",
        "cronjob": "cj_informer",
        "ttlafterfinished": "job_informer",
        "horizontalpodautoscaler": "hpa_informer",
        "disruption": "pdb_informer",
        "serviceaccount": "ns_informer",
        "serviceaccount-token": "sa_informer",
        "resourceclaim": "pod_informer",
    }.get(c.name, "")
