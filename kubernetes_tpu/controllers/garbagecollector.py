"""Garbage collector — cascade-delete orphans via ownerReferences.

Reference: ``pkg/controller/garbagecollector/garbagecollector.go`` (uid →
object dependency graph from informers; ``attemptToDeleteItem`` removes
objects whose owners are all gone; blockOwnerDeletion/foreground handled via
finalizers — here only the background-cascade core).
"""

from __future__ import annotations

from kubernetes_tpu.client.clientset import ApiError
from kubernetes_tpu.client.informer import InformerFactory
from kubernetes_tpu.store.apiserver import ALL_RESOURCES

# kinds tracked in the ownership graph (plural -> kind, namespaced)
GC_RESOURCES = ("pods", "replicasets", "deployments", "statefulsets",
                "daemonsets", "jobs", "cronjobs", "endpoints",
                "endpointslices", "serviceaccounts", "secrets", "resourceclaims",
                "replicationcontrollers")


class GarbageCollector:
    """Periodic mark-and-sweep over informer caches: any object with
    ownerReferences whose referenced uids all no longer exist is deleted.
    Runs from the manager's resync tick rather than a workqueue — the graph
    is global, not per-key."""

    name = "garbagecollector"

    def __init__(self, client):
        self.client = client
        self._informers = {}

    def register(self, factory: InformerFactory) -> None:
        for plural in GC_RESOURCES:
            self._informers[plural] = factory.informer(plural, None)

    def _dependents_of(self, uid: str) -> list[tuple[str, dict]]:
        out = []
        for plural, inf in self._informers.items():
            for obj in inf.store.list():
                if any(r.get("uid") == uid for r in
                       (obj.get("metadata") or {})
                       .get("ownerReferences") or []):
                    out.append((plural, obj))
        return out

    def _finish_terminating(self) -> tuple[int, set]:
        """Foreground / orphan propagation (attemptToDeleteItem's finalizer
        half): a TERMINATING owner holding ``foregroundDeletion`` waits for
        its dependents to be deleted first; one holding ``orphan`` gets its
        ownerReferences stripped from dependents. Either finalizer comes
        off once its obligation is met, completing the delete."""
        acted = 0
        orphaning: set = set()
        for plural, inf in self._informers.items():
            kind, namespaced = ALL_RESOURCES[plural]
            for obj in inf.store.list():
                md = obj.get("metadata") or {}
                fins = md.get("finalizers") or []
                if not md.get("deletionTimestamp"):
                    continue
                uid = md.get("uid", "")
                ns = md.get("namespace") if namespaced else None
                res = self.client.resource(plural, ns)
                if "foregroundDeletion" in fins:
                    deps = self._dependents_of(uid)
                    if deps:
                        for dplural, dep in deps:
                            dmd = dep.get("metadata") or {}
                            if dmd.get("deletionTimestamp"):
                                continue  # already going
                            dns = (dmd.get("namespace")
                                   if ALL_RESOURCES[dplural][1] else None)
                            try:
                                self.client.resource(dplural, dns).delete(
                                    dmd.get("name", ""))
                                acted += 1
                            except ApiError as e:
                                if e.code != 404:
                                    raise
                        continue  # finalizer stays until they're gone
                    self._strip_finalizer(res, obj, "foregroundDeletion")
                    acted += 1
                elif "orphan" in fins:
                    orphaning.add(uid)
                    for dplural, dep in self._dependents_of(uid):
                        dmd = dep.get("metadata") or {}
                        refs = [r for r in dmd.get("ownerReferences") or []
                                if r.get("uid") != uid]
                        dep2 = {**dep, "metadata": {**dmd,
                                                    "ownerReferences": refs}}
                        if not refs:
                            dep2["metadata"].pop("ownerReferences", None)
                        dns = (dmd.get("namespace")
                               if ALL_RESOURCES[dplural][1] else None)
                        try:
                            self.client.resource(dplural, dns).update(dep2)
                        except ApiError as e:
                            if e.code not in (404, 409):
                                raise
                    self._strip_finalizer(res, obj, "orphan")
                    acted += 1
        return acted, orphaning

    @staticmethod
    def _strip_finalizer(res, obj: dict, fin: str) -> None:
        # copy before mutating: ``obj`` is the shared informer-cache entry
        # (every controller on the factory reads it); an in-place strip
        # followed by a swallowed 409 would both corrupt the cache and
        # suppress the next sweep's retry
        md = obj.get("metadata") or {}
        obj2 = {**obj, "metadata": {
            **md, "finalizers": [f for f in md.get("finalizers") or []
                                 if f != fin]}}
        try:
            res.update(obj2)
        except ApiError as e:
            if e.code not in (404, 409):
                raise

    def sweep(self) -> int:
        """One mark-and-sweep pass; returns number of deletions issued."""
        deleted, orphaning = self._finish_terminating()
        live_uids = set()
        for inf in self._informers.values():
            for obj in inf.store.list():
                # a PRESENT owner keeps its dependents — even terminating
                # (a custom finalizer may still need them); only the
                # foreground flow deletes dependents of a terminating
                # owner, and it does so explicitly above
                uid = (obj.get("metadata") or {}).get("uid")
                if uid:
                    live_uids.add(uid)
        tracked_kinds = {ALL_RESOURCES[p][0] for p in GC_RESOURCES}
        for plural, inf in self._informers.items():
            kind, namespaced = ALL_RESOURCES[plural]
            for obj in inf.store.list():
                md = obj.get("metadata") or {}
                refs = md.get("ownerReferences") or []
                if not refs:
                    continue
                # Owners of kinds outside the graph (Node, Service, ...) have
                # unknowable liveness here — never treat their dependents as
                # orphaned (upstream deletes only when ALL owners are
                # confirmed gone).
                if any(r.get("kind") not in tracked_kinds for r in refs):
                    continue
                if any(r.get("uid") in live_uids for r in refs):
                    continue
                if any(r.get("uid") in orphaning for r in refs):
                    # the owner is being ORPHANED: its reference strip is
                    # in flight, and this informer copy predates it — the
                    # dependent must survive, not be collected
                    continue
                ns = md.get("namespace") if namespaced else None
                try:
                    # attemptToDeleteItem verifies LIVE before deleting:
                    # the informer copy may predate an ownerReference strip
                    # (an orphaned dependent must never be collected on
                    # stale cache)
                    live = self.client.resource(plural, ns).get(md["name"])
                    live_refs = (live.get("metadata") or {})                         .get("ownerReferences") or []
                    if not live_refs or any(
                            r.get("uid") in live_uids for r in live_refs):
                        continue
                    self.client.resource(plural, ns).delete(md["name"])
                    deleted += 1
                except ApiError as e:
                    if e.code != 404:
                        raise
        return deleted
