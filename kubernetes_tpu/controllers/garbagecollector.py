"""Garbage collector — cascade-delete orphans via ownerReferences.

Reference: ``pkg/controller/garbagecollector/garbagecollector.go`` (uid →
object dependency graph from informers; ``attemptToDeleteItem`` removes
objects whose owners are all gone; blockOwnerDeletion/foreground handled via
finalizers — here only the background-cascade core).
"""

from __future__ import annotations

from kubernetes_tpu.client.clientset import ApiError
from kubernetes_tpu.client.informer import InformerFactory
from kubernetes_tpu.store.apiserver import ALL_RESOURCES

# kinds tracked in the ownership graph (plural -> kind, namespaced)
GC_RESOURCES = ("pods", "replicasets", "deployments", "statefulsets",
                "daemonsets", "jobs", "cronjobs", "endpoints",
                "endpointslices", "serviceaccounts", "secrets", "resourceclaims",
                "replicationcontrollers")


class GarbageCollector:
    """Periodic mark-and-sweep over informer caches: any object with
    ownerReferences whose referenced uids all no longer exist is deleted.
    Runs from the manager's resync tick rather than a workqueue — the graph
    is global, not per-key."""

    name = "garbagecollector"

    def __init__(self, client):
        self.client = client
        self._informers = {}

    def register(self, factory: InformerFactory) -> None:
        for plural in GC_RESOURCES:
            self._informers[plural] = factory.informer(plural, None)

    def sweep(self) -> int:
        """One mark-and-sweep pass; returns number of deletions issued."""
        live_uids = set()
        for inf in self._informers.values():
            for obj in inf.store.list():
                uid = (obj.get("metadata") or {}).get("uid")
                if uid:
                    live_uids.add(uid)
        deleted = 0
        tracked_kinds = {ALL_RESOURCES[p][0] for p in GC_RESOURCES}
        for plural, inf in self._informers.items():
            kind, namespaced = ALL_RESOURCES[plural]
            for obj in inf.store.list():
                md = obj.get("metadata") or {}
                refs = md.get("ownerReferences") or []
                if not refs:
                    continue
                # Owners of kinds outside the graph (Node, Service, ...) have
                # unknowable liveness here — never treat their dependents as
                # orphaned (upstream deletes only when ALL owners are
                # confirmed gone).
                if any(r.get("kind") not in tracked_kinds for r in refs):
                    continue
                if any(r.get("uid") in live_uids for r in refs):
                    continue
                try:
                    ns = md.get("namespace") if namespaced else None
                    self.client.resource(plural, ns).delete(md["name"])
                    deleted += 1
                except ApiError as e:
                    if e.code != 404:
                        raise
        return deleted
