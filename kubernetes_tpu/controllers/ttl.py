"""TTL controller — size-tiered node annotation for secret/configmap TTLs.

Reference: ``pkg/controller/ttl/ttl_controller.go``: annotate every node
with ``node.alpha.kubernetes.io/ttl`` according to cluster size, so
kubelets cache secrets/configmaps longer in big clusters (0s <=100 nodes,
15s <=500, 30s <=1000, 60s <=2000, 300s above — upstream's ttlBoundaries).
"""

from __future__ import annotations

from kubernetes_tpu.client.clientset import ApiError
from kubernetes_tpu.client.informer import InformerFactory
from kubernetes_tpu.controllers.base import Controller

TTL_ANNOTATION = "node.alpha.kubernetes.io/ttl"
# (max cluster size, ttl seconds) — ttl_controller.go ttlBoundaries
_BOUNDARIES = ((100, 0), (500, 15), (1000, 30), (2000, 60))
_MAX_TTL = 300


class TTLController(Controller):
    name = "ttl"
    workers = 1

    def register(self, factory: InformerFactory) -> None:
        self.node_informer = factory.informer("nodes", None)
        self._last_ttl: int | None = None
        self.node_informer.add_event_handler(self._on_node)

    def _on_node(self, type_, obj, old) -> None:
        if type_ in ("ADDED", "DELETED"):
            # the fleet is re-enqueued only when the cluster-size TIER
            # changes (ttl_controller enqueues everything on boundary
            # crossings, not on every membership event — at fleet scale
            # per-event fan-out is O(N^2))
            ttl = self._desired_ttl()
            if ttl != self._last_ttl:
                self._last_ttl = ttl
                for n in self.node_informer.store.list():
                    self.enqueue(n)
                return
        if type_ != "DELETED":
            self.enqueue(obj)

    def _desired_ttl(self) -> int:
        n = len(self.node_informer.store)
        for bound, ttl in _BOUNDARIES:
            if n <= bound:
                return ttl
        return _MAX_TTL

    def sync(self, key: str) -> None:
        res = self.client.resource("nodes", None)
        try:
            node = res.get(key)
        except ApiError as e:
            if e.code == 404:
                return
            raise
        want = str(self._desired_ttl())
        ann = (node.get("metadata") or {}).get("annotations") or {}
        if ann.get(TTL_ANNOTATION) == want:
            return
        node.setdefault("metadata", {}).setdefault(
            "annotations", {})[TTL_ANNOTATION] = want
        try:
            res.update(node)
        except ApiError as e:
            if e.code not in (404, 409):
                raise
