"""Horizontal Pod Autoscaler controller (autoscaling/v2, Resource metrics).

Reference: ``pkg/controller/podautoscaler/horizontal.go``
(``reconcileAutoscaler`` + ``computeReplicasForMetrics``): desired =
ceil(current * actualUtilization / targetUtilization), clamped to
[minReplicas, maxReplicas], with a scale-down stabilization window.

Metrics source: upstream reads the metrics API (metrics-server). Here the
equivalent surface is a pluggable ``metrics_fn(pod_dict) -> used millicores``
defaulting to the ``kubernetes-tpu.io/cpu-usage`` pod annotation, which the
hollow kubelet (or a test) publishes — the shape of the data matches
``PodMetrics.containers[].usage.cpu``.
"""

from __future__ import annotations

import math
from typing import Callable, Optional

from kubernetes_tpu.api.resource import canonical
from kubernetes_tpu.api.selectors import label_selector_matches
from kubernetes_tpu.api.types import LabelSelector
from kubernetes_tpu.client.clientset import ApiError
from kubernetes_tpu.client.informer import InformerFactory
from kubernetes_tpu.controllers.base import Controller, active_pods, split_key
from kubernetes_tpu.utils.clock import REAL_CLOCK

USAGE_ANNOTATION = "kubernetes-tpu.io/cpu-usage"
TOLERANCE = 0.1  # upstream defaultTestingTolerance: skip scaling within 10%


def annotation_metrics(pod: dict) -> Optional[int]:
    """Used cpu millicores from the usage annotation (None = no sample)."""
    v = ((pod.get("metadata") or {}).get("annotations") or {}).get(
        USAGE_ANNOTATION)
    if v is None:
        return None
    return canonical("cpu", str(v))


class HorizontalPodAutoscalerController(Controller):
    name = "horizontalpodautoscaler"
    tick_interval = 2.0  # upstream --horizontal-pod-autoscaler-sync-period 15s

    def __init__(self, client, metrics_fn: Callable = annotation_metrics,
                 downscale_stabilization_s: float = 30.0, clock=None):
        super().__init__(client)
        self.metrics_fn = metrics_fn
        self.downscale_stabilization_s = downscale_stabilization_s
        # injectable clock (utils/clock.py): HPA-vs-autoscaler interplay
        # tests advance the stabilization window instead of sleeping it out
        self.clock = clock or REAL_CLOCK
        # key -> [(ts, recommended replicas)]; scale-down takes the max over
        # the stabilization window (upstream stabilizeRecommendation).
        self._recommendations: dict[str, list[tuple[float, int]]] = {}

    def register(self, factory: InformerFactory) -> None:
        self.hpa_informer = factory.informer("horizontalpodautoscalers", None)
        self.hpa_informer.add_event_handler(self.handler())
        self.deploy_informer = factory.informer("deployments", None)
        self.pod_informer = factory.informer("pods", None)

    def tick(self) -> None:
        for hpa in self.hpa_informer.store.list():
            self.enqueue(hpa)

    # -- metric evaluation -------------------------------------------------

    def _target_utilization(self, hpa: dict) -> Optional[int]:
        for m in (hpa.get("spec") or {}).get("metrics") or []:
            if m.get("type") != "Resource":
                continue
            res = m.get("resource") or {}
            if res.get("name") != "cpu":
                continue
            return (res.get("target") or {}).get("averageUtilization")
        return None

    def _pod_utilization(self, pod: dict) -> Optional[float]:
        used = self.metrics_fn(pod)
        if used is None:
            return None
        requested = 0
        for c in (pod.get("spec") or {}).get("containers") or []:
            r = ((c.get("resources") or {}).get("requests") or {}).get("cpu")
            if r:
                requested += canonical("cpu", str(r))
        if not requested:
            return None
        return 100.0 * used / requested

    def sync(self, key: str) -> None:
        ns, name = split_key(key)
        hpa = self.hpa_informer.store.get(key)
        if hpa is None:
            self._recommendations.pop(key, None)
            return
        spec = hpa.get("spec") or {}
        ref = spec.get("scaleTargetRef") or {}
        if ref.get("kind") != "Deployment":
            return  # only Deployments are scalable here
        dkey = f"{ns}/{ref.get('name', '')}"
        deploy = self.deploy_informer.store.get(dkey)
        if deploy is None:
            return
        target = self._target_utilization(hpa)
        if target is None:
            return
        dspec = deploy.get("spec") or {}
        current = int(dspec.get("replicas", 1))
        sel = LabelSelector.from_dict(dspec.get("selector"))
        pods = [p for p in active_pods(self.pod_informer.store.list())
                if (p.get("metadata") or {}).get("namespace", "") == ns
                and label_selector_matches(
                    sel, (p.get("metadata") or {}).get("labels") or {})]
        samples = [u for u in (self._pod_utilization(p) for p in pods)
                   if u is not None]
        if not samples:
            self._update_status(ns, hpa, current, current, None)
            return
        avg = sum(samples) / len(samples)
        ratio = avg / float(target)
        desired = current if abs(ratio - 1.0) <= TOLERANCE \
            else math.ceil(current * ratio)
        lo = int(spec.get("minReplicas", 1))
        hi = int(spec.get("maxReplicas", max(current, 1)))
        desired = max(lo, min(hi, desired))
        # Scale-down stabilization: the effective recommendation is the max
        # over the window, seeded with the replica count first observed, so a
        # dip must persist for the whole window before replicas drop.
        now = self.clock.now()
        recs = self._recommendations.setdefault(key, [(now, current)])
        recs.append((now, desired))
        cutoff = now - self.downscale_stabilization_s
        recs[:] = [(t, d) for t, d in recs if t >= cutoff]
        stabilized = max(d for _, d in recs)
        if stabilized > desired:
            desired = min(stabilized, current)
        if desired != current:
            patched = dict(deploy)
            patched["spec"] = {**dspec, "replicas": desired}
            try:
                self.client.resource("deployments", ns).update(patched)
            except ApiError as e:
                if e.code not in (404, 409):
                    raise
                return
        self._update_status(ns, hpa, current, desired, avg)

    def _update_status(self, ns, hpa, current, desired, avg):
        status = {"currentReplicas": current, "desiredReplicas": desired}
        if avg is not None:
            status["currentCPUUtilizationPercentage"] = round(avg, 1)
        if status == (hpa.get("status") or {}):
            return
        out = dict(hpa)
        out["status"] = status
        try:
            self.client.resource("horizontalpodautoscalers", ns) \
                .update_status(out)
        except ApiError as e:
            if e.code not in (404, 409):
                raise
