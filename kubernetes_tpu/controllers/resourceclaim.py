"""ResourceClaim controller — DRA claim lifecycle.

Reference: ``pkg/controller/resourceclaim/controller.go``: for each pod
entry in ``spec.resourceClaims`` referencing a ``resourceClaimTemplateName``,
generate a per-pod ResourceClaim (named ``<pod>-<entry name>`` here, owned
by the pod so the GC cascades it); and release allocations whose consumer
pod is gone (drop ``status.allocation``/``reservedFor`` so the devices
return to the pool — the deallocate half of dynamicresources.go).
"""

from __future__ import annotations

from kubernetes_tpu.client.clientset import ApiError
from kubernetes_tpu.client.informer import InformerFactory
from kubernetes_tpu.controllers.base import Controller, split_key
from kubernetes_tpu.sched.dra import release_patch


class ResourceClaimController(Controller):
    name = "resourceclaim"
    tick_interval = 2.0  # release sweep (consumer-gone detection)

    def register(self, factory: InformerFactory) -> None:
        self.pod_informer = factory.informer("pods", None)
        self.pod_informer.add_event_handler(self.handler())
        self.claim_informer = factory.informer("resourceclaims", None)
        self.tpl_informer = factory.informer("resourceclaimtemplates", None)

    def tick(self) -> None:
        # release pass: any allocated claim whose reserving pod no longer
        # exists (or is terminal) gets its allocation dropped
        for claim in self.claim_informer.store.list():
            status = claim.get("status") or {}
            if not status.get("allocation"):
                continue
            ns = (claim.get("metadata") or {}).get("namespace", "default")
            holders = status.get("reservedFor") or []
            live = False
            for ref in holders:
                pod = self.pod_informer.store.get(f"{ns}/{ref.get('name', '')}")
                if pod is None:
                    continue
                # a recreated same-name pod is a DIFFERENT consumer: the
                # reservation must name this pod's uid (upstream validates
                # reservedFor uids)
                ref_uid = ref.get("uid", "")
                if ref_uid and ref_uid != (pod.get("metadata") or {}).get("uid"):
                    continue
                if (pod.get("status") or {}).get("phase") not in (
                        "Succeeded", "Failed"):
                    live = True
            if not live:  # incl. an allocation nobody reserves
                try:
                    self.client.resource("resourceclaims", ns).update_status(
                        release_patch(claim))
                except ApiError as e:
                    if e.code not in (404, 409):
                        raise

    def sync(self, key: str) -> None:
        ns, name = split_key(key)
        pod = self.pod_informer.store.get(key)
        if pod is None:
            return  # pod-owned claims cascade via the GC
        for entry in (pod.get("spec") or {}).get("resourceClaims") or []:
            tpl_name = entry.get("resourceClaimTemplateName")
            if not tpl_name:
                continue
            claim_name = f"{name}-{entry.get('name', '')}"
            if self.claim_informer.store.get(f"{ns}/{claim_name}") is not None:
                continue
            tpl = self.tpl_informer.store.get(f"{ns}/{tpl_name}")
            if tpl is None:
                raise RuntimeError(f"claim template {ns}/{tpl_name} not found")
            md = pod.get("metadata") or {}
            claim = {
                "apiVersion": "resource.k8s.io/v1", "kind": "ResourceClaim",
                "metadata": {
                    "name": claim_name, "namespace": ns,
                    "ownerReferences": [{
                        "apiVersion": "v1", "kind": "Pod",
                        "name": md.get("name", ""), "uid": md.get("uid", ""),
                        "controller": True, "blockOwnerDeletion": True}],
                },
                "spec": dict(((tpl.get("spec") or {}).get("spec")) or {}),
            }
            try:
                self.client.resource("resourceclaims", ns).create(claim)
            except ApiError as e:
                if e.code != 409:
                    raise
