"""PVC/PV protection — finalizers against deleting storage in use.

Reference: ``pkg/controller/volume/pvcprotection`` and ``pvprotection``:
every PVC carries the ``kubernetes.io/pvc-protection`` finalizer (and PVs
``kubernetes.io/pv-protection``), so a user delete only marks the object
terminating; the finalizer comes off — letting the delete complete — when
no pod mounts the claim (resp. no claim binds the volume). Data in active
use can never vanish out from under its consumers.
"""

from __future__ import annotations

from kubernetes_tpu.client.clientset import ApiError
from kubernetes_tpu.client.informer import InformerFactory
from kubernetes_tpu.controllers.base import Controller, split_key

PVC_FINALIZER = "kubernetes.io/pvc-protection"
PV_FINALIZER = "kubernetes.io/pv-protection"


def _update(res, obj: dict) -> None:
    """409/404-tolerant update: a conflict means fresher state is already
    on the way through the informer, which re-enqueues."""
    try:
        res.update(obj)
    except ApiError as e:
        if e.code not in (404, 409):
            raise


class PVCProtectionController(Controller):
    name = "pvc-protection"
    workers = 1

    def register(self, factory: InformerFactory) -> None:
        self.pvc_informer = factory.informer("persistentvolumeclaims", None)
        self.pvc_informer.add_event_handler(self.handler())
        self.pod_informer = factory.informer("pods", None)
        self.pod_informer.add_event_handler(self._on_pod)

    def _on_pod(self, type_, obj, old) -> None:
        """A pod releasing a claim may unblock its pending delete."""
        ns = (obj.get("metadata") or {}).get("namespace", "default")
        for vol in (obj.get("spec") or {}).get("volumes") or []:
            claim = (vol.get("persistentVolumeClaim") or {}).get(
                "claimName", "")
            if claim:
                self.queue.add(f"{ns}/{claim}")

    def _in_use(self, ns: str, name: str) -> bool:
        for pod in self.pod_informer.store.list():
            md = pod.get("metadata") or {}
            if md.get("namespace", "default") != ns:
                continue
            if (pod.get("status") or {}).get("phase") in ("Succeeded",
                                                          "Failed"):
                continue
            for vol in (pod.get("spec") or {}).get("volumes") or []:
                if (vol.get("persistentVolumeClaim") or {}).get(
                        "claimName") == name:
                    return True
        return False

    def sync(self, key: str) -> None:
        ns, name = split_key(key)
        pvcs = self.client.resource("persistentvolumeclaims", ns)
        try:
            pvc = pvcs.get(name)
        except ApiError as e:
            if e.code == 404:
                return
            raise
        md = pvc.setdefault("metadata", {})
        fins = list(md.get("finalizers") or [])
        if md.get("deletionTimestamp"):
            if PVC_FINALIZER in fins and not self._in_use(ns, name):
                md["finalizers"] = [f for f in fins if f != PVC_FINALIZER]
                _update(pvcs, pvc)
        elif PVC_FINALIZER not in fins:
            md["finalizers"] = fins + [PVC_FINALIZER]
            _update(pvcs, pvc)


class PVProtectionController(Controller):
    name = "pv-protection"
    workers = 1

    def register(self, factory: InformerFactory) -> None:
        self.pv_informer = factory.informer("persistentvolumes", None)
        self.pv_informer.add_event_handler(self.handler())
        self.pvc_informer = factory.informer("persistentvolumeclaims", None)
        self.pvc_informer.add_event_handler(self._on_pvc)

    def _on_pvc(self, type_, obj, old) -> None:
        vol = (obj.get("spec") or {}).get("volumeName", "")
        if vol:
            self.queue.add(vol)

    def _bound(self, name: str) -> bool:
        for pvc in self.pvc_informer.store.list():
            if (pvc.get("spec") or {}).get("volumeName") == name:
                return True
        return False

    def sync(self, key: str) -> None:
        name = key.split("/")[-1]
        pvs = self.client.resource("persistentvolumes", None)
        try:
            pv = pvs.get(name)
        except ApiError as e:
            if e.code == 404:
                return
            raise
        md = pv.setdefault("metadata", {})
        fins = list(md.get("finalizers") or [])
        if md.get("deletionTimestamp"):
            if PV_FINALIZER in fins and not self._bound(name):
                md["finalizers"] = [f for f in fins if f != PV_FINALIZER]
                _update(pvs, pv)
        elif PV_FINALIZER not in fins:
            md["finalizers"] = fins + [PV_FINALIZER]
            _update(pvs, pv)
