"""CronJob controller — create Jobs on a cron schedule.

Reference: ``pkg/controller/cronjob/cronjob_controllerv2.go`` (``syncCronJob``:
compute the most recent scheduled time since lastScheduleTime, honor
``suspend``/``startingDeadlineSeconds``/``concurrencyPolicy``, create a Job
named ``<cronjob>-<scheduled-unix-minute>``, update
``status.lastScheduleTime``/``active``) with a minimal 5-field cron parser in
place of robfig/cron.
"""

from __future__ import annotations

import time
from functools import lru_cache

from kubernetes_tpu.client.clientset import ApiError
from kubernetes_tpu.client.informer import InformerFactory
from kubernetes_tpu.controllers.base import (
    Controller,
    is_controlled_by,
    owner_reference,
    split_key,
)
from kubernetes_tpu.controllers.job import job_finished


def _parse_field(spec: str, lo: int, hi: int) -> set[int]:
    out: set[int] = set()
    for part in spec.split(","):
        step = 1
        if "/" in part:
            part, s = part.split("/", 1)
            step = int(s)
        if part == "*":
            a, b = lo, hi
        elif "-" in part:
            a, b = (int(x) for x in part.split("-", 1))
        else:
            a = b = int(part)
        out.update(range(a, b + 1, step))
    return out


@lru_cache(maxsize=256)
def _compile(expr: str):
    """Parse a 5-field cron expression once into membership sets."""
    f = expr.split()
    if len(f) != 5:
        raise ValueError(f"bad cron expression {expr!r}")
    minute, hour, dom, month, dow = f
    # cron dow: 0 and 7 both mean Sunday — parse with hi=7 then fold 7 onto 0
    # (a textual 7→0 substitution would corrupt ranges like "5-7" or "*/7")
    dows = frozenset(d % 7 for d in _parse_field(dow, 0, 7))
    return (_parse_field(minute, 0, 59), _parse_field(hour, 0, 23),
            _parse_field(dom, 1, 31), _parse_field(month, 1, 12),
            dows, dom != "*", dow != "*")


def cron_matches(expr: str, ts: float) -> bool:
    """5-field cron (minute hour dom month dow) against a unix timestamp."""
    minutes, hours, doms, months, dows, dom_restr, dow_restr = _compile(expr)
    t = time.gmtime(ts)
    if (t.tm_min not in minutes or t.tm_hour not in hours
            or t.tm_mon not in months):
        return False
    dom_ok = t.tm_mday in doms
    # struct_time: Monday=0; cron: Sunday=0
    dow_ok = (t.tm_wday + 1) % 7 in dows
    # dom/dow OR-semantics when both are restricted (vixie cron)
    if dom_restr and dow_restr:
        return dom_ok or dow_ok
    return dom_ok and dow_ok


_HORIZON_S = 10 * 24 * 3600  # upstream's 'too many missed start times' guard


def most_recent_schedule(expr: str, earliest: float, now: float):
    """Latest minute in (earliest, now] matching ``expr`` (None if none).
    Scans minute-by-minute backwards, bounded to ~10 days like upstream's
    'too many missed start times' guard."""
    t = int(now) // 60 * 60
    floor = max(int(earliest), t - _HORIZON_S)
    while t > floor:
        if cron_matches(expr, t):
            return float(t)
        t -= 60
    return None


def next_schedule(expr: str, after: float):
    """First minute strictly after ``after`` matching ``expr`` (None if no
    match within the 10-day horizon)."""
    t = (int(after) // 60 + 1) * 60
    ceil = int(after) + _HORIZON_S
    while t <= ceil:
        if cron_matches(expr, t):
            return float(t)
        t += 60
    return None


class CronJobController(Controller):
    name = "cronjob"
    tick_interval = 1.0  # schedule resolution is one minute; 1s tick is cheap

    def __init__(self, client):
        super().__init__(client)
        # key -> (earliest used, next fire ts, most recent sched): between
        # fire times the minute scan's answer can't change for a fixed
        # earliest, so the 1s ticks reuse it and steady-state sync is O(1)
        self._sched_cache: dict[str, tuple[float, float, object]] = {}

    def register(self, factory: InformerFactory) -> None:
        self.cj_informer = factory.informer("cronjobs", None)
        self.cj_informer.add_event_handler(self.handler())
        self.job_informer = factory.informer("jobs", None)
        self.job_informer.add_event_handler(
            self.handler(lambda obj: self.enqueue_owner(obj, "CronJob")))

    def tick(self) -> None:
        for cj in self.cj_informer.store.list():
            self.enqueue(cj)

    def _owned_jobs(self, cj: dict) -> list[dict]:
        ns = (cj.get("metadata") or {}).get("namespace", "")
        return [j for j in self.job_informer.store.list()
                if (j.get("metadata") or {}).get("namespace", "") == ns
                and is_controlled_by(j, cj)]

    def sync(self, key: str) -> None:
        ns, name = split_key(key)
        cj = self.cj_informer.store.get(key)
        if cj is None:
            self._sched_cache.pop(key, None)
            return
        spec = cj.get("spec") or {}
        status = cj.get("status") or {}
        owned = self._owned_jobs(cj)
        active = [j for j in owned if not job_finished(j)]
        now = time.time()

        if spec.get("suspend"):
            return
        expr = spec.get("schedule", "")
        if not expr:
            return
        earliest = status.get("lastScheduleTime")
        if earliest is None:
            # A brand-new CronJob is eligible for the minute boundary just
            # passed, so its first Job doesn't wait out the current minute.
            created = (cj.get("metadata") or {}).get("creationTimestamp") or now
            earliest = float(created) - 60.0
        cached = self._sched_cache.get(key)
        if (cached is not None and cached[0] == (earliest, expr)
                and now < cached[1]):
            sched = cached[2]
        else:
            try:
                sched = most_recent_schedule(expr, float(earliest), now)
                nxt = next_schedule(expr, now)
            except ValueError as e:
                # Surface the broken expression on the object instead of
                # spinning through the requeue loop every tick (upstream
                # records an UnparseableSchedule event and skips).
                self._set_invalid_schedule(ns, cj, str(e))
                return
            self._sched_cache[key] = (
                (earliest, expr), nxt if nxt is not None else now + 3600.0,
                sched)
        if sched is None:
            self._update_status(ns, cj, active)
            return
        deadline = spec.get("startingDeadlineSeconds")
        if deadline is not None and now - sched > float(deadline):
            self._update_status(ns, cj, active)  # missed its window
            return
        # A Job for this schedule time already exists (possibly finished, or
        # created a tick ago before lastScheduleTime landed): nothing to
        # start, and crucially Replace must not delete it.
        job_name = f"{name}-{int(sched) // 60}"
        if any((j.get("metadata") or {}).get("name") == job_name
               for j in owned):
            self._update_status(ns, cj, active, sched)
            return
        policy = spec.get("concurrencyPolicy", "Allow")
        if active and policy == "Forbid":
            self._update_status(ns, cj, active)
            return
        if active and policy == "Replace":
            for j in active:
                try:
                    self.client.resource("jobs", ns).delete(
                        (j.get("metadata") or {}).get("name", ""))
                except ApiError as e:
                    if e.code != 404:
                        raise
            active = []

        tpl = (spec.get("jobTemplate") or {})
        job = {"apiVersion": "apps/v1", "kind": "Job",
               "metadata": {**dict(tpl.get("metadata") or {}),
                            "name": job_name, "namespace": ns,
                            "ownerReferences": [owner_reference(cj, "CronJob")]},
               "spec": dict(tpl.get("spec") or {})}
        try:
            self.client.resource("jobs", ns).create(job)
        except ApiError as e:
            if e.code != 409:  # AlreadyExists: another worker won the race
                raise
        self._update_status(ns, cj, active + [job], sched)

    def _set_invalid_schedule(self, ns, cj, msg: str) -> None:
        status = dict(cj.get("status") or {})
        cond = {"type": "InvalidSchedule", "status": "True", "message": msg}
        if status.get("conditions") == [cond]:
            return
        status["conditions"] = [cond]
        desired = dict(cj)
        desired["status"] = status
        try:
            self.client.resource("cronjobs", ns).update_status(desired)
        except ApiError as e:
            if e.code not in (404, 409):
                raise

    def _update_status(self, ns, cj, active, sched=None):
        status = dict(cj.get("status") or {})
        status.pop("conditions", None)  # clear a stale InvalidSchedule
        if sched is not None:
            status["lastScheduleTime"] = sched
        status["active"] = [
            {"kind": "Job", "name": (j.get("metadata") or {}).get("name", ""),
             "namespace": ns} for j in active]
        if status == (cj.get("status") or {}):
            return
        desired = dict(cj)
        desired["status"] = status
        try:
            self.client.resource("cronjobs", ns).update_status(desired)
        except ApiError as e:
            if e.code not in (404, 409):
                raise
