"""Root-CA publisher — kube-root-ca.crt in every namespace.

Reference: ``pkg/controller/certificates/rootcacertpublisher``: every
namespace gets (and keeps) a ``kube-root-ca.crt`` ConfigMap carrying the
cluster CA bundle so workloads can verify the apiserver; deletions and
drift are healed on the next sync. The CA pem comes from the cluster CA
(controllers/certificates.py ClusterCA) or any caller-supplied bundle.
"""

from __future__ import annotations

from kubernetes_tpu.client.clientset import ApiError
from kubernetes_tpu.client.informer import InformerFactory
from kubernetes_tpu.controllers.base import Controller

CONFIGMAP_NAME = "kube-root-ca.crt"


class RootCAPublisher(Controller):
    name = "root-ca-cert-publisher"
    workers = 1

    def __init__(self, client, ca_pem: str = ""):
        super().__init__(client)
        if not ca_pem:
            from cryptography.hazmat.primitives import serialization
            from kubernetes_tpu.controllers.certificates import generate_ca
            cert, _key = generate_ca()
            ca_pem = cert.public_bytes(serialization.Encoding.PEM).decode()
        self.ca_pem = ca_pem

    def register(self, factory: InformerFactory) -> None:
        self.ns_informer = factory.informer("namespaces", None)
        self.ns_informer.add_event_handler(self.handler())
        self.cm_informer = factory.informer("configmaps", None)
        self.cm_informer.add_event_handler(self._on_configmap)

    def _on_configmap(self, type_, obj, old) -> None:
        md = obj.get("metadata") or {}
        if md.get("name") == CONFIGMAP_NAME:
            # deleted or drifted bundle: re-enqueue the namespace to heal
            self.queue.add(md.get("namespace", "default"))

    def sync(self, key: str) -> None:
        ns = key.split("/")[-1]
        cms = self.client.resource("configmaps", ns)
        want = {"ca.crt": self.ca_pem}
        try:
            cm = cms.get(CONFIGMAP_NAME)
            if cm.get("data") == want:
                return
            cm["data"] = want
            cms.update(cm)  # ktpu-lint: disable=KTL006 -- reconcile, not status publish: failures must RAISE so the workqueue requeues; the best-effort upsert would swallow them
        except ApiError as e:
            if e.code != 404:
                raise
            try:
                # ktpu-lint: disable=KTL006 -- reconcile, not status publish: non-409 failures must RAISE so the workqueue requeues; the best-effort upsert would swallow them
                cms.create({"kind": "ConfigMap",
                            "metadata": {"name": CONFIGMAP_NAME,
                                         "namespace": ns},
                            "data": want})
            except ApiError as e2:
                if e2.code != 409:
                    raise
