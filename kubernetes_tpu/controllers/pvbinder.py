"""PersistentVolume controller — bind claims, provision dynamic volumes.

Reference: ``pkg/controller/volume/persistentvolume/pv_controller.go``
(``syncUnboundClaim``: Immediate-mode claims bind to the smallest matching
PV; WaitForFirstConsumer claims wait for the scheduler's selected-node
annotation) + the external-provisioner contract (claims annotated
``volume.kubernetes.io/selected-node`` with a provisioner-backed class get a
volume created for them — played in-process here).
"""

from __future__ import annotations

from kubernetes_tpu.client.clientset import ApiError
from kubernetes_tpu.client.informer import InformerFactory
from kubernetes_tpu.controllers.base import Controller, split_key
from kubernetes_tpu.sched.volumebinding import (
    SELECTED_NODE_ANNOTATION,
    WAIT_FOR_FIRST_CONSUMER,
    VolumeCatalog,
    find_matching_pvs,
)


class PersistentVolumeController(Controller):
    name = "pvbinder"

    def register(self, factory: InformerFactory) -> None:
        self.pvc_informer = factory.informer("persistentvolumeclaims", None)
        self.pvc_informer.add_event_handler(self.handler())
        self.pv_informer = factory.informer("persistentvolumes", None)
        self.pv_informer.add_event_handler(self.handler(self._requeue_unbound))
        self.sc_informer = factory.informer("storageclasses", None)

    def _requeue_unbound(self, _pv: dict) -> None:
        for pvc in self.pvc_informer.store.list():
            if not (pvc.get("spec") or {}).get("volumeName"):
                self.enqueue(pvc)

    def _catalog(self) -> VolumeCatalog:
        return VolumeCatalog.from_lists(
            pvcs=self.pvc_informer.store.list(),
            pvs=self.pv_informer.store.list(),
            storage_classes=self.sc_informer.store.list())

    def sync(self, key: str) -> None:
        ns, name = split_key(key)
        pvc = self.pvc_informer.store.get(key)
        if pvc is None:
            return
        spec = pvc.get("spec") or {}
        if spec.get("volumeName"):
            self._ensure_bound_status(pvc)
            return
        catalog = self._catalog()
        sc_name = spec.get("storageClassName", "") or ""
        sc = catalog.storage_classes.get(sc_name)
        selected = ((pvc.get("metadata") or {}).get("annotations") or {}) \
            .get(SELECTED_NODE_ANNOTATION, "")
        wait_mode = bool(sc) and sc.get("volumeBindingMode",
                                        "Immediate") == WAIT_FOR_FIRST_CONSUMER
        if wait_mode and not selected:
            return  # scheduler picks the node first
        matches = find_matching_pvs(pvc, catalog)
        if matches:
            self._bind(pvc, matches[0])
        elif sc and sc.get("provisioner") and (selected or not wait_mode):
            self._provision(pvc, sc, selected)

    # ---- binding ---------------------------------------------------------

    def _bind(self, pvc: dict, pv: dict) -> None:
        md = pvc["metadata"]
        pv = dict(pv)
        pv["spec"] = {**(pv.get("spec") or {}),
                      "claimRef": {"kind": "PersistentVolumeClaim",
                                   "namespace": md.get("namespace", "default"),
                                   "name": md["name"], "uid": md.get("uid", "")}}
        pv["status"] = {**(pv.get("status") or {}), "phase": "Bound"}
        self.client.resource("persistentvolumes", None).update(pv)
        pvc = dict(pvc)
        pvc["spec"] = {**(pvc.get("spec") or {}),
                       "volumeName": pv["metadata"]["name"]}
        self.client.resource("persistentvolumeclaims",
                             md.get("namespace", "default")).update(pvc)
        self._ensure_bound_status(
            self.client.resource("persistentvolumeclaims",
                                 md.get("namespace", "default")).get(md["name"]))

    def _ensure_bound_status(self, pvc: dict) -> None:
        if (pvc.get("status") or {}).get("phase") == "Bound":
            return
        try:
            self.client.resource("persistentvolumeclaims",
                                 pvc["metadata"].get("namespace", "default")) \
                .update_status({**pvc, "status": {"phase": "Bound"}})
        except ApiError as e:
            if e.code not in (404, 409):
                raise

    def _provision(self, pvc: dict, sc: dict, selected_node: str) -> None:
        md = pvc["metadata"]
        spec = pvc.get("spec") or {}
        req = ((spec.get("resources") or {}).get("requests") or {}) \
            .get("storage", "1Gi")
        pv = {
            "apiVersion": "v1", "kind": "PersistentVolume",
            "metadata": {"name": f"pvc-{md.get('uid', md['name'])}",
                         "labels": {}},
            "spec": {"capacity": {"storage": req},
                     "accessModes": list(spec.get("accessModes") or
                                         ["ReadWriteOnce"]),
                     "storageClassName": spec.get("storageClassName", ""),
                     "claimRef": {"kind": "PersistentVolumeClaim",
                                  "namespace": md.get("namespace", "default"),
                                  "name": md["name"],
                                  "uid": md.get("uid", "")}},
            "status": {"phase": "Bound"},
        }
        if selected_node:
            # provisioned volume is reachable only from the selected node's
            # topology (external-provisioner sets real accessible topology;
            # node-pinned is the strictest faithful choice)
            pv["spec"]["nodeAffinity"] = {"required": {"nodeSelectorTerms": [
                {"matchFields": [{"key": "metadata.name", "operator": "In",
                                  "values": [selected_node]}]}]}}
        try:
            self.client.resource("persistentvolumes", None).create(pv)
        except ApiError as e:
            if e.code != 409:
                raise
        pvc = dict(pvc)
        pvc["spec"] = {**spec, "volumeName": pv["metadata"]["name"]}
        self.client.resource("persistentvolumeclaims",
                             md.get("namespace", "default")).update(pvc)
