"""Ephemeral-volume controller — PVCs for generic ephemeral volumes.

Reference: ``pkg/controller/volume/ephemeral/controller.go``: a pod volume
with ``ephemeral.volumeClaimTemplate`` gets a PersistentVolumeClaim named
``<pod>-<volume>``, owned by the pod (so it dies with it); the controller
refuses to adopt a same-named claim that is NOT owned by the pod
(conflict -> event, pod stays pending) exactly like upstream's
ephemeral_controller conflict check.
"""

from __future__ import annotations

from kubernetes_tpu.client.clientset import ApiError
from kubernetes_tpu.client.informer import InformerFactory
from kubernetes_tpu.controllers.base import Controller, split_key


class EphemeralVolumeController(Controller):
    name = "ephemeral"
    workers = 1

    def __init__(self, client):
        super().__init__(client)
        from kubernetes_tpu.utils.events import EventRecorder
        self.recorder = EventRecorder(client, "ephemeral-volume-controller")

    def register(self, factory: InformerFactory) -> None:
        self.pod_informer = factory.informer("pods", None)
        self.pod_informer.add_event_handler(self.handler())
        self.pvc_informer = factory.informer("persistentvolumeclaims", None)

    def sync(self, key: str) -> None:
        ns, _name = split_key(key)
        pod = self.pod_informer.store.get(key)
        if pod is None:
            return
        md = pod.get("metadata") or {}
        if md.get("deletionTimestamp"):
            return  # claims are owned: GC reaps them with the pod
        pvcs = self.client.resource("persistentvolumeclaims", ns)
        for vol in (pod.get("spec") or {}).get("volumes") or []:
            eph = vol.get("ephemeral") or {}
            tmpl = eph.get("volumeClaimTemplate")
            if not tmpl:
                continue
            claim_name = f"{md.get('name', '')}-{vol.get('name', '')}"
            existing = self.pvc_informer.store.get(f"{ns}/{claim_name}")
            if existing is not None:
                if not self._owned_by(existing, pod):
                    # same-named foreign claim: NEVER adopt (data of
                    # another workload); surface and leave the pod pending
                    self.recorder_event(pod, claim_name)
                continue
            claim = {
                "kind": "PersistentVolumeClaim",
                "metadata": {
                    "name": claim_name, "namespace": ns,
                    "labels": dict((tmpl.get("metadata") or {})
                                   .get("labels") or {}),
                    "annotations": dict((tmpl.get("metadata") or {})
                                        .get("annotations") or {}),
                    "ownerReferences": [{
                        "apiVersion": "v1", "kind": "Pod",
                        "name": md.get("name", ""),
                        "uid": md.get("uid", ""),
                        "controller": True,
                        "blockOwnerDeletion": True}],
                },
                "spec": dict(tmpl.get("spec") or {}),
            }
            try:
                pvcs.create(claim)
            except ApiError as e:
                if e.code != 409:
                    raise

    @staticmethod
    def _owned_by(claim: dict, pod: dict) -> bool:
        pod_uid = (pod.get("metadata") or {}).get("uid", "")
        return any(ref.get("kind") == "Pod" and ref.get("uid") == pod_uid
                   for ref in (claim.get("metadata") or {})
                   .get("ownerReferences") or [])

    def recorder_event(self, pod: dict, claim_name: str) -> None:
        self.recorder.event(pod, "Warning", "ConflictingPVC",
                            f"PVC {claim_name!r} exists and is not owned "
                            "by the pod")
