"""Controller base — the informer + workqueue + sync(key) reconcile pattern.

Reference shape: every controller in ``pkg/controller/<name>/`` is informer
event handlers enqueueing keys into a rate-limited workqueue, N workers
popping keys and running ``syncX(key)``; errors requeue with backoff,
successes forget. Wiring mirrors ``pkg/controller/controller_utils.go``
(owner-reference helpers: ``GetControllerOf``, adoption semantics).
"""

from __future__ import annotations

import logging
import threading
from typing import Callable, Optional

from kubernetes_tpu.client.informer import InformerFactory, meta_namespace_key
from kubernetes_tpu.client.workqueue import RateLimitingQueue

_LOG = logging.getLogger(__name__)

MAX_REQUEUES = 15  # maxRetries in most upstream controllers


def controller_of(obj: dict) -> Optional[dict]:
    """The ownerReference with controller=true (metav1.GetControllerOf)."""
    for ref in (obj.get("metadata") or {}).get("ownerReferences") or []:
        if ref.get("controller"):
            return ref
    return None


def is_controlled_by(obj: dict, owner: dict) -> bool:
    ref = controller_of(obj)
    return ref is not None and ref.get("uid") == (owner.get("metadata") or {}).get("uid")


def owner_reference(owner: dict, kind: str) -> dict:
    md = owner.get("metadata") or {}
    return {
        "apiVersion": owner.get("apiVersion", "apps/v1"),
        "kind": kind,
        "name": md.get("name", ""),
        "uid": md.get("uid", ""),
        "controller": True,
        "blockOwnerDeletion": True,
    }


class Controller:
    """Workqueue-driven reconcile loop.

    Subclasses set ``name``, register informers in ``register(factory)`` and
    implement ``sync(key)``. ``enqueue(obj)`` / ``enqueue_owner(obj, kind)``
    are the standard event-handler bodies.
    """

    name = "controller"
    workers = 2
    # time-driven controllers (cronjob schedule ticks, TTL expiry, HPA
    # evaluation) set tick_interval and implement tick(); the base runs it
    # on a timer alongside the workers (the upstream analog is the informer
    # resync period re-delivering every object)
    tick_interval: Optional[float] = None

    def __init__(self, client):
        self.client = client
        self.queue = RateLimitingQueue()
        self._threads: list[threading.Thread] = []
        self._stop = threading.Event()

    # ---- wiring ----------------------------------------------------------

    def register(self, factory: InformerFactory) -> None:
        raise NotImplementedError

    def sync(self, key: str) -> None:
        raise NotImplementedError

    def enqueue(self, obj: dict) -> None:
        self.queue.add(meta_namespace_key(obj))

    def enqueue_owner(self, obj: dict, kind: str) -> None:
        """Enqueue the controlling owner of ``obj`` if it has the given kind
        (resolveControllerRef pattern: pod events wake the ReplicaSet, etc.)."""
        ref = controller_of(obj)
        if ref is not None and ref.get("kind") == kind:
            ns = (obj.get("metadata") or {}).get("namespace", "")
            self.queue.add(f"{ns}/{ref['name']}" if ns else ref["name"])

    def handler(self, enqueue_fn: Optional[Callable] = None):
        fn = enqueue_fn or self.enqueue

        def on_event(type_, obj, old):
            fn(obj)
        return on_event

    # ---- worker loop -----------------------------------------------------

    def start(self):
        for i in range(self.workers):
            t = threading.Thread(target=self._worker, daemon=True,
                                 name=f"{self.name}-{i}")
            t.start()
            self._threads.append(t)
        if self.tick_interval:
            t = threading.Thread(target=self._tick_loop, daemon=True,
                                 name=f"{self.name}-tick")
            t.start()
            self._threads.append(t)
        return self

    def tick(self) -> None:
        """Periodic work for time-driven controllers (see tick_interval)."""

    def _tick_loop(self):
        while not self._stop.wait(self.tick_interval):
            try:
                self.tick()
            except Exception:
                # the loop survives, but a failing tick is a stalled
                # controller — it must be visible in the logs
                _LOG.exception("%s tick failed; retrying next interval",
                               type(self).__name__)

    def stop(self):
        self._stop.set()
        self.queue.close()
        for t in self._threads:
            t.join(timeout=2.0)

    def _worker(self):
        while not self._stop.is_set():
            key = self.queue.get(timeout=0.2)
            if key is None:
                continue
            try:
                self.sync(key)
            except Exception:
                _LOG.exception("%s sync of %r failed",
                               type(self).__name__, key)
                if self.queue.num_requeues(key) < MAX_REQUEUES:
                    self.queue.add_rate_limited(key)
                else:
                    self.queue.forget(key)
            else:
                self.queue.forget(key)
            finally:
                self.queue.done(key)


def split_key(key: str) -> tuple[str, str]:
    ns, _, name = key.rpartition("/")
    return ns, name


def active_pods(pods: list[dict]) -> list[dict]:
    """Pods not terminal and not being deleted (controller_utils FilterActivePods)."""
    return [p for p in pods
            if (p.get("status") or {}).get("phase") not in ("Succeeded", "Failed")
            and not (p.get("metadata") or {}).get("deletionTimestamp")]
