"""ResourceQuota controller — keep quota status.used in sync with reality.

Reference: ``pkg/controller/resourcequota/resource_quota_controller.go``:
the admission plugin ENFORCES quota at write time (store/admission.py
``resource_quota``); this controller RECALCULATES ``status.used`` from live
objects so users (and the admission fast path upstream) see current usage —
on quota add/update, on a full resync tick, and when pods churn.

Usage model mirrored from ``pkg/quota/v1/evaluator/core``: non-terminal
pods contribute ``pods``, ``requests.cpu``, ``requests.memory`` (and bare
``cpu``/``memory`` aliases); ``count/<plural>`` tracks object counts for
the common namespaced kinds served here.
"""

from __future__ import annotations

from kubernetes_tpu.api.resource import canonical
from kubernetes_tpu.client.clientset import ApiError
from kubernetes_tpu.client.informer import InformerFactory, meta_namespace_key
from kubernetes_tpu.controllers.base import Controller

_COUNTED = {"count/configmaps": "configmaps", "count/secrets": "secrets",
            "count/services": "services",
            "count/persistentvolumeclaims": "persistentvolumeclaims",
            "count/replicationcontrollers": "replicationcontrollers",
            "count/deployments.apps": "deployments",
            "count/jobs.batch": "jobs"}


def _fmt(resource: str, amount: int) -> str:
    """Canonical units back to wire quantities (cpu millis -> 'Nm')."""
    key = resource.split("requests.", 1)[-1]
    if key == "cpu":
        return f"{amount}m"
    return str(amount)


class ResourceQuotaController(Controller):
    name = "resourcequota"
    workers = 1
    # upstream's full resync is every 5m; event-driven enqueues (quota
    # changes, pod churn) carry the steady state — tests override this
    tick_interval = 300.0

    def register(self, factory: InformerFactory) -> None:
        self.quota_informer = factory.informer("resourcequotas", None)
        self.quota_informer.add_event_handler(
            lambda t, obj, old: self.enqueue(obj))
        # pod churn re-syncs the owning namespace's quotas
        self.pod_informer = factory.informer("pods", None)
        self.pod_informer.add_event_handler(self._on_pod)

    def _on_pod(self, type_, obj, old) -> None:
        ns = (obj.get("metadata") or {}).get("namespace", "default")
        for q in self.quota_informer.store.list():
            if (q.get("metadata") or {}).get("namespace") == ns:
                self.enqueue(q)

    def tick(self) -> None:
        for q in self.quota_informer.store.list():
            self.enqueue(q)

    def sync(self, key: str) -> None:
        ns, _, name = key.partition("/")
        res = self.client.resource("resourcequotas", ns)
        try:
            quota = res.get(name)
        except ApiError as e:
            if e.code == 404:
                return
            raise
        hard = (quota.get("spec") or {}).get("hard") or {}
        used: dict[str, str] = {}
        pods = [p for p in self.pod_informer.store.list()
                if (p.get("metadata") or {}).get("namespace") == ns
                and (p.get("status") or {}).get("phase")
                not in ("Succeeded", "Failed")]
        for r in hard:
            if r == "pods":
                used[r] = str(len(pods))
            elif r in ("cpu", "memory", "requests.cpu", "requests.memory"):
                key_r = r.split("requests.", 1)[-1]
                total = 0
                for p in pods:
                    for c in ((p.get("spec") or {}).get("containers") or []):
                        req = ((c.get("resources") or {})
                               .get("requests") or {})
                        if key_r in req:
                            total += canonical(key_r, req[key_r])
                used[r] = _fmt(r, total)
            elif r in _COUNTED:
                try:
                    n = len(self.client.resource(_COUNTED[r], ns).list())
                except ApiError:
                    n = 0
                used[r] = str(n)
        status = quota.get("status") or {}
        if status.get("used") == used and status.get("hard") == hard:
            return
        quota["status"] = {"hard": dict(hard), "used": used}
        try:
            res.update_status(quota)
        except ApiError as e:
            if e.code not in (404, 409):  # 409: raced; requeue via churn
                raise
