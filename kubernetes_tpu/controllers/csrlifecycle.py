"""CSR approving + cleaning — the other two certificate controllers.

Reference: ``pkg/controller/certificates/approver`` (auto-approve kubelet
client CSRs whose requestor is a node/bootstrapper identity — the
``sarapprove`` flow minus the SubjectAccessReview, which our RBAC layer
answers implicitly via group membership) and
``pkg/controller/certificates/cleaner`` (drop CSRs that are approved+issued,
denied, failed, or simply stale after an hour — the API is a request queue,
not a certificate store).
"""

from __future__ import annotations

import time

from kubernetes_tpu.client.clientset import ApiError
from kubernetes_tpu.client.informer import InformerFactory
from kubernetes_tpu.controllers.base import Controller
from kubernetes_tpu.controllers.certificates import _is_approved, _is_denied
from kubernetes_tpu.utils.clock import rfc3339_now

SIGNER_KUBELET_CLIENT = "kubernetes.io/kube-apiserver-client-kubelet"
NODE_GROUPS = ("system:nodes", "system:bootstrappers")


class CSRApprovingController(Controller):
    """Auto-approve kubelet client certificate requests from node
    identities (csrapproving)."""

    name = "csrapproving"
    workers = 1

    def register(self, factory: InformerFactory) -> None:
        self.csr_informer = factory.informer("certificatesigningrequests",
                                             None)
        self.csr_informer.add_event_handler(self.handler())

    def _eligible(self, csr: dict) -> bool:
        spec = csr.get("spec") or {}
        if spec.get("signerName") != SIGNER_KUBELET_CLIENT:
            return False
        groups = set(spec.get("groups") or [])
        username = spec.get("username", "")
        return bool(groups & set(NODE_GROUPS)) \
            or username.startswith("system:node:")

    def sync(self, key: str) -> None:
        res = self.client.resource("certificatesigningrequests", None)
        try:
            csr = res.get(key)
        except ApiError as e:
            if e.code == 404:
                return
            raise
        if _is_approved(csr) or _is_denied(csr) or not self._eligible(csr):
            return
        status = csr.setdefault("status", {})
        status.setdefault("conditions", []).append(
            {"type": "Approved", "status": "True",
             "reason": "AutoApproved",
             "message": "Auto approving kubelet client certificate after "
                        "SubjectAccessReview.",
             "lastUpdateTime": rfc3339_now()})
        try:
            res.update_status(csr)
        except ApiError as e:
            if e.code not in (404, 409):
                raise


class CSRCleanerController(Controller):
    """Garbage-collect finished or stale CSRs (cleaner.go: issued ones
    after 1h, denied/failed after 1h, unresolved after 24h; one tick
    interval here for all, configurable)."""

    name = "csrcleaner"
    workers = 1
    tick_interval = 60.0

    def __init__(self, client, issued_ttl: float = 3600.0,
                 stale_ttl: float = 24 * 3600.0):
        super().__init__(client)
        self.issued_ttl = issued_ttl
        self.stale_ttl = stale_ttl

    def register(self, factory: InformerFactory) -> None:
        self.csr_informer = factory.informer("certificatesigningrequests",
                                             None)

    @staticmethod
    def _age(csr: dict) -> float:
        created = (csr.get("metadata") or {}).get("creationTimestamp")
        try:
            return time.time() - float(created)
        except (TypeError, ValueError):
            return 0.0

    def _expired(self, csr: dict) -> bool:
        age = self._age(csr)
        status = csr.get("status") or {}
        finished = (status.get("certificate") or _is_denied(csr)
                    or any(c.get("type") == "Failed"
                           for c in status.get("conditions") or []))
        if finished:
            return age > self.issued_ttl
        return age > self.stale_ttl

    def tick(self) -> None:
        res = self.client.resource("certificatesigningrequests", None)
        for csr in self.csr_informer.store.list():
            if self._expired(csr):
                try:
                    res.delete((csr.get("metadata") or {}).get("name", ""))
                except ApiError as e:
                    if e.code != 404:
                        raise

    def sync(self, key: str) -> None:
        pass  # purely tick-driven
