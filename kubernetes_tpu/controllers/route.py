"""Route controller — cloud routes for node pod CIDRs.

Reference: ``staging/src/k8s.io/cloud-provider/controllers/route``
(``reconcile``: CreateRoute for every node's podCIDR, DeleteRoute for
routes whose node is gone, then flip the node's NetworkUnavailable
condition to False — kubelets refuse pods until that happens). The cloud
route table is an in-process dict; the node-condition side effect is the
part the rest of the cluster observes.
"""

from __future__ import annotations

import threading

from kubernetes_tpu.client.clientset import ApiError
from kubernetes_tpu.client.informer import InformerFactory
from kubernetes_tpu.controllers.base import Controller
from kubernetes_tpu.utils.clock import rfc3339_now

RECONCILE_KEY = "_routes"


class RouteController(Controller):
    name = "route"
    workers = 1

    def __init__(self, client):
        super().__init__(client)
        self.routes: dict[str, str] = {}  # node -> cidr (the cloud table)
        self._lock = threading.Lock()

    def register(self, factory: InformerFactory) -> None:
        self.node_informer = factory.informer("nodes", None)
        self.node_informer.add_event_handler(
            lambda *_a: self.queue.add(RECONCILE_KEY))

    def sync(self, key: str) -> None:
        nodes = {(n.get("metadata") or {}).get("name", ""): n
                 for n in self.node_informer.store.list()}
        with self._lock:
            # delete routes for vanished nodes or changed CIDRs
            for name in [n for n, cidr in self.routes.items()
                         if (n not in nodes
                             or (nodes[n].get("spec") or {})
                             .get("podCIDR", "") != cidr)]:
                del self.routes[name]
            created = []
            for name, node in nodes.items():
                cidr = (node.get("spec") or {}).get("podCIDR", "")
                if cidr and self.routes.get(name) != cidr:
                    self.routes[name] = cidr  # CreateRoute
                    created.append(name)
        res = self.client.resource("nodes", None)
        for name in created:
            ok = False
            try:
                node = res.get(name)
                st = node.setdefault("status", {})
                conds = [c for c in st.get("conditions") or []
                         if c.get("type") != "NetworkUnavailable"]
                conds.append({"type": "NetworkUnavailable",
                              "status": "False",
                              "reason": "RouteCreated",
                              "message": "RouteController created a route",
                              "lastTransitionTime": rfc3339_now()})
                st["conditions"] = conds
                res.update_status(node)
                ok = True
            except ApiError as e:
                if e.code == 404:
                    continue  # node gone; the delete pass reaps the route
            if not ok:
                # the condition flip is the externally-observable half of
                # CreateRoute: un-record the route so the requeue retries
                # it (a 409 against a heartbeat would otherwise leave the
                # node NetworkUnavailable forever)
                with self._lock:
                    self.routes.pop(name, None)
                self.queue.add(RECONCILE_KEY)
