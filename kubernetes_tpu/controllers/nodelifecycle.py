"""Node lifecycle controller — taint unhealthy nodes, evict their pods.

Reference: ``pkg/controller/nodelifecycle/node_lifecycle_controller.go``
(monitorNodeHealth: Ready condition staleness -> NoExecute ``not-ready`` /
``unreachable`` taints) and the NoExecute taint-manager eviction path
(``tainteviction/``: pods without a matching toleration are evicted after
tolerationSeconds).

Disruption modes (upstream handleDisruption): when the unready fraction
crosses ``unhealthyZoneThreshold`` (default 0.55) the controller stops
trusting its own staleness signal — mass unreadiness is far more likely
an apiserver/network outage than half the fleet dying at once, and the
worst possible response is a fleet-wide taint/evict storm the moment the
control plane comes back:

  Normal             taint + evict as usual (unthrottled)
  PartialDisruption  fraction >= threshold; small clusters
                     (< largeClusterSizeThreshold) halt evictions, large
                     ones add NoExecute taints at the reduced secondary
                     rate (upstream secondary-node-eviction-rate)
  FullDisruption     EVERY node unready: taints removed + evictions
                     halted entirely (upstream markNodeAsReachable on
                     entering full disruption)

Clusters smaller than ``min_disruption_nodes`` (default 3) never enter a
disruption mode — "mass-unready protection" needs a mass, and a one-node
cluster's single NotReady node is its own ground truth. The mode is a
gauge (``nodelifecycle_disruption_mode``), a status ConfigMap (the
``ktpu status`` Disruption line), and the DisasterChurn bench gate.
"""

from __future__ import annotations

import json
import logging
import threading
import time

from kubernetes_tpu.api.types import Pod, Taint
from kubernetes_tpu.client.clientset import ApiError
from kubernetes_tpu.client.informer import InformerFactory
from kubernetes_tpu.controllers.base import Controller, split_key
from kubernetes_tpu.metrics.registry import (
    DISRUPTION_MODE,
    NODELIFE_DEFERRED,
    NODELIFE_EVICTIONS,
)

_LOG = logging.getLogger("kubernetes_tpu.controllers.nodelifecycle")

TAINT_NOT_READY = "node.kubernetes.io/not-ready"
TAINT_UNREACHABLE = "node.kubernetes.io/unreachable"

DEFAULT_GRACE = 40.0  # nodeMonitorGracePeriod default 40s

MODE_NORMAL = "Normal"
MODE_PARTIAL = "PartialDisruption"
MODE_FULL = "FullDisruption"
_MODE_GAUGE = {MODE_NORMAL: 0, MODE_PARTIAL: 1, MODE_FULL: 2}

# ``ktpu status`` reads the Disruption line from this ConfigMap
NODELIFECYCLE_CONFIGMAP = "kubernetes-tpu-nodelifecycle-status"


def _ready_condition(node: dict):
    for c in (node.get("status") or {}).get("conditions") or []:
        if c.get("type") == "Ready":
            return c
    return None


class NodeLifecycleController(Controller):
    """Sync per node: reconcile health taints; evict intolerant pods on
    NoExecute-tainted nodes. A monitor thread recomputes the disruption
    mode and re-enqueues all nodes every ``monitor_period`` so staleness
    is noticed without events."""

    name = "nodelifecycle"

    def __init__(self, client, grace_period: float = DEFAULT_GRACE,
                 monitor_period: float = 5.0,
                 unhealthy_zone_threshold: float = 0.55,
                 large_cluster_threshold: int = 50,
                 secondary_eviction_rate_qps: float = 0.01,
                 min_disruption_nodes: int = 3,
                 status_namespace: str = "default"):
        super().__init__(client)
        self.grace_period = grace_period
        self.monitor_period = monitor_period
        self.unhealthy_zone_threshold = unhealthy_zone_threshold
        self.large_cluster_threshold = large_cluster_threshold
        self.secondary_eviction_rate_qps = secondary_eviction_rate_qps
        self.min_disruption_nodes = min_disruption_nodes
        self.status_namespace = status_namespace
        self._monitor: threading.Thread | None = None
        # disruption-mode state (written by the monitor thread, read by
        # sync workers; plain attribute reads — GIL-atomic)
        self.mode = MODE_NORMAL
        self.unready_fraction = 0.0
        self.cluster_size = 0
        self.engaged_count = 0  # times the mode left Normal
        self.transitions: list[dict] = []
        # taint/evict accounting (the DisasterChurn bench gates on these)
        self.evictions = 0
        self.evictions_deferred = 0
        self.taints_suppressed = 0
        # secondary-rate token bucket (PartialDisruption, large clusters)
        self._tokens = 1.0
        self._tokens_ts = time.monotonic()
        self._token_lock = threading.Lock()
        self._sweeps_since_publish = 0
        # fresh-grace shield: set when a disruption RELEASES *or* when
        # this controller's own informers heal a SIGNIFICANT watch gap
        # (>= min_shield_gap_s — the controller itself lived through a
        # connectivity loss, e.g. an apiserver restart). Staleness
        # accrued across either window is not evidence — without the
        # gap-heal trigger, a SHORT outage (< grace) lets nodes cross
        # grace staggered AFTER the heal and the first crossers are
        # tainted/evicted before the unready fraction can trip the
        # disruption threshold. Unreachable taints are suppressed until
        # a FULL grace window has re-elapsed (0 = no shield; upstream's
        # analog is the fresh probeTimestamp every node gets when the
        # controller restarts).
        self._normal_since = 0.0
        self._seen_gap_ends: dict[str, float] = {}

    def register(self, factory: InformerFactory) -> None:
        self.lease_informer = factory.informer("leases", None)
        self.node_informer = factory.informer("nodes", None)
        self.node_informer.add_event_handler(self.handler())
        self.pod_informer = factory.informer("pods", None)

    def start(self):
        super().start()
        self._monitor = threading.Thread(target=self._monitor_loop, daemon=True)
        self._monitor.start()
        return self

    def _monitor_loop(self):
        while not self._stop.wait(self.monitor_period):
            # mode FIRST: by the time a sync worker pops a key, the sweep
            # that enqueued it has already judged whether this is an
            # outage — a mass-unready sweep must never race its own keys
            # into un-protected syncs
            try:
                self._update_disruption_mode()
            except Exception:
                _LOG.exception("disruption-mode sweep failed")
            for key in self.node_informer.store.keys():
                self.queue.add(key)

    # ---- disruption modes (handleDisruption) ----------------------------

    # gaps shorter than this never grant the fleet-wide shield: a routine
    # TooOld relist under churn heals sub-second, and refreshing the
    # shield on every one would suppress dead-node detection forever
    min_shield_gap_s = 1.0

    def _observe_gap_heals(self) -> None:
        """Grant the fresh-grace shield when an informer heals a
        SIGNIFICANT watch gap (an apiserver outage, not watch-window
        churn): staleness bookkeeping that spans the gap is not
        evidence."""
        for attr in ("node_informer", "lease_informer"):
            inf = getattr(self, attr, None)
            if inf is None:
                continue
            end = inf.last_gap_end
            if end is None or end == self._seen_gap_ends.get(attr):
                continue
            self._seen_gap_ends[attr] = end
            if inf.last_gap_duration >= self.min_shield_gap_s:
                _LOG.warning(
                    "%s healed a %.1fs watch gap (control-plane outage):"
                    " granting the fleet a fresh %.0fs grace window",
                    attr, inf.last_gap_duration, self.grace_period)
                self._normal_since = max(self._normal_since, end)

    def _update_disruption_mode(self) -> None:
        self._observe_gap_heals()
        nodes = self.node_informer.store.list()
        total = len(nodes)
        self.cluster_size = total
        if total >= max(1, self.min_disruption_nodes):
            unready = sum(1 for n in nodes
                          if self._wanted_taint(n) is not None)
            frac = unready / total
        else:
            frac = 0.0  # too small to distinguish outage from dead nodes
        self.unready_fraction = frac
        if frac >= 1.0:
            mode = MODE_FULL
        elif frac >= self.unhealthy_zone_threshold:
            mode = MODE_PARTIAL
        else:
            mode = MODE_NORMAL
        changed = mode != self.mode
        if changed:
            _LOG.warning(
                "disruption mode %s -> %s (%d/%d nodes unready)",
                self.mode, mode, int(round(frac * total)), total)
            if self.mode == MODE_NORMAL:
                self.engaged_count += 1
            elif mode == MODE_NORMAL:
                # release: the laggards whose lease renewals haven't
                # landed yet are stale from the SAME outage that engaged
                # the mode — they must re-accrue a full grace window
                # before "unreachable" means anything again, or the
                # release itself taints/evicts half the fleet
                self._normal_since = time.time()
            self.mode = mode
            self.transitions.append(
                {"mode": mode, "at": time.time(),
                 "unreadyFraction": round(frac, 3), "nodes": total})
            del self.transitions[:-20]
            DISRUPTION_MODE.set(_MODE_GAUGE[mode])
        self._sweeps_since_publish += 1
        if changed or self._sweeps_since_publish >= 10:
            self._sweeps_since_publish = 0
            self.publish_status()

    def _evictions_halted(self) -> bool:
        return (self.mode == MODE_FULL
                or (self.mode == MODE_PARTIAL
                    and self.cluster_size < self.large_cluster_threshold))

    def _staleness_distrusted(self) -> bool:
        """True while staleness must not drive new unreachable taints: a
        watch gap is OPEN on the informers this controller judges from
        (their caches are aging untracked — the apiserver may be down or
        freshly restarted), or a gap/disruption healed less than one full
        grace period ago (the laggards' staleness is gap-era evidence)."""
        for inf in (getattr(self, "node_informer", None),
                    getattr(self, "lease_informer", None)):
            if inf is not None and inf.gap_since:
                return True
        return bool(self._normal_since
                    and time.time() - self._normal_since
                    < self.grace_period)

    def _take_eviction_token(self) -> bool:
        """Secondary-rate token bucket (PartialDisruption, large cluster):
        one NEW taint per 1/secondary_rate seconds across the fleet."""
        with self._token_lock:
            now = time.monotonic()
            self._tokens = min(
                1.0, self._tokens + (now - self._tokens_ts)
                * self.secondary_eviction_rate_qps)
            self._tokens_ts = now
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                return True
            return False

    def disruption_status(self) -> dict:
        return {
            "mode": self.mode,
            "unreadyFraction": round(self.unready_fraction, 3),
            "nodes": self.cluster_size,
            "evictionsHalted": self._evictions_halted(),
            "unhealthyZoneThreshold": self.unhealthy_zone_threshold,
            "largeClusterThreshold": self.large_cluster_threshold,
            "engagedCount": self.engaged_count,
            "evictions": self.evictions,
            "evictionsDeferred": self.evictions_deferred,
            "taintsSuppressed": self.taints_suppressed,
            "stalenessDistrusted": self._staleness_distrusted(),
            "transitions": self.transitions[-5:],
        }

    def publish_status(self) -> None:
        """Best-effort ConfigMap for ``ktpu status``; during the very
        outage this mode protects against, the write itself fails — it
        re-asserts on the first post-heal sweep."""
        from kubernetes_tpu.utils.configmap import upsert_configmap
        upsert_configmap(
            self.client, self.status_namespace, NODELIFECYCLE_CONFIGMAP,
            {"disruption": json.dumps(self.disruption_status())},
            site="nodelifecycle_publish")

    # ---- monitorNodeHealth ----------------------------------------------

    def _lease_renew_time(self, node_name: str):
        """renewTime of the node's kube-node-lease Lease, if any — lease
        renewal counts as a heartbeat (monitorNodeHealth's probeTimestamp
        advances on lease updates; upstream kubelets renew every 10s while
        touching node STATUS only 5-minutely)."""
        inf = getattr(self, "lease_informer", None)
        if inf is None:
            return None
        lease = inf.store.get(f"kube-node-lease/{node_name}")
        if lease is None:
            return None
        rt = (lease.get("spec") or {}).get("renewTime")
        try:
            return float(rt)
        except (TypeError, ValueError):
            return None

    def _wanted_taint(self, node: dict) -> str | None:
        cond = _ready_condition(node)
        if cond is None:
            return None  # no kubelet heartbeat model yet — leave untouched
        if cond.get("status") == "False":
            return TAINT_NOT_READY
        hb = cond.get("lastHeartbeatTime")
        renew = self._lease_renew_time(
            (node.get("metadata") or {}).get("name", ""))
        candidates = [renew]
        if hb is not None:
            candidates.append(float(hb))
        latest = max([t for t in candidates if t is not None],
                     default=None)
        if latest is not None and time.time() - latest > self.grace_period:
            return TAINT_UNREACHABLE
        if cond.get("status") == "Unknown" and renew is None:
            return TAINT_UNREACHABLE
        return None

    def sync(self, key: str) -> None:
        _, name = split_key(key)
        node = self.node_informer.store.get(key) or self.node_informer.store.get(name)
        if node is None:
            return
        wanted = self._wanted_taint(node)
        taints = [t for t in (node.get("spec") or {}).get("taints") or []]
        ours = [t for t in taints
                if t.get("key") in (TAINT_NOT_READY, TAINT_UNREACHABLE)
                and t.get("effect") == "NoExecute"]
        rest = [t for t in taints if t not in ours]
        evict_allowed = True
        if (wanted == TAINT_UNREACHABLE
                and self._staleness_distrusted()
                and not (ours and ours[0].get("key") == wanted)):
            # the staleness evidence spans a connectivity gap (open watch
            # gap, or inside the fresh-grace window after one healed):
            # suppress — an explicit Ready=False still taints, and the
            # disruption-mode FRACTION still counts raw staleness so
            # mass-unready protection engages regardless
            self.taints_suppressed += 1
            return
        if wanted:
            mode = self.mode
            already = bool(ours) and ours[0].get("key") == wanted
            if mode == MODE_FULL:
                # upstream markNodeAsReachable on entering full disruption:
                # the staleness signal itself is distrusted — drop OUR
                # taints and add none, so an apiserver outage leaves zero
                # taint/evict residue to storm through on reconnect
                self.taints_suppressed += 1
                wanted, evict_allowed = None, False
            elif mode == MODE_PARTIAL:
                if self._evictions_halted():
                    # small cluster: halt (upstream setLimiterInZone(0)) —
                    # existing taints stay, nothing new, no evictions
                    evict_allowed = False
                    if not already:
                        self.taints_suppressed += 1
                        return
                elif not already:
                    # large cluster: new taints trickle at the secondary
                    # eviction rate; deferred nodes retry next sweep
                    if not self._take_eviction_token():
                        self.evictions_deferred += 1
                        NODELIFE_DEFERRED.inc()
                        return
        added_ts = None
        if wanted:
            # Carry the existing timestamp if the same taint is already
            # present; otherwise this sync IS the add — the informer copy is
            # stale on this very sync, so the eviction check below must use
            # this value, not whatever the node object says.
            added_ts = (float(ours[0].get("timeAdded", time.time()))
                        if ours and ours[0].get("key") == wanted
                        else time.time())
        new_taints = rest + ([{"key": wanted, "effect": "NoExecute",
                               "timeAdded": added_ts}] if wanted else [])
        if new_taints != taints:
            obj = {**node, "spec": {**(node.get("spec") or {}), "taints": new_taints}}
            try:
                self.client.nodes().update(obj)
            except ApiError as e:
                if e.code not in (404, 409):
                    raise
        if wanted and evict_allowed:
            self._evict_intolerant(node, wanted, added_ts)

    # ---- NoExecute taint eviction ---------------------------------------

    def _evict_intolerant(self, node: dict, taint_key: str,
                          added: float) -> None:
        node_name = (node.get("metadata") or {}).get("name", "")
        taint_obj = Taint(key=taint_key, effect="NoExecute")
        for p in self.pod_informer.store.list():
            if (p.get("spec") or {}).get("nodeName") != node_name:
                continue
            if (p.get("status") or {}).get("phase") in ("Succeeded", "Failed"):
                continue
            pod = Pod.from_dict(p)
            matching = [t for t in pod.spec.tolerations if t.tolerates(taint_obj)]
            if matching:
                secs = [t.toleration_seconds for t in matching]
                if any(s is None for s in secs):
                    continue  # tolerates forever
                if time.time() - added < min(s for s in secs if s is not None):
                    continue  # still within tolerationSeconds
            try:
                self.client.pods(pod.metadata.namespace).evict(pod.metadata.name)
            except ApiError as e:
                if e.code != 404:
                    raise
            else:
                self.evictions += 1
                NODELIFE_EVICTIONS.inc()
