"""Node lifecycle controller — taint unhealthy nodes, evict their pods.

Reference: ``pkg/controller/nodelifecycle/node_lifecycle_controller.go``
(monitorNodeHealth: Ready condition staleness -> NoExecute ``not-ready`` /
``unreachable`` taints) and the NoExecute taint-manager eviction path
(``tainteviction/``: pods without a matching toleration are evicted after
tolerationSeconds).
"""

from __future__ import annotations

import threading
import time

from kubernetes_tpu.api.types import Pod, Taint, Toleration
from kubernetes_tpu.client.clientset import ApiError
from kubernetes_tpu.client.informer import InformerFactory
from kubernetes_tpu.controllers.base import Controller, split_key

TAINT_NOT_READY = "node.kubernetes.io/not-ready"
TAINT_UNREACHABLE = "node.kubernetes.io/unreachable"

DEFAULT_GRACE = 40.0  # nodeMonitorGracePeriod default 40s


def _ready_condition(node: dict):
    for c in (node.get("status") or {}).get("conditions") or []:
        if c.get("type") == "Ready":
            return c
    return None


class NodeLifecycleController(Controller):
    """Sync per node: reconcile health taints; evict intolerant pods on
    NoExecute-tainted nodes. A monitor thread re-enqueues all nodes every
    ``monitor_period`` so staleness is noticed without events."""

    name = "nodelifecycle"

    def __init__(self, client, grace_period: float = DEFAULT_GRACE,
                 monitor_period: float = 5.0):
        super().__init__(client)
        self.grace_period = grace_period
        self.monitor_period = monitor_period
        self._monitor: threading.Thread | None = None

    def register(self, factory: InformerFactory) -> None:
        self.lease_informer = factory.informer("leases", None)
        self.node_informer = factory.informer("nodes", None)
        self.node_informer.add_event_handler(self.handler())
        self.pod_informer = factory.informer("pods", None)

    def start(self):
        super().start()
        self._monitor = threading.Thread(target=self._monitor_loop, daemon=True)
        self._monitor.start()
        return self

    def _monitor_loop(self):
        while not self._stop.wait(self.monitor_period):
            for key in self.node_informer.store.keys():
                self.queue.add(key)

    # ---- monitorNodeHealth ----------------------------------------------

    def _lease_renew_time(self, node_name: str):
        """renewTime of the node's kube-node-lease Lease, if any — lease
        renewal counts as a heartbeat (monitorNodeHealth's probeTimestamp
        advances on lease updates; upstream kubelets renew every 10s while
        touching node STATUS only 5-minutely)."""
        inf = getattr(self, "lease_informer", None)
        if inf is None:
            return None
        lease = inf.store.get(f"kube-node-lease/{node_name}")
        if lease is None:
            return None
        rt = (lease.get("spec") or {}).get("renewTime")
        try:
            return float(rt)
        except (TypeError, ValueError):
            return None

    def _wanted_taint(self, node: dict) -> str | None:
        cond = _ready_condition(node)
        if cond is None:
            return None  # no kubelet heartbeat model yet — leave untouched
        if cond.get("status") == "False":
            return TAINT_NOT_READY
        hb = cond.get("lastHeartbeatTime")
        renew = self._lease_renew_time(
            (node.get("metadata") or {}).get("name", ""))
        candidates = [renew]
        if hb is not None:
            candidates.append(float(hb))
        latest = max([t for t in candidates if t is not None],
                     default=None)
        if latest is not None and time.time() - latest > self.grace_period:
            return TAINT_UNREACHABLE
        if cond.get("status") == "Unknown" and renew is None:
            return TAINT_UNREACHABLE
        return None

    def sync(self, key: str) -> None:
        _, name = split_key(key)
        node = self.node_informer.store.get(key) or self.node_informer.store.get(name)
        if node is None:
            return
        wanted = self._wanted_taint(node)
        taints = [t for t in (node.get("spec") or {}).get("taints") or []]
        ours = [t for t in taints
                if t.get("key") in (TAINT_NOT_READY, TAINT_UNREACHABLE)
                and t.get("effect") == "NoExecute"]
        rest = [t for t in taints if t not in ours]
        added_ts = None
        if wanted:
            # Carry the existing timestamp if the same taint is already
            # present; otherwise this sync IS the add — the informer copy is
            # stale on this very sync, so the eviction check below must use
            # this value, not whatever the node object says.
            added_ts = (float(ours[0].get("timeAdded", time.time()))
                        if ours and ours[0].get("key") == wanted
                        else time.time())
        new_taints = rest + ([{"key": wanted, "effect": "NoExecute",
                               "timeAdded": added_ts}] if wanted else [])
        if new_taints != taints:
            obj = {**node, "spec": {**(node.get("spec") or {}), "taints": new_taints}}
            try:
                self.client.nodes().update(obj)
            except ApiError as e:
                if e.code not in (404, 409):
                    raise
        if wanted:
            self._evict_intolerant(node, wanted, added_ts)

    # ---- NoExecute taint eviction ---------------------------------------

    def _evict_intolerant(self, node: dict, taint_key: str,
                          added: float) -> None:
        node_name = (node.get("metadata") or {}).get("name", "")
        taint_obj = Taint(key=taint_key, effect="NoExecute")
        for p in self.pod_informer.store.list():
            if (p.get("spec") or {}).get("nodeName") != node_name:
                continue
            if (p.get("status") or {}).get("phase") in ("Succeeded", "Failed"):
                continue
            pod = Pod.from_dict(p)
            matching = [t for t in pod.spec.tolerations if t.tolerates(taint_obj)]
            if matching:
                secs = [t.toleration_seconds for t in matching]
                if any(s is None for s in secs):
                    continue  # tolerates forever
                if time.time() - added < min(s for s in secs if s is not None):
                    continue  # still within tolerationSeconds
            try:
                self.client.pods(pod.metadata.namespace).evict(pod.metadata.name)
            except ApiError as e:
                if e.code != 404:
                    raise
