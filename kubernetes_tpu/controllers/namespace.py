"""Namespace lifecycle controller — purge a deleted namespace's contents.

Reference: ``pkg/controller/namespace/namespace_controller.go`` +
``deletion/namespaced_resources_deleter.go``: upstream holds the Namespace
in Terminating behind a finalizer while group-walking every namespaced
resource and deleting the contents. Our store deletes objects immediately,
so the analog runs the same group-walk as a reaction to the Namespace's
DELETED event (content left behind would otherwise be invisible garbage —
the GC only chases ownerReferences). Built on the base workqueue so a
failed purge retries with rate-limited backoff instead of hot-looping.
"""

from __future__ import annotations

from kubernetes_tpu.client.clientset import ApiError
from kubernetes_tpu.client.informer import InformerFactory
from kubernetes_tpu.controllers.base import Controller
from kubernetes_tpu.store.apiserver import ALL_RESOURCES


class NamespaceController(Controller):
    name = "namespace"
    workers = 1

    def register(self, factory: InformerFactory) -> None:
        self.ns_informer = factory.informer("namespaces", None)

        def on_event(type_, obj, old):
            if type_ == "DELETED":
                self.queue.add((obj.get("metadata") or {}).get("name", ""))
        self.ns_informer.add_event_handler(on_event)

    def sync(self, key: str) -> None:
        # Keys are only enqueued on DELETED; if the namespace reappeared
        # (recreated with the same name) leave its fresh contents alone.
        if self.ns_informer.store.get(key) is not None:
            return
        self.purge(key)

    def purge(self, ns: str) -> None:
        """Delete every namespaced object in ``ns`` (the deleter's
        deleteAllContent group-walk)."""
        for plural, (kind, namespaced) in ALL_RESOURCES.items():
            if not namespaced or plural == "namespaces":
                continue
            handle = self.client.resource(plural, ns)
            try:
                items = handle.list()
            except ApiError:
                continue
            for obj in items:
                md = obj.get("metadata") or {}
                if md.get("namespace", "") != ns:
                    continue
                try:
                    handle.delete(md.get("name", ""))
                except ApiError as e:
                    if e.code != 404:
                        raise
