"""Certificates controller — CSR approval plumbing + the signing controller.

Reference: ``pkg/controller/certificates/`` (``signer/signer.go``: watch
CertificateSigningRequests, sign the ones carrying an Approved condition
with the cluster CA, write status.certificate; ``approver/`` auto-approves
self-node client certs — kept manual here, like kubectl certificate
approve). Real X.509: the controller holds a self-signed cluster CA and
issues certificates honoring the CSR's subject and requested usages.
"""

from __future__ import annotations

import base64
import datetime

from kubernetes_tpu.utils.clock import rfc3339_now
from kubernetes_tpu.client.clientset import ApiError
from kubernetes_tpu.client.informer import InformerFactory
from kubernetes_tpu.controllers.base import Controller

SIGNER_KUBE_APISERVER_CLIENT = "kubernetes.io/kube-apiserver-client"

# ``cryptography`` is an optional dependency: every X.509 operation below
# imports it lazily, and components that can run without a signer (the
# controller manager, tests) consult this flag instead of crashing on
# construction. Skip-marked tests key off it too.
try:
    import cryptography  # noqa: F401
    HAVE_CRYPTOGRAPHY = True
except ImportError:  # pragma: no cover - depends on environment
    HAVE_CRYPTOGRAPHY = False

_USAGE_MAP = {  # CSR usages -> x509 KeyUsage flag names
    "digital signature": "digital_signature",
    "key encipherment": "key_encipherment",
}


def generate_ca(common_name: str = "ktpu-cluster-ca"):
    """-> (ca_cert, ca_key) — the cluster CA the signer issues from."""
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes
    from cryptography.hazmat.primitives.asymmetric import ec
    from cryptography.x509.oid import NameOID
    key = ec.generate_private_key(ec.SECP256R1())
    name = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, common_name)])
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (x509.CertificateBuilder()
            .subject_name(name).issuer_name(name)
            .public_key(key.public_key())
            .serial_number(x509.random_serial_number())
            .not_valid_before(now - datetime.timedelta(minutes=5))
            .not_valid_after(now + datetime.timedelta(days=3650))
            .add_extension(x509.BasicConstraints(ca=True, path_length=None),
                           critical=True)
            .sign(key, hashes.SHA256()))
    return cert, key


def make_csr_pem(common_name: str, organizations: tuple = ()) -> tuple:
    """Test/client helper: -> (csr_pem bytes, private key)."""
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import ec
    from cryptography.x509.oid import NameOID
    key = ec.generate_private_key(ec.SECP256R1())
    attrs = [x509.NameAttribute(NameOID.COMMON_NAME, common_name)]
    attrs += [x509.NameAttribute(NameOID.ORGANIZATION_NAME, o)
              for o in organizations]
    csr = (x509.CertificateSigningRequestBuilder()
           .subject_name(x509.Name(attrs))
           .sign(key, hashes.SHA256()))
    return csr.public_bytes(serialization.Encoding.PEM), key


def _is_approved(csr: dict) -> bool:
    for cond in (csr.get("status") or {}).get("conditions") or []:
        if cond.get("type") == "Approved" and cond.get("status", "True") \
                in ("True", True):
            return True
    return False


def _is_denied(csr: dict) -> bool:
    return any(c.get("type") == "Denied"
               for c in (csr.get("status") or {}).get("conditions") or [])


def _has_failed(csr: dict) -> bool:
    return any(c.get("type") == "Failed"
               for c in (csr.get("status") or {}).get("conditions") or [])


class CSRSigningController(Controller):
    """Sign approved CSRs with the cluster CA (signer/signer.go)."""

    name = "csrsigning"
    workers = 1

    def __init__(self, client, ca=None, ttl_days: int = 365):
        super().__init__(client)
        self.ca_cert, self.ca_key = ca if ca is not None else generate_ca()
        self.ttl_days = ttl_days

    def ca_pem(self) -> bytes:
        from cryptography.hazmat.primitives import serialization
        return self.ca_cert.public_bytes(serialization.Encoding.PEM)

    def register(self, factory: InformerFactory) -> None:
        self.csr_informer = factory.informer("certificatesigningrequests",
                                             None)
        self.csr_informer.add_event_handler(
            lambda t, obj, old: self.enqueue(obj))

    def sync(self, key: str) -> None:
        res = self.client.resource("certificatesigningrequests", None)
        try:
            csr = res.get(key)
        except ApiError as e:
            if e.code == 404:
                return
            raise
        status = csr.get("status") or {}
        if status.get("certificate") or _is_denied(csr) \
                or _has_failed(csr) or not _is_approved(csr):
            # a recorded Failed condition is terminal: retrying an
            # unsignable request would hot-loop (each status write
            # re-enqueues via the watch) while growing conditions forever
            return
        spec = csr.get("spec") or {}
        if spec.get("signerName", SIGNER_KUBE_APISERVER_CLIENT) \
                != SIGNER_KUBE_APISERVER_CLIENT:
            return  # another signer's jurisdiction
        try:
            pem = base64.b64decode(spec.get("request", ""))
            cert_pem = self._sign(pem, spec.get("usages") or [])
        except Exception as e:
            status.setdefault("conditions", []).append(
                {"type": "Failed", "status": "True",
                 "reason": "SigningError", "message": str(e)})
            csr["status"] = status
            self._write_status(res, csr)
            return
        status["certificate"] = base64.b64encode(cert_pem).decode()
        csr["status"] = status
        self._write_status(res, csr)

    def _write_status(self, res, csr) -> None:
        try:
            res.update_status(csr)
        except ApiError as e:
            if e.code not in (404, 409):  # 409: raced; watch re-enqueues
                raise

    def _sign(self, csr_pem: bytes, usages: list) -> bytes:
        from cryptography import x509
        from cryptography.hazmat.primitives import hashes, serialization
        req = x509.load_pem_x509_csr(csr_pem)
        if not req.is_signature_valid:
            raise ValueError("CSR signature invalid")
        now = datetime.datetime.now(datetime.timezone.utc)
        ku = {name: False for name in (
            "digital_signature", "content_commitment", "key_encipherment",
            "data_encipherment", "key_agreement", "key_cert_sign",
            "crl_sign", "encipher_only", "decipher_only")}
        for u in usages or ["digital signature", "key encipherment"]:
            flag = _USAGE_MAP.get(str(u).lower())
            if flag:
                ku[flag] = True
        if not any(ku.values()):
            ku["digital_signature"] = True
        cert = (x509.CertificateBuilder()
                .subject_name(req.subject)
                .issuer_name(self.ca_cert.subject)
                .public_key(req.public_key())
                .serial_number(x509.random_serial_number())
                .not_valid_before(now - datetime.timedelta(minutes=5))
                .not_valid_after(now + datetime.timedelta(days=self.ttl_days))
                .add_extension(x509.BasicConstraints(ca=False,
                                                     path_length=None),
                               critical=True)
                .add_extension(x509.KeyUsage(**ku), critical=True)
                .add_extension(x509.ExtendedKeyUsage(
                    [x509.oid.ExtendedKeyUsageOID.CLIENT_AUTH]),
                    critical=False)
                .sign(self.ca_key, hashes.SHA256()))
        return cert.public_bytes(serialization.Encoding.PEM)


def approve_csr(client, name: str, message: str = "approved") -> dict:
    """kubectl certificate approve analog: append the Approved condition."""
    res = client.resource("certificatesigningrequests", None)
    csr = res.get(name)
    status = csr.setdefault("status", {})
    conds = status.setdefault("conditions", [])
    if not _is_approved(csr):
        conds.append({"type": "Approved", "status": "True",
                      "reason": "ManualApproval", "message": message,
                      "lastUpdateTime": rfc3339_now()})
    return res.update_status(csr)


def deny_csr(client, name: str, message: str = "denied") -> dict:
    res = client.resource("certificatesigningrequests", None)
    csr = res.get(name)
    status = csr.setdefault("status", {})
    if not _is_denied(csr):  # idempotent, like approve_csr
        status.setdefault("conditions", []).append(
            {"type": "Denied", "status": "True", "reason": "ManualDenial",
             "message": message, "lastUpdateTime": rfc3339_now()})
    return res.update_status(csr)
