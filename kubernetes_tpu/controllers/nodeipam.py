"""Node IPAM controller — pod CIDR allocation per node.

Reference: ``pkg/controller/nodeipam/node_ipam_controller.go`` with the
RangeAllocator (``ipam/range_allocator.go``): the cluster CIDR (e.g.
``10.244.0.0/16``) is carved into fixed-size per-node subnets
(``--node-cidr-mask-size``, default /24); every node without
``spec.podCIDR`` gets the next free subnet, releases happen on node
delete, and CIDRs already present on nodes (e.g. after a controller
restart) are re-reserved from the informer cache before any allocation.
"""

from __future__ import annotations

import ipaddress
import threading

from kubernetes_tpu.client.clientset import ApiError
from kubernetes_tpu.client.informer import InformerFactory
from kubernetes_tpu.controllers.base import Controller


class CidrSet:
    """The RangeAllocator's cidrset: index-addressed fixed-size subnets of
    the cluster CIDR (``ipam/cidrset/cidr_set.go``)."""

    def __init__(self, cluster_cidr: str, node_mask_size: int):
        self.net = ipaddress.ip_network(cluster_cidr)
        if node_mask_size < self.net.prefixlen:
            raise ValueError("node mask must be narrower than the cluster "
                             "CIDR")
        self.node_mask_size = node_mask_size
        self.max = 1 << (node_mask_size - self.net.prefixlen)
        self._used: set[int] = set()
        self._next = 0
        self._lock = threading.Lock()

    def cidr_at(self, index: int) -> str:
        base = int(self.net.network_address) \
            + (index << (self.net.max_prefixlen - self.node_mask_size))
        return f"{ipaddress.ip_address(base)}/{self.node_mask_size}"

    def index_of(self, cidr: str) -> int:
        net = ipaddress.ip_network(cidr)
        return (int(net.network_address) - int(self.net.network_address)) \
            >> (self.net.max_prefixlen - self.node_mask_size)

    def occupy(self, cidr: str) -> None:
        try:
            i = self.index_of(cidr)
        except ValueError:
            return
        if 0 <= i < self.max:
            with self._lock:
                self._used.add(i)

    def allocate(self) -> str:
        """Next free subnet (round-robin from the last allocation, like the
        upstream cidrset's nextCandidate scan)."""
        with self._lock:
            for off in range(self.max):
                i = (self._next + off) % self.max
                if i not in self._used:
                    self._used.add(i)
                    self._next = (i + 1) % self.max
                    return self.cidr_at(i)
        raise RuntimeError("cluster CIDR exhausted")

    def release(self, cidr: str) -> None:
        try:
            i = self.index_of(cidr)
        except ValueError:
            return
        with self._lock:
            self._used.discard(i)


class NodeIpamController(Controller):
    name = "nodeipam"
    workers = 1

    def __init__(self, client, cluster_cidr: str = "10.244.0.0/16",
                 node_mask_size: int = 24):
        super().__init__(client)
        self.cidrs = CidrSet(cluster_cidr, node_mask_size)
        self._assigned: dict[str, str] = {}  # node name -> cidr

    def register(self, factory: InformerFactory) -> None:
        self.node_informer = factory.informer("nodes", None)
        # Restart safety, both wiring orders: in the normal flow (register
        # before factory.start_all) the informer replays every existing
        # node as an ADDED event during cache sync, and _on_node occupies
        # its podCIDR before any worker allocates. If this controller is
        # ever registered against an ALREADY-synced shared informer (no
        # replay), the store scan below provides the same guarantee.
        for n in self.node_informer.store.list():
            self._reserve_existing(n)
        self.node_informer.add_event_handler(self._on_node)

    def _reserve_existing(self, node: dict) -> None:
        cidr = (node.get("spec") or {}).get("podCIDR", "")
        if cidr:
            self.cidrs.occupy(cidr)
            self._assigned[(node.get("metadata") or {})
                           .get("name", "")] = cidr

    def _on_node(self, type_, obj, old) -> None:
        name = (obj.get("metadata") or {}).get("name", "")
        if type_ == "DELETED":
            cidr = self._assigned.pop(name, None) \
                or (obj.get("spec") or {}).get("podCIDR", "")
            if cidr:
                self.cidrs.release(cidr)
            return
        self._reserve_existing(obj)
        self.enqueue(obj)

    def sync(self, key: str) -> None:
        res = self.client.resource("nodes", None)
        try:
            node = res.get(key)
        except ApiError as e:
            if e.code == 404:
                return
            raise
        spec = node.setdefault("spec", {})
        if spec.get("podCIDR"):
            return
        cidr = self.cidrs.allocate()
        spec["podCIDR"] = cidr
        spec["podCIDRs"] = [cidr]
        try:
            res.update(node)
            self._assigned[key] = cidr
        except ApiError as e:
            # lost the race or the node vanished: return the subnet
            self.cidrs.release(cidr)
            if e.code not in (404, 409):
                raise
