"""ClusterRole aggregation controller.

Reference: ``pkg/controller/clusterroleaggregation/clusterroleaggregation_
controller.go``: a ClusterRole carrying ``aggregationRule.
clusterRoleSelectors`` gets its ``rules`` REPLACED by the union of rules
from every ClusterRole matching any selector (this is how admin/edit/view
absorb CRD permission grants labeled ``rbac.authorization.k8s.io/
aggregate-to-admin`` etc.).
"""

from __future__ import annotations

import json

from kubernetes_tpu.api.policy import _matches
from kubernetes_tpu.client.clientset import ApiError
from kubernetes_tpu.client.informer import InformerFactory
from kubernetes_tpu.controllers.base import Controller


class ClusterRoleAggregationController(Controller):
    name = "clusterroleaggregation"
    workers = 1

    def register(self, factory: InformerFactory) -> None:
        self.role_informer = factory.informer("clusterroles", None)
        self.role_informer.add_event_handler(self._on_role)

    def _on_role(self, type_, obj, old) -> None:
        # any labeled-role change can feed any aggregating role: enqueue
        # every aggregator (upstream enqueues all on each change too)
        for role in self.role_informer.store.list():
            if (role.get("aggregationRule") or {}).get("clusterRoleSelectors"):
                self.enqueue(role)

    def sync(self, key: str) -> None:
        res = self.client.resource("clusterroles", None)
        try:
            role = res.get(key)
        except ApiError as e:
            if e.code == 404:
                return
            raise
        selectors = (role.get("aggregationRule") or {}).get(
            "clusterRoleSelectors") or []
        if not selectors:
            return
        rules: list[dict] = []
        seen: set[str] = set()
        for other in sorted(self.role_informer.store.list(),
                            key=lambda r: (r.get("metadata") or {})
                            .get("name", "")):
            omd = other.get("metadata") or {}
            if omd.get("name") == key:
                continue
            labels = omd.get("labels") or {}
            if not any(_matches(sel, labels) for sel in selectors):
                continue
            for rule in other.get("rules") or []:
                fp = json.dumps(rule, sort_keys=True)
                if fp not in seen:
                    seen.add(fp)
                    rules.append(rule)
        if role.get("rules") == rules:
            return
        role["rules"] = rules
        try:
            res.update(role)
        except ApiError as e:
            if e.code not in (404, 409):
                raise
