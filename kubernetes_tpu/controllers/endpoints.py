"""Endpoints controller — Service selector -> ready pod addresses.

Reference: ``pkg/controller/endpoint/endpoints_controller.go``
(``syncService``: list pods matching .spec.selector, split into
ready/notReady addresses, write the Endpoints object the proxy consumes).
The EndpointSlice shape upstream adds is a sharded encoding of the same
data; one Endpoints object per service carries it here.
"""

from __future__ import annotations

from kubernetes_tpu.api.types import PodStatus
from kubernetes_tpu.client.clientset import ApiError
from kubernetes_tpu.client.informer import InformerFactory
from kubernetes_tpu.controllers.base import Controller, split_key


def _resolve_target_port(sp: dict, pod: dict):
    """targetPort may be a name — resolve it against THIS pod's container
    ports (endpoints_controller FindPort is per-pod: during a rolling update
    the same port name can map to different containerPorts on old and new
    pods, and each address must advertise its own). None = the pod does not
    expose the named port, so it is skipped for this service port."""
    tp = sp.get("targetPort", sp.get("port", 0))
    if isinstance(tp, int):
        return tp
    if isinstance(tp, str) and tp.isdigit():
        return int(tp)
    for c in (pod.get("spec") or {}).get("containers") or []:
        for port in c.get("ports") or []:
            if port.get("name") == tp and port.get("containerPort"):
                return int(port["containerPort"])
    return None


class EndpointsController(Controller):
    name = "endpoints"

    def register(self, factory: InformerFactory) -> None:
        self.svc_informer = factory.informer("services", None)
        self.svc_informer.add_event_handler(self.handler())
        self.pod_informer = factory.informer("pods", None)
        self.pod_informer.add_event_handler(self.handler(self._enqueue_services))

    def _enqueue_services(self, pod: dict) -> None:
        labels = (pod.get("metadata") or {}).get("labels") or {}
        ns = (pod.get("metadata") or {}).get("namespace", "")
        for svc in self.svc_informer.store.list():
            smd = svc.get("metadata") or {}
            if smd.get("namespace", "") != ns:
                continue
            sel = (svc.get("spec") or {}).get("selector") or {}
            if sel and all(labels.get(k) == v for k, v in sel.items()):
                self.enqueue(svc)

    def sync(self, key: str) -> None:
        ns, name = split_key(key)
        svc = self.svc_informer.store.get(key)
        if svc is None:
            # service deleted -> delete its endpoints
            try:
                self.client.endpoints(ns).delete(name)
            except ApiError as e:
                if e.code != 404:
                    raise
            return
        sel = (svc.get("spec") or {}).get("selector") or {}
        if not sel:
            return  # selectorless services manage endpoints manually
        svc_ports = (svc.get("spec") or {}).get("ports") or []
        # Group addresses by their RESOLVED port set (RepackSubsets): pods
        # whose named targetPorts resolve differently land in separate
        # subsets, each advertising its own containerPort.
        groups: dict[tuple, dict] = {}
        for p in self.pod_informer.store.list():
            md = p.get("metadata") or {}
            if md.get("namespace", "") != ns:
                continue
            labels = md.get("labels") or {}
            if not all(labels.get(k) == v for k, v in sel.items()):
                continue
            st = PodStatus.from_dict(p.get("status"))
            if st.phase in ("Succeeded", "Failed") or not st.pod_ip:
                continue
            ports = []
            for sp in svc_ports:
                port = _resolve_target_port(sp, p)
                if port is not None:
                    ports.append({"name": sp.get("name", ""), "port": port,
                                  "protocol": sp.get("protocol", "TCP")})
            if svc_ports and not ports:
                continue  # pod exposes none of the service's named ports
            gkey = tuple(sorted((pp["name"], pp["port"], pp["protocol"])
                                for pp in ports))
            g = groups.setdefault(gkey, {"ports": ports, "ready": [],
                                         "not_ready": []})
            addr = {"ip": st.pod_ip,
                    "nodeName": (p.get("spec") or {}).get("nodeName", ""),
                    "targetRef": {"kind": "Pod", "name": md.get("name", ""),
                                  "namespace": ns, "uid": md.get("uid", "")}}
            g["ready" if st.is_ready() else "not_ready"].append(addr)
        subsets = []
        for gkey in sorted(groups):
            g = groups[gkey]
            subset: dict = {"ports": g["ports"]}
            if g["ready"]:
                subset["addresses"] = sorted(g["ready"], key=lambda a: a["ip"])
            if g["not_ready"]:
                subset["notReadyAddresses"] = sorted(g["not_ready"],
                                                     key=lambda a: a["ip"])
            subsets.append(subset)
        ep_api = self.client.endpoints(ns)
        desired = {"apiVersion": "v1", "kind": "Endpoints",
                   "metadata": {"name": name, "namespace": ns,
                                "labels": dict((svc.get("metadata") or {})
                                               .get("labels") or {})},
                   "subsets": subsets}
        try:
            current = ep_api.get(name)
        except ApiError as e:
            if e.code != 404:
                raise
            ep_api.create(desired)
            return
        if current.get("subsets") != subsets:
            desired["metadata"]["resourceVersion"] = \
                (current.get("metadata") or {}).get("resourceVersion", "")
            ep_api.update(desired)  # 409 -> requeue with backoff
