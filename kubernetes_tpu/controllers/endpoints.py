"""Endpoints controller — Service selector -> ready pod addresses.

Reference: ``pkg/controller/endpoint/endpoints_controller.go``
(``syncService``: list pods matching .spec.selector, split into
ready/notReady addresses, write the Endpoints object the proxy consumes).
The EndpointSlice shape upstream adds is a sharded encoding of the same
data; one Endpoints object per service carries it here.
"""

from __future__ import annotations

from kubernetes_tpu.api.types import PodStatus
from kubernetes_tpu.client.clientset import ApiError
from kubernetes_tpu.client.informer import InformerFactory
from kubernetes_tpu.controllers.base import Controller, split_key


def _resolve_target_port(sp: dict, matched_pods: list[dict]) -> int:
    """targetPort may be a name — resolve it against the matched pods'
    container ports (endpoints_controller FindPort); fall back to the
    service port rather than failing the whole sync."""
    tp = sp.get("targetPort", sp.get("port", 0))
    if isinstance(tp, int):
        return tp
    if isinstance(tp, str) and tp.isdigit():
        return int(tp)
    for p in matched_pods:
        for c in (p.get("spec") or {}).get("containers") or []:
            for port in c.get("ports") or []:
                if port.get("name") == tp and port.get("containerPort"):
                    return int(port["containerPort"])
    return int(sp.get("port", 0))


class EndpointsController(Controller):
    name = "endpoints"

    def register(self, factory: InformerFactory) -> None:
        self.svc_informer = factory.informer("services", None)
        self.svc_informer.add_event_handler(self.handler())
        self.pod_informer = factory.informer("pods", None)
        self.pod_informer.add_event_handler(self.handler(self._enqueue_services))

    def _enqueue_services(self, pod: dict) -> None:
        labels = (pod.get("metadata") or {}).get("labels") or {}
        ns = (pod.get("metadata") or {}).get("namespace", "")
        for svc in self.svc_informer.store.list():
            smd = svc.get("metadata") or {}
            if smd.get("namespace", "") != ns:
                continue
            sel = (svc.get("spec") or {}).get("selector") or {}
            if sel and all(labels.get(k) == v for k, v in sel.items()):
                self.enqueue(svc)

    def sync(self, key: str) -> None:
        ns, name = split_key(key)
        svc = self.svc_informer.store.get(key)
        if svc is None:
            # service deleted -> delete its endpoints
            try:
                self.client.endpoints(ns).delete(name)
            except ApiError as e:
                if e.code != 404:
                    raise
            return
        sel = (svc.get("spec") or {}).get("selector") or {}
        if not sel:
            return  # selectorless services manage endpoints manually
        ready, not_ready, matched = [], [], []
        for p in self.pod_informer.store.list():
            md = p.get("metadata") or {}
            if md.get("namespace", "") != ns:
                continue
            labels = md.get("labels") or {}
            if not all(labels.get(k) == v for k, v in sel.items()):
                continue
            st = PodStatus.from_dict(p.get("status"))
            if st.phase in ("Succeeded", "Failed") or not st.pod_ip:
                continue
            matched.append(p)
            addr = {"ip": st.pod_ip,
                    "nodeName": (p.get("spec") or {}).get("nodeName", ""),
                    "targetRef": {"kind": "Pod", "name": md.get("name", ""),
                                  "namespace": ns, "uid": md.get("uid", "")}}
            (ready if st.is_ready() else not_ready).append(addr)
        ports = [{"name": sp.get("name", ""),
                  "port": _resolve_target_port(sp, matched),
                  "protocol": sp.get("protocol", "TCP")}
                 for sp in (svc.get("spec") or {}).get("ports") or []]
        subsets = []
        if ready or not_ready:
            subset: dict = {"ports": ports}
            if ready:
                subset["addresses"] = sorted(ready, key=lambda a: a["ip"])
            if not_ready:
                subset["notReadyAddresses"] = sorted(not_ready, key=lambda a: a["ip"])
            subsets = [subset]
        ep_api = self.client.endpoints(ns)
        desired = {"apiVersion": "v1", "kind": "Endpoints",
                   "metadata": {"name": name, "namespace": ns,
                                "labels": dict((svc.get("metadata") or {})
                                               .get("labels") or {})},
                   "subsets": subsets}
        try:
            current = ep_api.get(name)
        except ApiError as e:
            if e.code != 404:
                raise
            ep_api.create(desired)
            return
        if current.get("subsets") != subsets:
            desired["metadata"]["resourceVersion"] = \
                (current.get("metadata") or {}).get("resourceVersion", "")
            ep_api.update(desired)  # 409 -> requeue with backoff
