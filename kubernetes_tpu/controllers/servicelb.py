"""Service LoadBalancer controller — cloud LB provisioning, played local.

Reference: ``staging/src/k8s.io/cloud-provider/controllers/service``
(``EnsureLoadBalancer``/``EnsureLoadBalancerDeleted`` against the cloud
API): Services of type LoadBalancer get an external ingress IP in
``status.loadBalancer.ingress`` once the cloud provisions one; switching
the type away releases it. The "cloud" here is an in-process IP pool,
the same stance as pvbinder playing the external provisioner.
"""

from __future__ import annotations

import ipaddress
import threading

from kubernetes_tpu.client.clientset import ApiError
from kubernetes_tpu.client.informer import InformerFactory
from kubernetes_tpu.controllers.base import Controller, split_key


class _LbPool:
    """The cloud's LB address pool."""

    def __init__(self, cidr: str = "203.0.113.0/24"):
        self.net = ipaddress.ip_network(cidr)
        self._used: dict[str, str] = {}  # service key -> ip
        self._lock = threading.Lock()

    def ensure(self, key: str) -> str:
        with self._lock:
            ip = self._used.get(key)
            if ip:
                return ip
            taken = set(self._used.values())
            for host in self.net.hosts():
                if str(host) not in taken:
                    self._used[key] = str(host)
                    return str(host)
        raise RuntimeError("LB pool exhausted")

    def release(self, key: str) -> None:
        with self._lock:
            self._used.pop(key, None)


class ServiceLBController(Controller):
    name = "service-lb"
    workers = 1

    def __init__(self, client, pool: _LbPool | None = None):
        super().__init__(client)
        self.pool = pool or _LbPool()

    def register(self, factory: InformerFactory) -> None:
        self.svc_informer = factory.informer("services", None)
        self.svc_informer.add_event_handler(self.handler())

    def sync(self, key: str) -> None:
        import copy
        ns, name = split_key(key)
        cached = self.svc_informer.store.get(key)
        res = self.client.resource("services", ns)
        if cached is None:
            self.pool.release(key)
            return
        # never mutate the informer's cached object: a failed status write
        # would poison the cache and make every retry early-return
        svc = copy.deepcopy(cached)
        spec = svc.get("spec") or {}
        status = svc.setdefault("status", {})
        lb = status.setdefault("loadBalancer", {})
        if spec.get("type") != "LoadBalancer":
            # type changed away: the cloud LB is torn down
            if lb.get("ingress"):
                self.pool.release(key)
                lb.pop("ingress", None)
                self._update_status(res, svc)
            return
        ip = self.pool.ensure(key)
        if lb.get("ingress") == [{"ip": ip}]:
            return
        lb["ingress"] = [{"ip": ip}]
        self._update_status(res, svc)

    @staticmethod
    def _update_status(res, svc: dict) -> None:
        try:
            res.update_status(svc)
        except ApiError as e:
            if e.code not in (404, 409):
                raise
