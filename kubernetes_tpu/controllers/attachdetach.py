"""Attach/detach controller — VolumeAttachment reconciliation.

Reference: ``pkg/controller/volume/attachdetach/attach_detach_controller.go``
(desired-state-of-world from pods' volumes vs actual-state-of-world from
VolumeAttachment objects; the reconciler attaches what pods on a node need
and detaches what nothing needs) plus the storage.k8s.io/v1
``VolumeAttachment`` API (``csi-attacher`` sets ``status.attached``; played
in-process here, as pvbinder plays the external provisioner).

Desired: every (node, PV) pair where a pod bound to the node mounts a PVC
whose bound PV is attachable (CSI-backed). Reconcile:
- missing pair -> create VolumeAttachment {attacher, nodeName, source}
  and mark ``status.attached`` true;
- orphaned VolumeAttachment (no pod needs it) -> delete;
- node.status.volumesAttached mirrors the attached set (kubelets and the
  scheduler's NodeVolumeLimits read it upstream).
"""

from __future__ import annotations

from kubernetes_tpu.client.clientset import ApiError
from kubernetes_tpu.client.informer import InformerFactory
from kubernetes_tpu.controllers.base import Controller

RECONCILE_KEY = "_reconcile"


def attachment_name(pv_name: str, node_name: str) -> str:
    import hashlib
    h = hashlib.sha256(f"{pv_name}/{node_name}".encode()).hexdigest()[:12]
    return f"csi-{h}"


class AttachDetachController(Controller):
    name = "attachdetach"
    workers = 1

    def register(self, factory: InformerFactory) -> None:
        self.pod_informer = factory.informer("pods", None)
        self.pvc_informer = factory.informer("persistentvolumeclaims", None)
        self.pv_informer = factory.informer("persistentvolumes", None)
        self.va_informer = factory.informer("volumeattachments", None)
        self.node_informer = factory.informer("nodes", None)
        for inf in (self.pod_informer, self.pvc_informer, self.pv_informer,
                    self.va_informer, self.node_informer):
            inf.add_event_handler(
                lambda *_a: self.enqueue_key(RECONCILE_KEY))

    def enqueue_key(self, key: str) -> None:
        self.queue.add(key)

    # ---- desired / actual state ------------------------------------------

    def _attachable_pv(self, pv: dict) -> bool:
        spec = pv.get("spec") or {}
        return bool(spec.get("csi"))  # local/hostPath volumes never attach

    def _desired(self) -> dict[tuple[str, str], dict]:
        """(pv_name, node_name) -> pv object for every needed attachment."""
        pvc_to_pv: dict[tuple, dict] = {}
        pvs = {((p.get("metadata") or {}).get("name", "")): p
               for p in self.pv_informer.store.list()}
        for pvc in self.pvc_informer.store.list():
            md = pvc.get("metadata") or {}
            vol = (pvc.get("spec") or {}).get("volumeName", "")
            if vol and vol in pvs:
                pvc_to_pv[(md.get("namespace", "default"),
                           md.get("name", ""))] = pvs[vol]
        out: dict[tuple[str, str], dict] = {}
        for pod in self.pod_informer.store.list():
            spec = pod.get("spec") or {}
            node = spec.get("nodeName", "")
            phase = (pod.get("status") or {}).get("phase", "")
            if not node or phase in ("Succeeded", "Failed"):
                continue
            ns = (pod.get("metadata") or {}).get("namespace", "default")
            for v in spec.get("volumes") or []:
                claim = (v.get("persistentVolumeClaim") or {}).get(
                    "claimName", "")
                if not claim:
                    continue
                pv = pvc_to_pv.get((ns, claim))
                if pv is not None and self._attachable_pv(pv):
                    name = (pv.get("metadata") or {}).get("name", "")
                    out[(name, node)] = pv
        return out

    # ---- reconcile -------------------------------------------------------

    def sync(self, key: str) -> None:
        desired = self._desired()
        vas = self.client.resource("volumeattachments", None)
        actual: dict[tuple[str, str], dict] = {}
        for va in self.va_informer.store.list():
            spec = va.get("spec") or {}
            pv_name = ((spec.get("source") or {})
                       .get("persistentVolumeName", ""))
            actual[(pv_name, spec.get("nodeName", ""))] = va

        for (pv_name, node), pv in desired.items():
            if (pv_name, node) in actual:
                continue
            driver = ((pv.get("spec") or {}).get("csi") or {}).get(
                "driver", "csi")
            try:
                created = vas.create({
                    "kind": "VolumeAttachment",
                    "metadata": {"name": attachment_name(pv_name, node)},
                    "spec": {"attacher": driver, "nodeName": node,
                             "source": {"persistentVolumeName": pv_name}}})
            except ApiError as e:
                if e.code != 409:
                    raise
                continue
            # play the external attacher: report attached
            created.setdefault("status", {})["attached"] = True
            try:
                vas.update_status(created)
            except ApiError as e:
                if e.code not in (404, 409):
                    raise

        for (pv_name, node), va in actual.items():
            if (pv_name, node) in desired:
                continue
            try:
                vas.delete((va.get("metadata") or {}).get("name", ""))
            except ApiError as e:
                if e.code != 404:
                    raise

        self._sync_node_status(desired)

    def _sync_node_status(self, desired: dict) -> None:
        """node.status.volumesAttached mirrors the attached set."""
        by_node: dict[str, list[str]] = {}
        for (pv_name, node) in desired:
            by_node.setdefault(node, []).append(pv_name)
        nodes = self.client.resource("nodes", None)
        for n in self.node_informer.store.list():
            name = (n.get("metadata") or {}).get("name", "")
            want = [{"name": f"kubernetes.io/csi/{pv}", "devicePath": ""}
                    for pv in sorted(by_node.get(name, []))]
            have = (n.get("status") or {}).get("volumesAttached") or []
            if have == want:
                continue
            try:
                node = nodes.get(name)
                node.setdefault("status", {})["volumesAttached"] = want
                nodes.update_status(node)
            except ApiError as e:
                if e.code not in (404, 409):
                    raise
