"""Deployment controller — declarative rollouts over ReplicaSets.

Reference: ``pkg/controller/deployment/deployment_controller.go``
(``syncDeployment``) + ``sync.go`` (``getNewReplicaSet`` keyed by
pod-template-hash) + ``rolling.go`` (``reconcileNewReplicaSet`` /
``reconcileOldReplicaSets`` honoring maxSurge/maxUnavailable).
"""

from __future__ import annotations

import hashlib
import json

from kubernetes_tpu.client.clientset import ApiError
from kubernetes_tpu.client.informer import InformerFactory
from kubernetes_tpu.controllers.base import (
    Controller,
    is_controlled_by,
    owner_reference,
    split_key,
)

HASH_LABEL = "pod-template-hash"


def template_hash(dep: dict) -> str:
    """Stable content hash of .spec.template (ComputeHash analog)."""
    tpl = (dep.get("spec") or {}).get("template") or {}
    blob = json.dumps(tpl, sort_keys=True).encode()
    return hashlib.sha1(blob).hexdigest()[:10]


def _resolve_bound(value, total: int, round_up: bool) -> int:
    """intstr percentage resolution (intstr.GetScaledValueFromIntOrPercent)."""
    if isinstance(value, str) and value.endswith("%"):
        frac = int(value[:-1]) / 100.0 * total
        return int(-(-frac // 1)) if round_up else int(frac)
    return int(value)


class DeploymentController(Controller):
    name = "deployment"

    def register(self, factory: InformerFactory) -> None:
        self.dep_informer = factory.informer("deployments", None)
        self.dep_informer.add_event_handler(self.handler())
        self.rs_informer = factory.informer("replicasets", None)
        self.rs_informer.add_event_handler(
            self.handler(lambda obj: self.enqueue_owner(obj, "Deployment")))

    # ---- syncDeployment --------------------------------------------------

    def _owned_rs(self, dep: dict) -> list[dict]:
        ns = (dep.get("metadata") or {}).get("namespace", "")
        return [rs for rs in self.rs_informer.store.list()
                if (rs.get("metadata") or {}).get("namespace", "") == ns
                and is_controlled_by(rs, dep)]

    def sync(self, key: str) -> None:
        ns, name = split_key(key)
        dep = self.dep_informer.store.get(key)
        if dep is None or (dep.get("metadata") or {}).get("deletionTimestamp"):
            return
        spec = dep.get("spec") or {}
        replicas = int(spec.get("replicas", 1))
        h = template_hash(dep)
        owned = self._owned_rs(dep)
        new_rs = next((rs for rs in owned
                       if ((rs.get("metadata") or {}).get("labels") or {})
                       .get(HASH_LABEL) == h), None)
        old_rses = [rs for rs in owned if rs is not new_rs]

        rs_api = self.client.resource("replicasets", ns)
        if new_rs is None:
            new_rs = rs_api.create(self._new_rs(dep, h, replicas=0))

        strategy = spec.get("strategy") or {}
        if strategy.get("type") == "Recreate":
            self._recreate(dep, new_rs, old_rses, replicas)
        else:
            self._rolling(dep, new_rs, old_rses, replicas, strategy)
        self._update_status(dep, [new_rs] + old_rses)

    def _new_rs(self, dep: dict, h: str, replicas: int) -> dict:
        tpl = json.loads(json.dumps((dep.get("spec") or {}).get("template") or {}))
        tpl.setdefault("metadata", {}).setdefault("labels", {})[HASH_LABEL] = h
        sel = json.loads(json.dumps((dep.get("spec") or {}).get("selector") or {}))
        sel.setdefault("matchLabels", {})[HASH_LABEL] = h
        md = dep.get("metadata") or {}
        return {
            "apiVersion": "apps/v1",
            "kind": "ReplicaSet",
            "metadata": {
                "name": f"{md.get('name', 'x')}-{h}",
                "namespace": md.get("namespace", "default"),
                "labels": {**(tpl.get("metadata", {}).get("labels") or {})},
                "ownerReferences": [owner_reference({**dep, "apiVersion": "apps/v1"},
                                                    "Deployment")],
            },
            "spec": {"replicas": replicas, "selector": sel, "template": tpl},
            "status": {},
        }

    def _scale_rs(self, rs: dict, replicas: int) -> dict:
        if int((rs.get("spec") or {}).get("replicas", 0)) == replicas:
            return rs
        obj = json.loads(json.dumps(rs))
        obj["spec"]["replicas"] = replicas
        ns = obj["metadata"].get("namespace")
        try:
            return self.client.resource("replicasets", ns).update(obj)
        except ApiError as e:
            if e.code == 409:
                raise  # requeue with backoff; informer will deliver fresh rv
            raise

    def _recreate(self, dep, new_rs, old_rses, replicas) -> None:
        # scale all old to 0; only when their pods are gone scale new up
        for rs in old_rses:
            self._scale_rs(rs, 0)
        if any(int((rs.get("status") or {}).get("replicas", 0)) > 0
               for rs in old_rses):
            raise RuntimeError("waiting for old replicas to terminate")  # requeue
        self._scale_rs(new_rs, replicas)

    def _rolling(self, dep, new_rs, old_rses, replicas, strategy) -> None:
        ru = strategy.get("rollingUpdate") or {}
        max_surge = _resolve_bound(ru.get("maxSurge", "25%"), replicas, round_up=True)
        max_unavail = _resolve_bound(ru.get("maxUnavailable", "25%"), replicas,
                                     round_up=False)
        if max_surge == 0 and max_unavail == 0:
            max_unavail = 1  # validation upstream forbids both-zero; be safe

        def spec_n(rs): return int((rs.get("spec") or {}).get("replicas", 0))
        def ready_n(rs): return int((rs.get("status") or {}).get("readyReplicas", 0))

        total = spec_n(new_rs) + sum(spec_n(rs) for rs in old_rses)
        # reconcileNewReplicaSet (rolling.go): above spec -> scale straight
        # down to spec (covers `ktpu scale` lowering replicas mid/post
        # rollout); below -> grow up to replicas + surge - total.
        if spec_n(new_rs) > replicas:
            new_rs = self._scale_rs(new_rs, replicas)
        else:
            grow = min(replicas - spec_n(new_rs), replicas + max_surge - total)
            if grow > 0:
                new_rs = self._scale_rs(new_rs, spec_n(new_rs) + grow)
        # reconcileOldReplicaSets: shrink old while staying above min-available
        ready_total = ready_n(new_rs) + sum(ready_n(rs) for rs in old_rses)
        can_remove = ready_total - (replicas - max_unavail)
        for rs in sorted(old_rses, key=spec_n, reverse=True):
            if can_remove <= 0:
                break
            cut = min(spec_n(rs), can_remove)
            if cut > 0:
                self._scale_rs(rs, spec_n(rs) - cut)
                can_remove -= cut
        # garbage-collect fully scaled-down, fully drained old RSes beyond
        # revisionHistoryLimit (simplified: always keep them at 0, like
        # upstream with default limit 10 — deletion left to GC/explicit)

    def _update_status(self, dep: dict, rses: list[dict]) -> None:
        def n(rs, f): return int((rs.get("status") or {}).get(f, 0))
        status = {
            "replicas": sum(n(rs, "replicas") for rs in rses),
            "readyReplicas": sum(n(rs, "readyReplicas") for rs in rses),
            "availableReplicas": sum(n(rs, "availableReplicas") for rs in rses),
            "updatedReplicas": n(rses[0], "replicas"),
            "observedGeneration": (dep.get("metadata") or {}).get("generation", 0),
        }
        if dep.get("status") != status:
            try:
                self.client.resource("deployments",
                                     dep["metadata"].get("namespace")) \
                    .update_status({**dep, "status": status})
            except ApiError as e:
                if e.code not in (404, 409):
                    raise
