"""EndpointSlice mirroring — custom Endpoints get mirrored slices.

Reference: ``pkg/controller/endpointslicemirroring``: Endpoints objects
maintained by USERS (no matching selector-driven controller — e.g. an
external database published as a Service without a selector) are mirrored
into EndpointSlices so slice-only consumers (kube-proxy's nftables
backend, topology-aware routing) see them. Endpoints managed by the
endpoints controller itself are skipped (the endpointslice controller
already covers those), via the ``endpointslice.kubernetes.io/skip-mirror``
label upstream's endpoints controller stamps.
"""

from __future__ import annotations

from kubernetes_tpu.client.clientset import ApiError
from kubernetes_tpu.client.informer import InformerFactory
from kubernetes_tpu.controllers.base import Controller, split_key

SKIP_MIRROR_LABEL = "endpointslice.kubernetes.io/skip-mirror"
MANAGED_BY = "endpointslicemirroring-controller.k8s.io"


class EndpointSliceMirroringController(Controller):
    name = "endpointslicemirroring"
    workers = 1

    def register(self, factory: InformerFactory) -> None:
        self.ep_informer = factory.informer("endpoints", None)
        self.ep_informer.add_event_handler(self.handler())
        # Service create/delete/selector changes flip mirror eligibility
        self.svc_informer = factory.informer("services", None)
        self.svc_informer.add_event_handler(self.handler())
        # an out-of-band slice deletion must heal: re-enqueue the owner
        self.slice_informer = factory.informer("endpointslices", None)
        self.slice_informer.add_event_handler(self._on_slice)

    def _on_slice(self, type_, obj, old) -> None:
        md = obj.get("metadata") or {}
        labels = md.get("labels") or {}
        if labels.get("endpointslice.kubernetes.io/managed-by") \
                == MANAGED_BY:
            ns = md.get("namespace", "default")
            self.queue.add(f"{ns}/{labels.get('kubernetes.io/service-name', '')}")

    def _should_mirror(self, ep: dict, key: str) -> bool:
        labels = (ep.get("metadata") or {}).get("labels") or {}
        if labels.get(SKIP_MIRROR_LABEL) in ("true", "True"):
            return False
        svc = self.svc_informer.store.get(key)
        if svc is None:
            return False  # no backing Service: nothing to mirror for
        # selector-driven services are the endpointslice controller's job
        return not (svc.get("spec") or {}).get("selector")

    def _desired_slices(self, ep: dict, ns: str, name: str) -> list[dict]:
        """One mirror slice PER SUBSET: a subset binds its addresses to its
        ports (that is what subsets express), so flattening would advertise
        addresses on ports they do not serve — the sibling endpointslice
        controller groups by port set the same way."""
        out = []
        for i, subset in enumerate(ep.get("subsets") or []):
            ports = [{"name": p.get("name", ""), "port": p.get("port"),
                      "protocol": p.get("protocol", "TCP")}
                     for p in subset.get("ports") or []]
            endpoints = (
                [{"addresses": [a.get("ip", "")],
                  "conditions": {"ready": True}}
                 for a in subset.get("addresses") or []]
                + [{"addresses": [a.get("ip", "")],
                    "conditions": {"ready": False}}
                   for a in subset.get("notReadyAddresses") or []])
            out.append({
                "kind": "EndpointSlice",
                "metadata": {
                    "name": f"{name}-mirror-{i}", "namespace": ns,
                    "labels": {"kubernetes.io/service-name": name,
                               "endpointslice.kubernetes.io/managed-by":
                               MANAGED_BY},
                },
                "addressType": "IPv4",
                "endpoints": endpoints,
                "ports": ports,
            })
        return out

    def sync(self, key: str) -> None:
        ns, name = split_key(key)
        slices = self.client.resource("endpointslices", ns)
        existing = [
            s for s in self.slice_informer.store.list()
            if (s.get("metadata") or {}).get("namespace", "") == ns
            and ((s.get("metadata") or {}).get("labels") or {})
            .get("kubernetes.io/service-name") == name
            and ((s.get("metadata") or {}).get("labels") or {})
            .get("endpointslice.kubernetes.io/managed-by") == MANAGED_BY]
        ep = self.ep_informer.store.get(key)
        desired = ([] if ep is None or not self._should_mirror(ep, key)
                   else self._desired_slices(ep, ns, name))
        by_name = {(s.get("metadata") or {}).get("name"): s
                   for s in existing}
        for d in desired:
            cur = by_name.pop(d["metadata"]["name"], None)
            if cur is None:
                try:
                    slices.create(d)
                except ApiError as e:
                    if e.code != 409:
                        raise
            elif (cur.get("endpoints") != d["endpoints"]
                  or cur.get("ports") != d["ports"]):
                # optimistic concurrency: carry the precondition rv
                d["metadata"]["resourceVersion"] = \
                    (cur.get("metadata") or {}).get("resourceVersion", "")
                try:
                    slices.update(d)
                except ApiError as e:
                    if e.code not in (404, 409):
                        raise
        for stale in by_name.values():
            try:
                slices.delete((stale.get("metadata") or {}).get("name", ""))
            except ApiError as e:
                if e.code != 404:
                    raise
