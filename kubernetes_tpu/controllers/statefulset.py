"""StatefulSet controller — ordered, identity-stable replicas.

Reference: ``pkg/controller/statefulset/stateful_set.go`` +
``stateful_set_control.go`` (``UpdateStatefulSet``: ordinal pods
``<name>-<i>``, OrderedReady semantics — create ordinal i only when i-1 is
Running+Ready, scale down from the top, also only one at a time).
"""

from __future__ import annotations

import json

from kubernetes_tpu.api.types import PodStatus
from kubernetes_tpu.client.clientset import ApiError
from kubernetes_tpu.client.informer import InformerFactory
from kubernetes_tpu.controllers.base import (
    Controller,
    is_controlled_by,
    owner_reference,
    split_key,
)


def _ordinal(pod_name: str, set_name: str) -> int:
    prefix = set_name + "-"
    if not pod_name.startswith(prefix):
        return -1
    try:
        return int(pod_name[len(prefix):])
    except ValueError:
        return -1


class StatefulSetController(Controller):
    name = "statefulset"

    def register(self, factory: InformerFactory) -> None:
        self.ss_informer = factory.informer("statefulsets", None)
        self.ss_informer.add_event_handler(self.handler())
        self.pod_informer = factory.informer("pods", None)
        self.pod_informer.add_event_handler(
            self.handler(lambda obj: self.enqueue_owner(obj, "StatefulSet")))

    def _ordinal_pod(self, ss: dict, i: int) -> dict:
        tpl = (ss.get("spec") or {}).get("template") or {}
        md = ss.get("metadata") or {}
        return {
            "apiVersion": "v1",
            "kind": "Pod",
            "metadata": {
                "name": f"{md.get('name', 'x')}-{i}",
                "namespace": md.get("namespace", "default"),
                "labels": dict((tpl.get("metadata") or {}).get("labels") or {}),
                "ownerReferences": [owner_reference(ss, "StatefulSet")],
            },
            "spec": json.loads(json.dumps(tpl.get("spec") or {})),
            "status": {"phase": "Pending"},
        }

    def sync(self, key: str) -> None:
        ns, name = split_key(key)
        ss = self.ss_informer.store.get(key)
        if ss is None or (ss.get("metadata") or {}).get("deletionTimestamp"):
            return
        replicas = int((ss.get("spec") or {}).get("replicas", 1))
        owned = {_ordinal(p["metadata"]["name"], name): p
                 for p in self.pod_informer.store.list()
                 if (p.get("metadata") or {}).get("namespace", "") == ns
                 and is_controlled_by(p, ss)
                 and _ordinal(p["metadata"]["name"], name) >= 0}
        pods_api = self.client.pods(ns)

        # monotonic scale-up: first missing/unready ordinal gates the rest
        ready = 0
        for i in range(replicas):
            p = owned.get(i)
            if p is None:
                pods_api.create(self._ordinal_pod(ss, i))
                break
            st = PodStatus.from_dict(p.get("status"))
            if st.phase == "Failed" or (p.get("metadata") or {}).get("deletionTimestamp"):
                if not (p.get("metadata") or {}).get("deletionTimestamp"):
                    pods_api.delete(p["metadata"]["name"])  # replace next sync
                break
            if not (st.phase == "Running" and st.is_ready()):
                break  # OrderedReady: wait before creating i+1
            ready += 1

        # scale-down from the top, one at a time, only when all ≤replicas-1
        # are stable (condemned ordering in stateful_set_control.go)
        above = sorted((i for i in owned if i >= replicas), reverse=True)
        if above and ready == replicas:
            try:
                pods_api.delete(owned[above[0]]["metadata"]["name"])
            except ApiError as e:
                if e.code != 404:
                    raise

        status = {
            "replicas": len([i for i in owned if i < replicas]),
            "readyReplicas": ready,
            "currentReplicas": len([i for i in owned if i < replicas]),
            "observedGeneration": (ss.get("metadata") or {}).get("generation", 0),
        }
        if ss.get("status") != status:
            try:
                self.client.resource("statefulsets", ns).update_status(
                    {**ss, "status": status})
            except ApiError as e:
                if e.code not in (404, 409):
                    raise
