"""ReplicaSet controller — keep N pod replicas alive.

Reference: ``pkg/controller/replicaset/replica_set.go`` (``syncReplicaSet``:
list matching active pods, adopt via controller-ref, diff against
spec.replicas, batch create/delete, then update status counters).
"""

from __future__ import annotations

from kubernetes_tpu.api.selectors import label_selector_matches
from kubernetes_tpu.api.types import LabelSelector, PodStatus
from kubernetes_tpu.client.clientset import ApiError
from kubernetes_tpu.client.informer import InformerFactory
from kubernetes_tpu.controllers.base import (
    Controller,
    active_pods,
    is_controlled_by,
    owner_reference,
    split_key,
)

BURST_REPLICAS = 500  # upstream burstReplicas cap per sync


def pod_from_template(rs: dict, kind: str = "ReplicaSet") -> dict:
    """Materialize a pod from .spec.template with owner ref + generateName."""
    tpl = (rs.get("spec") or {}).get("template") or {}
    md = rs.get("metadata") or {}
    pod = {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {
            "generateName": f"{md.get('name', 'x')}-",
            "namespace": md.get("namespace", "default"),
            "labels": dict((tpl.get("metadata") or {}).get("labels") or {}),
            "ownerReferences": [owner_reference(rs, kind)],
        },
        "spec": dict(tpl.get("spec") or {}),
        "status": {"phase": "Pending"},
    }
    return pod


class ReplicaSetController(Controller):
    name = "replicaset"
    plural = "replicasets"
    kind = "ReplicaSet"

    def __init__(self, client):
        super().__init__(client)
        self.rs_informer = None
        self.pod_informer = None

    def register(self, factory: InformerFactory) -> None:
        self.rs_informer = factory.informer(self.plural, None)
        self.rs_informer.add_event_handler(self.handler())
        self.pod_informer = factory.informer("pods", None)
        self.pod_informer.add_event_handler(
            self.handler(lambda obj: self.enqueue_owner(obj, self.kind)))

    def _selector(self, rs: dict):
        return LabelSelector.from_dict((rs.get("spec") or {}).get("selector"))

    # ---- syncReplicaSet --------------------------------------------------

    def _owned_pods(self, rs: dict) -> list[dict]:
        ns = (rs.get("metadata") or {}).get("namespace", "")
        sel = self._selector(rs)
        out = []
        for p in self.pod_informer.store.list():
            md = p.get("metadata") or {}
            if md.get("namespace", "") != ns:
                continue
            if not label_selector_matches(sel, md.get("labels") or {}):
                continue
            if is_controlled_by(p, rs):
                out.append(p)
        return out

    def sync(self, key: str) -> None:
        ns, name = split_key(key)
        rs = self.rs_informer.store.get(key)
        if rs is None or (rs.get("metadata") or {}).get("deletionTimestamp"):
            return
        owned = self._owned_pods(rs)
        alive = active_pods(owned)
        want = int((rs.get("spec") or {}).get("replicas", 1))
        diff = want - len(alive)
        pods_api = self.client.pods(ns)
        if diff > 0:
            for _ in range(min(diff, BURST_REPLICAS)):
                pods_api.create(pod_from_template(rs, self.kind))
        elif diff < 0:
            # delete highest-cost pods first: unscheduled, then not-ready,
            # then youngest (getPodsToDelete ranking, simplified)
            def rank(p):
                st = PodStatus.from_dict(p.get("status"))
                return (bool((p.get("spec") or {}).get("nodeName")),
                        st.is_ready(),
                        (p.get("metadata") or {}).get("creationTimestamp", 0.0))
            for p in sorted(alive, key=rank)[:min(-diff, BURST_REPLICAS)]:
                try:
                    pods_api = self.client.pods((p["metadata"].get("namespace", ns)))
                    pods_api.delete(p["metadata"]["name"])
                except ApiError as e:
                    if e.code != 404:
                        raise
        self._update_status(rs, alive)

    def _update_status(self, rs: dict, alive: list[dict]) -> None:
        ready = sum(1 for p in alive
                    if PodStatus.from_dict(p.get("status")).is_ready())
        available = ready  # no minReadySeconds tracking
        new_status = {
            "replicas": len(alive),
            "readyReplicas": ready,
            "availableReplicas": available,
            "observedGeneration": (rs.get("metadata") or {}).get("generation", 0),
        }
        if rs.get("status") != new_status:
            obj = {**rs, "status": new_status}
            try:
                self.client.resource(self.plural,
                                     rs["metadata"].get("namespace")).update_status(obj)
            except ApiError as e:
                if e.code not in (404, 409):
                    raise


class ReplicationControllerController(ReplicaSetController):
    """Legacy ReplicationController — same reconcile with v1 semantics.

    Reference: ``pkg/controller/replication`` (a thin adapter over the
    ReplicaSet logic upstream too). RC selectors are plain label MAPS, not
    LabelSelectors, and default to the template's labels when unset.
    """

    name = "replicationcontroller"
    plural = "replicationcontrollers"
    kind = "ReplicationController"

    def _selector(self, rc: dict):
        sel = (rc.get("spec") or {}).get("selector")
        if not sel:
            tpl = ((rc.get("spec") or {}).get("template") or {})
            sel = (tpl.get("metadata") or {}).get("labels") or {}
        return LabelSelector(match_labels=dict(sel))
