"""ServiceAccount + token controllers.

Reference: ``pkg/controller/serviceaccount/serviceaccounts_controller.go``
(ensure the ``default`` ServiceAccount exists in every namespace) and
``tokens_controller.go`` (legacy path: mint a
``kubernetes.io/service-account-token`` Secret per ServiceAccount and record
it in ``sa.secrets``). The apiserver's TokenAuthenticator resolves these
secrets into ``system:serviceaccount:<ns>:<name>`` identities
(store/auth.py), closing the loop: create a namespace -> default SA ->
token secret -> authenticated API access for the namespace's workloads.
"""

from __future__ import annotations

import secrets as _secrets

from kubernetes_tpu.client.clientset import ApiError
from kubernetes_tpu.client.informer import InformerFactory
from kubernetes_tpu.controllers.base import Controller, owner_reference, split_key
from kubernetes_tpu.store.auth import SA_NAME_ANNOTATION, SA_TOKEN_TYPE


class ServiceAccountController(Controller):
    """Every active namespace gets a ``default`` ServiceAccount."""

    name = "serviceaccount"
    workers = 1

    def register(self, factory: InformerFactory) -> None:
        self.ns_informer = factory.informer("namespaces", None)
        self.ns_informer.add_event_handler(self.handler())
        self.sa_informer = factory.informer("serviceaccounts", None)
        # recreate the default SA if somebody deletes it
        self.sa_informer.add_event_handler(self.handler(self._enqueue_ns))

    def _enqueue_ns(self, sa: dict) -> None:
        ns = (sa.get("metadata") or {}).get("namespace", "")
        if ns:
            self.queue.add(ns)

    def sync(self, key: str) -> None:
        if self.ns_informer.store.get(key) is None:
            return  # namespace gone; its contents are being purged
        if self.sa_informer.store.get(f"{key}/default") is not None:
            return
        try:
            self.client.resource("serviceaccounts", key).create({
                "apiVersion": "v1", "kind": "ServiceAccount",
                "metadata": {"name": "default", "namespace": key}})
        except ApiError as e:
            if e.code != 409:
                raise


class TokenController(Controller):
    """Every ServiceAccount gets a token Secret it owns."""

    name = "serviceaccount-token"
    workers = 1

    def register(self, factory: InformerFactory) -> None:
        self.sa_informer = factory.informer("serviceaccounts", None)
        self.sa_informer.add_event_handler(self.handler())
        self.secret_informer = factory.informer("secrets", None)
        self.secret_informer.add_event_handler(
            self.handler(lambda obj: self.enqueue_owner(obj, "ServiceAccount")))

    def sync(self, key: str) -> None:
        ns, name = split_key(key)
        sa = self.sa_informer.store.get(key)
        if sa is None:
            return  # GC cascades the owned secret
        secret_name = f"{name}-token"
        existing = self.secret_informer.store.get(f"{ns}/{secret_name}")
        if existing is None:
            secret = {
                "apiVersion": "v1", "kind": "Secret",
                "metadata": {
                    "name": secret_name, "namespace": ns,
                    "annotations": {SA_NAME_ANNOTATION: name},
                    "ownerReferences": [owner_reference(sa, "ServiceAccount")],
                },
                "type": SA_TOKEN_TYPE,
                "data": {"token": f"ktpu-sa-{_secrets.token_hex(16)}"},
            }
            try:
                self.client.resource("secrets", ns).create(secret)
            except ApiError as e:
                if e.code != 409:
                    raise
        if secret_name not in [s.get("name") for s in sa.get("secrets") or []]:
            desired = dict(sa)
            desired["secrets"] = (list(sa.get("secrets") or [])
                                  + [{"name": secret_name}])
            try:
                self.client.resource("serviceaccounts", ns).update(desired)
            except ApiError as e:
                if e.code not in (404, 409):
                    raise
