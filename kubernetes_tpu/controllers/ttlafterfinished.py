"""TTL-after-finished controller — delete finished Jobs past their TTL.

Reference: ``pkg/controller/ttlafterfinished/ttlafterfinished_controller.go``
(``processJob``: a Job with ``spec.ttlSecondsAfterFinished`` whose finish
time + TTL has passed is deleted; cascading deletion of its pods is the
garbage collector's business via ownerReferences).
"""

from __future__ import annotations

import time

from kubernetes_tpu.client.clientset import ApiError
from kubernetes_tpu.client.informer import InformerFactory
from kubernetes_tpu.controllers.base import Controller, split_key
from kubernetes_tpu.controllers.job import job_finished


def _finish_time(job: dict) -> float:
    st = job.get("status") or {}
    if st.get("completionTime"):
        return float(st["completionTime"])
    for c in st.get("conditions") or []:
        if c.get("type") in ("Complete", "Failed") and c.get("status") == "True":
            if c.get("lastTransitionTime"):
                return float(c["lastTransitionTime"])
    return float((job.get("metadata") or {}).get("creationTimestamp") or 0)


class TTLAfterFinishedController(Controller):
    name = "ttlafterfinished"
    tick_interval = 1.0

    def register(self, factory: InformerFactory) -> None:
        self.job_informer = factory.informer("jobs", None)
        self.job_informer.add_event_handler(self.handler())

    def tick(self) -> None:
        for j in self.job_informer.store.list():
            if (j.get("spec") or {}).get("ttlSecondsAfterFinished") is not None:
                self.enqueue(j)

    def sync(self, key: str) -> None:
        ns, name = split_key(key)
        job = self.job_informer.store.get(key)
        if job is None:
            return
        ttl = (job.get("spec") or {}).get("ttlSecondsAfterFinished")
        if ttl is None or not job_finished(job):
            return
        if time.time() - _finish_time(job) < float(ttl):
            return
        try:
            self.client.resource("jobs", ns).delete(name)
        except ApiError as e:
            if e.code != 404:
                raise
