"""Pod garbage collector.

Reference: ``pkg/controller/podgc/gc_controller.go``: periodically delete
(a) terminated pods beyond ``terminatedPodThreshold`` (oldest first;
upstream kube-controller-manager defaults the threshold to 12500),
(b) orphaned pods bound to nodes that no longer exist, and (c) unscheduled
pods that are terminating (deletionTimestamp set, no node).

Safety deviations that matter:
- Orphan deletion requires BOTH a quarantine period (upstream's
  ``quarantineTime`` ~40s) and a live apiserver GET confirming the node is
  really gone — a stale or unsynced informer cache must never mass-delete
  healthy pods.
- The terminated sweep skips pods still owned by a controller: Job
  completion counting here recounts live pods (no job-tracking finalizers),
  so reaping a Job's Succeeded pods would erase completed work. Owned
  terminated pods are the TTL / cascade controllers' jurisdiction.
"""

from __future__ import annotations

import time

from kubernetes_tpu.client.clientset import ApiError
from kubernetes_tpu.client.informer import InformerFactory
from kubernetes_tpu.controllers.base import Controller, controller_of


class PodGCController(Controller):
    name = "podgc"
    workers = 1
    tick_interval = 2.0  # upstream gcCheckPeriod 20s

    def __init__(self, client, terminated_threshold: int = 12500,
                 quarantine_s: float = 40.0):
        super().__init__(client)
        self.terminated_threshold = terminated_threshold
        self.quarantine_s = quarantine_s
        # node name -> first time the informer reported it missing
        self._missing_since: dict[str, float] = {}

    def register(self, factory: InformerFactory) -> None:
        self.pod_informer = factory.informer("pods", None)
        self.node_informer = factory.informer("nodes", None)

    def sync(self, key: str) -> None:
        pass  # purely tick-driven (upstream runs gc() on a timer, no queue)

    def tick(self) -> None:
        pods = self.pod_informer.store.list()
        nodes = {(n.get("metadata") or {}).get("name", "")
                 for n in self.node_informer.store.list()}
        self._gc_terminated(pods)
        self._gc_orphaned(pods, nodes)
        self._gc_unscheduled_terminating(pods)

    def _delete(self, pod: dict) -> None:
        md = pod.get("metadata") or {}
        try:
            self.client.pods(md.get("namespace", "default")).delete(
                md.get("name", ""))
        except ApiError as e:
            if e.code != 404:
                raise

    def _gc_terminated(self, pods: list[dict]) -> None:
        """Reap the oldest UNOWNED terminated pods beyond the threshold."""
        if self.terminated_threshold <= 0:
            return
        terminated = [p for p in pods
                      if (p.get("status") or {}).get("phase")
                      in ("Succeeded", "Failed")
                      and controller_of(p) is None]
        excess = len(terminated) - self.terminated_threshold
        if excess <= 0:
            return

        def created(p):
            return (p.get("metadata") or {}).get("creationTimestamp") or 0
        for p in sorted(terminated, key=created)[:excess]:
            self._delete(p)

    def _node_really_gone(self, name: str) -> bool:
        """Quarantine + live confirmation (gcOrphaned's discoverDeletedNodes):
        the informer's absence must persist for quarantine_s AND the
        apiserver itself must 404 the node."""
        now = time.time()
        since = self._missing_since.setdefault(name, now)
        if now - since < self.quarantine_s:
            return False
        try:
            self.client.nodes().get(name)
            return False  # cache was stale; the node exists
        except ApiError as e:
            return e.code == 404
        except Exception:  # ktpu-lint: disable=KTL002 -- apiserver unreachable: never delete on doubt (the fallback IS the safety decision)
            return False  # apiserver unreachable: never delete on doubt

    def _gc_orphaned(self, pods: list[dict], nodes: set) -> None:
        """Pods bound to a node that no longer exists (gcOrphaned)."""
        bound_to = {(p.get("spec") or {}).get("nodeName", "") for p in pods}
        for name in list(self._missing_since):
            if name in nodes or name not in bound_to:
                del self._missing_since[name]  # reappeared / nothing bound
        for p in pods:
            node = (p.get("spec") or {}).get("nodeName", "")
            if node and node not in nodes and self._node_really_gone(node):
                self._delete(p)

    def _gc_unscheduled_terminating(self, pods: list[dict]) -> None:
        """Terminating pods that never got a node (gcUnscheduledTerminating)."""
        for p in pods:
            md = p.get("metadata") or {}
            if md.get("deletionTimestamp") and \
                    not (p.get("spec") or {}).get("nodeName"):
                self._delete(p)
