"""DaemonSet controller — one pod per eligible node.

Reference: ``pkg/controller/daemon/daemon_controller.go`` (``syncDaemonSet``,
``podsShouldBeOnNode``) and ``util/daemonset_util.go``. Pods are pinned with
a required nodeAffinity ``matchFields metadata.name`` term and flow through
the regular scheduler (the ≥1.12 ScheduleDaemonSetPods behavior), with the
standard auto-added not-ready/unreachable NoExecute tolerations.
"""

from __future__ import annotations

from kubernetes_tpu.api.selectors import (
    label_selector_matches,
    node_fields,
    node_selector_matches,
)
from kubernetes_tpu.api.types import (
    EFFECT_NO_SCHEDULE,
    NodeSelectorTerm,
    Pod,
    Requirement,
    Taint,
    Toleration,
)
from kubernetes_tpu.client.clientset import ApiError
from kubernetes_tpu.client.informer import InformerFactory
from kubernetes_tpu.controllers.base import (
    Controller,
    active_pods,
    is_controlled_by,
    split_key,
)
from kubernetes_tpu.controllers.replicaset import pod_from_template

# AddOrUpdateDaemonPodTolerations (pkg/controller/daemon/util/daemonset_util.go)
DAEMON_TOLERATIONS = [
    {"key": "node.kubernetes.io/not-ready", "operator": "Exists", "effect": "NoExecute"},
    {"key": "node.kubernetes.io/unreachable", "operator": "Exists", "effect": "NoExecute"},
    {"key": "node.kubernetes.io/unschedulable", "operator": "Exists", "effect": "NoSchedule"},
]


def daemon_pod_for_node(ds: dict, node_name: str) -> dict:
    pod = pod_from_template(ds, kind="DaemonSet")
    spec = pod["spec"]
    aff = spec.setdefault("affinity", {})
    na = aff.setdefault("nodeAffinity", {})
    req = na.setdefault("requiredDuringSchedulingIgnoredDuringExecution", {})
    req["nodeSelectorTerms"] = [{
        "matchFields": [{"key": "metadata.name", "operator": "In",
                         "values": [node_name]}]}]
    tols = list(spec.get("tolerations") or [])
    have = {(t.get("key"), t.get("effect")) for t in tols}
    for t in DAEMON_TOLERATIONS:
        if (t["key"], t["effect"]) not in have:
            tols.append(dict(t))
    spec["tolerations"] = tols
    return pod


class DaemonSetController(Controller):
    name = "daemonset"

    def register(self, factory: InformerFactory) -> None:
        self.ds_informer = factory.informer("daemonsets", None)
        self.ds_informer.add_event_handler(self.handler())
        self.node_informer = factory.informer("nodes", None)
        self.node_informer.add_event_handler(self.handler(self._enqueue_all))
        self.pod_informer = factory.informer("pods", None)
        self.pod_informer.add_event_handler(
            self.handler(lambda obj: self.enqueue_owner(obj, "DaemonSet")))

    def _enqueue_all(self, _obj: dict) -> None:
        # node add/remove re-evaluates every daemonset
        for key in self.ds_informer.store.keys():
            self.queue.add(key)

    # ---- eligibility (nodeShouldRunDaemonPod) ----------------------------

    def _node_eligible(self, ds: dict, node: dict) -> bool:
        tpl_spec = ((ds.get("spec") or {}).get("template") or {}).get("spec") or {}
        labels = (node.get("metadata") or {}).get("labels") or {}
        name = (node.get("metadata") or {}).get("name", "")
        sel = tpl_spec.get("nodeSelector") or {}
        if sel and not all(labels.get(k) == v for k, v in sel.items()):
            return False
        na = ((tpl_spec.get("affinity") or {}).get("nodeAffinity") or {})
        req = (na.get("requiredDuringSchedulingIgnoredDuringExecution") or {})
        terms = [NodeSelectorTerm.from_dict(t)
                 for t in req.get("nodeSelectorTerms") or []]
        if terms and not node_selector_matches(terms, labels, node_fields(name)):
            return False
        # NoSchedule/NoExecute taints must be tolerated (daemon tolerations
        # are auto-added to the pod, so include them here)
        tols = [Toleration.from_dict(t) for t in
                list(tpl_spec.get("tolerations") or []) + DAEMON_TOLERATIONS]
        for td in (node.get("spec") or {}).get("taints") or []:
            taint = Taint.from_dict(td)
            if taint.effect == "PreferNoSchedule":
                continue
            if not any(t.tolerates(taint) for t in tols):
                return False
        return True

    def sync(self, key: str) -> None:
        ns, name = split_key(key)
        ds = self.ds_informer.store.get(key)
        if ds is None or (ds.get("metadata") or {}).get("deletionTimestamp"):
            return
        owned = [p for p in self.pod_informer.store.list()
                 if (p.get("metadata") or {}).get("namespace", "") == ns
                 and is_controlled_by(p, ds)]
        by_node: dict[str, list[dict]] = {}
        for p in active_pods(owned):
            n = _pinned_node(p)
            if n:
                by_node.setdefault(n, []).append(p)
            else:
                self._delete(p)  # un-pinned daemon pod is malformed
        pods_api = self.client.pods(ns)
        desired = 0
        ready = 0
        for node in self.node_informer.store.list():
            node_name = (node.get("metadata") or {}).get("name", "")
            eligible = self._node_eligible(ds, node)
            have = by_node.get(node_name, [])
            if eligible:
                desired += 1
                if not have:
                    pods_api.create(daemon_pod_for_node(ds, node_name))
                else:
                    for extra in have[1:]:
                        self._delete(extra)
                    if Pod.from_dict(have[0]).status.is_ready():
                        ready += 1
            else:
                for p in have:
                    self._delete(p)
        # pods pinned to vanished nodes
        node_names = {(n.get("metadata") or {}).get("name", "")
                      for n in self.node_informer.store.list()}
        for n, pods in by_node.items():
            if n not in node_names:
                for p in pods:
                    self._delete(p)
        status = {
            "desiredNumberScheduled": desired,
            "currentNumberScheduled": sum(len(v) for k, v in by_node.items()
                                          if k in node_names),
            "numberReady": ready,
            "observedGeneration": (ds.get("metadata") or {}).get("generation", 0),
        }
        if ds.get("status") != status:
            try:
                self.client.resource("daemonsets", ns).update_status(
                    {**ds, "status": status})
            except ApiError as e:
                if e.code not in (404, 409):
                    raise

    def _delete(self, p: dict) -> None:
        try:
            self.client.pods(p["metadata"].get("namespace", "default")) \
                .delete(p["metadata"]["name"])
        except ApiError as e:
            if e.code != 404:
                raise


def _pinned_node(pod: dict) -> str:
    """Target node of a daemon pod: bound nodeName, else the matchFields pin."""
    spec = pod.get("spec") or {}
    if spec.get("nodeName"):
        return spec["nodeName"]
    na = ((spec.get("affinity") or {}).get("nodeAffinity") or {})
    for term in (na.get("requiredDuringSchedulingIgnoredDuringExecution") or {}) \
            .get("nodeSelectorTerms") or []:
        for mf in term.get("matchFields") or []:
            if mf.get("key") == "metadata.name" and mf.get("values"):
                return mf["values"][0]
    return ""
