"""Job controller — run pods to completion.

Reference: ``pkg/controller/job/job_controller.go`` (``syncJob``: count
active/succeeded/failed pods, create up to parallelism, stop at completions,
fail the job past backoffLimit).
"""

from __future__ import annotations

import time

from kubernetes_tpu.api.selectors import label_selector_matches
from kubernetes_tpu.api.types import LabelSelector
from kubernetes_tpu.client.clientset import ApiError
from kubernetes_tpu.client.informer import InformerFactory
from kubernetes_tpu.controllers.base import (
    Controller,
    active_pods,
    is_controlled_by,
    split_key,
)
from kubernetes_tpu.controllers.replicaset import pod_from_template


def _condition(job: dict, type_: str) -> bool:
    return any(c.get("type") == type_ and c.get("status") == "True"
               for c in (job.get("status") or {}).get("conditions") or [])


def job_finished(job: dict) -> bool:
    return _condition(job, "Complete") or _condition(job, "Failed")


class JobController(Controller):
    name = "job"

    def register(self, factory: InformerFactory) -> None:
        self.job_informer = factory.informer("jobs", None)
        self.job_informer.add_event_handler(self.handler())
        self.pod_informer = factory.informer("pods", None)
        self.pod_informer.add_event_handler(
            self.handler(lambda obj: self.enqueue_owner(obj, "Job")))

    def _owned_pods(self, job: dict) -> list[dict]:
        ns = (job.get("metadata") or {}).get("namespace", "")
        sel = LabelSelector.from_dict((job.get("spec") or {}).get("selector"))
        out = []
        for p in self.pod_informer.store.list():
            md = p.get("metadata") or {}
            if md.get("namespace", "") != ns:
                continue
            if sel is not None and not label_selector_matches(sel, md.get("labels") or {}):
                continue
            if is_controlled_by(p, job):
                out.append(p)
        return out

    def sync(self, key: str) -> None:
        ns, name = split_key(key)
        job = self.job_informer.store.get(key)
        if job is None or (job.get("metadata") or {}).get("deletionTimestamp"):
            return
        if job_finished(job):
            return
        spec = job.get("spec") or {}
        parallelism = int(spec.get("parallelism", 1))
        completions = spec.get("completions")  # None = work-queue semantics
        backoff_limit = int(spec.get("backoffLimit", 6))

        pods = self._owned_pods(job)
        active = active_pods(pods)
        succeeded = sum(1 for p in pods
                        if (p.get("status") or {}).get("phase") == "Succeeded")
        failed = sum(1 for p in pods
                     if (p.get("status") or {}).get("phase") == "Failed")

        conditions = list((job.get("status") or {}).get("conditions") or [])
        now = time.time()
        if failed > backoff_limit:
            conditions.append({"type": "Failed", "status": "True",
                               "reason": "BackoffLimitExceeded",
                               "lastTransitionTime": now})
            for p in active:
                self._delete_pod(p)
            active = []
        elif completions is not None and succeeded >= int(completions):
            conditions.append({"type": "Complete", "status": "True",
                               "lastTransitionTime": now})
            for p in active:
                self._delete_pod(p)
            active = []
        else:
            want_active = parallelism
            if completions is not None:
                want_active = min(parallelism, int(completions) - succeeded)
            diff = want_active - len(active)
            if diff > 0:
                pods_api = self.client.pods(ns)
                tpl_job = {**job, "apiVersion": "batch/v1"}
                for _ in range(diff):
                    pod = pod_from_template(tpl_job, kind="Job")
                    pod["spec"]["restartPolicy"] = (job.get("spec", {})
                                                    .get("template", {})
                                                    .get("spec", {})
                                                    .get("restartPolicy", "Never"))
                    pods_api.create(pod)
            elif diff < 0:
                for p in active[:(-diff)]:
                    self._delete_pod(p)

        status = {
            "active": len(active),
            "succeeded": succeeded,
            "failed": failed,
            "conditions": conditions,
        }
        if job.get("status") != status:
            try:
                self.client.resource("jobs", ns).update_status({**job, "status": status})
            except ApiError as e:
                if e.code not in (404, 409):
                    raise

    def _delete_pod(self, p: dict) -> None:
        try:
            self.client.pods(p["metadata"].get("namespace", "default")) \
                .delete(p["metadata"]["name"])
        except ApiError as e:
            if e.code != 404:
                raise
