"""EndpointSlice controller — sharded endpoints (discovery.k8s.io/v1).

Reference: ``pkg/controller/endpointslice/endpointslice_controller.go`` +
``staging/src/k8s.io/endpointslice/reconciler.go``: for each Service, emit
EndpointSlice objects labeled ``kubernetes.io/service-name`` holding at most
``maxEndpointsPerSlice`` endpoints each, with per-endpoint ready condition
and per-slice resolved ports (named targetPorts resolve per pod, so pods
whose ports differ land in different slices — same grouping as the
Endpoints controller's subsets).
"""

from __future__ import annotations

from kubernetes_tpu.api.types import PodStatus
from kubernetes_tpu.client.clientset import ApiError
from kubernetes_tpu.client.informer import InformerFactory
from kubernetes_tpu.controllers.base import Controller, split_key
from kubernetes_tpu.controllers.endpoints import _resolve_target_port

SERVICE_NAME_LABEL = "kubernetes.io/service-name"
MANAGED_BY_LABEL = "endpointslice.kubernetes.io/managed-by"
MANAGED_BY = "endpointslice-controller.k8s.io"
MAX_ENDPOINTS_PER_SLICE = 100


class EndpointSliceController(Controller):
    name = "endpointslice"

    def register(self, factory: InformerFactory) -> None:
        self.svc_informer = factory.informer("services", None)
        self.svc_informer.add_event_handler(self.handler())
        self.pod_informer = factory.informer("pods", None)
        self.pod_informer.add_event_handler(self.handler(self._enqueue_services))
        self.slice_informer = factory.informer("endpointslices", None)

    def _enqueue_services(self, pod: dict) -> None:
        labels = (pod.get("metadata") or {}).get("labels") or {}
        ns = (pod.get("metadata") or {}).get("namespace", "")
        for svc in self.svc_informer.store.list():
            smd = svc.get("metadata") or {}
            if smd.get("namespace", "") != ns:
                continue
            sel = (svc.get("spec") or {}).get("selector") or {}
            if sel and all(labels.get(k) == v for k, v in sel.items()):
                self.enqueue(svc)

    def _desired_slices(self, svc: dict, ns: str, name: str) -> list[dict]:
        sel = (svc.get("spec") or {}).get("selector") or {}
        svc_ports = (svc.get("spec") or {}).get("ports") or []
        groups: dict[tuple, dict] = {}
        for p in self.pod_informer.store.list():
            md = p.get("metadata") or {}
            if md.get("namespace", "") != ns:
                continue
            labels = md.get("labels") or {}
            if not sel or not all(labels.get(k) == v for k, v in sel.items()):
                continue
            st = PodStatus.from_dict(p.get("status"))
            if st.phase in ("Succeeded", "Failed") or not st.pod_ip:
                continue
            ports = []
            for sp in svc_ports:
                port = _resolve_target_port(sp, p)
                if port is not None:
                    ports.append({"name": sp.get("name", ""), "port": port,
                                  "protocol": sp.get("protocol", "TCP")})
            if svc_ports and not ports:
                continue
            gkey = tuple(sorted((pp["name"], pp["port"], pp["protocol"])
                                for pp in ports))
            g = groups.setdefault(gkey, {"ports": ports, "endpoints": []})
            g["endpoints"].append({
                "addresses": [st.pod_ip],
                "conditions": {"ready": st.is_ready()},
                "nodeName": (p.get("spec") or {}).get("nodeName", ""),
                "targetRef": {"kind": "Pod", "name": md.get("name", ""),
                              "namespace": ns, "uid": md.get("uid", "")}})
        slices = []
        idx = 0
        for gkey in sorted(groups):
            g = groups[gkey]
            eps = sorted(g["endpoints"], key=lambda e: e["addresses"][0])
            for off in range(0, len(eps), MAX_ENDPOINTS_PER_SLICE):
                slices.append({
                    "apiVersion": "discovery.k8s.io/v1",
                    "kind": "EndpointSlice",
                    "metadata": {"name": f"{name}-{idx}", "namespace": ns,
                                 "labels": {SERVICE_NAME_LABEL: name,
                                            MANAGED_BY_LABEL: MANAGED_BY}},
                    "addressType": "IPv4",
                    "ports": g["ports"],
                    "endpoints": eps[off:off + MAX_ENDPOINTS_PER_SLICE]})
                idx += 1
        return slices

    def sync(self, key: str) -> None:
        ns, name = split_key(key)
        svc = self.svc_informer.store.get(key)
        handle = self.client.resource("endpointslices", ns)
        existing = [
            s for s in self.slice_informer.store.list()
            if (s.get("metadata") or {}).get("namespace", "") == ns
            and ((s.get("metadata") or {}).get("labels") or {})
            .get(SERVICE_NAME_LABEL) == name
            # only slices THIS controller stamped are its to reconcile or
            # delete: a foreign manager's mirrors and a user's hand-made
            # unlabeled slices are both left alone (upstream contract)
            and ((s.get("metadata") or {}).get("labels") or {})
            .get(MANAGED_BY_LABEL) == MANAGED_BY]
        if svc is None or not (svc.get("spec") or {}).get("selector"):
            for s in existing:
                try:
                    handle.delete((s.get("metadata") or {}).get("name", ""))
                except ApiError as e:
                    if e.code != 404:
                        raise
            return
        desired = self._desired_slices(svc, ns, name)
        by_name = {(s.get("metadata") or {}).get("name"): s for s in existing}
        for d in desired:
            cur = by_name.pop(d["metadata"]["name"], None)
            if cur is None:
                try:
                    handle.create(d)
                except ApiError as e:
                    if e.code != 409:
                        raise
            elif (cur.get("endpoints") != d["endpoints"]
                  or cur.get("ports") != d["ports"]):
                d["metadata"]["resourceVersion"] = \
                    (cur.get("metadata") or {}).get("resourceVersion", "")
                handle.update(d)
        for stale in by_name.values():  # more slices than needed
            try:
                handle.delete((stale.get("metadata") or {}).get("name", ""))
            except ApiError as e:
                if e.code != 404:
                    raise
