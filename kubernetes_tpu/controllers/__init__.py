"""Controllers — reconcile loops over the API (SURVEY §2.3).

Each controller is the informer + workqueue + ``sync(key)`` pattern from
``pkg/controller/``; ``ControllerManager`` is the kube-controller-manager
analog wiring them over one shared informer factory.
"""

from kubernetes_tpu.controllers.base import Controller, active_pods, controller_of
from kubernetes_tpu.controllers.daemonset import DaemonSetController
from kubernetes_tpu.controllers.deployment import DeploymentController
from kubernetes_tpu.controllers.endpoints import EndpointsController
from kubernetes_tpu.controllers.garbagecollector import GarbageCollector
from kubernetes_tpu.controllers.job import JobController
from kubernetes_tpu.controllers.manager import ControllerManager
from kubernetes_tpu.controllers.nodelifecycle import NodeLifecycleController
from kubernetes_tpu.controllers.replicaset import ReplicaSetController
from kubernetes_tpu.controllers.statefulset import StatefulSetController

__all__ = [
    "Controller", "ControllerManager", "DaemonSetController",
    "DeploymentController", "EndpointsController", "GarbageCollector",
    "JobController", "NodeLifecycleController", "ReplicaSetController",
    "StatefulSetController", "active_pods", "controller_of",
]
