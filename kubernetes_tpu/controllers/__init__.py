"""Controllers — reconcile loops over the API (SURVEY §2.3).

Each controller is the informer + workqueue + ``sync(key)`` pattern from
``pkg/controller/``; ``ControllerManager`` is the kube-controller-manager
analog wiring them over one shared informer factory.
"""

from kubernetes_tpu.controllers.base import Controller, active_pods, controller_of
from kubernetes_tpu.controllers.cronjob import CronJobController
from kubernetes_tpu.controllers.daemonset import DaemonSetController
from kubernetes_tpu.controllers.deployment import DeploymentController
from kubernetes_tpu.controllers.disruption import DisruptionController
from kubernetes_tpu.controllers.endpoints import EndpointsController
from kubernetes_tpu.controllers.endpointslice import EndpointSliceController
from kubernetes_tpu.controllers.garbagecollector import GarbageCollector
from kubernetes_tpu.controllers.hpa import HorizontalPodAutoscalerController
from kubernetes_tpu.controllers.job import JobController
from kubernetes_tpu.controllers.manager import ControllerManager
from kubernetes_tpu.controllers.namespace import NamespaceController
from kubernetes_tpu.controllers.nodelifecycle import NodeLifecycleController
from kubernetes_tpu.controllers.podgc import PodGCController
from kubernetes_tpu.controllers.replicaset import (
    ReplicaSetController,
    ReplicationControllerController,
)
from kubernetes_tpu.controllers.resourceclaim import ResourceClaimController
from kubernetes_tpu.controllers.serviceaccount import (
    ServiceAccountController,
    TokenController,
)
from kubernetes_tpu.controllers.statefulset import StatefulSetController
from kubernetes_tpu.controllers.ttlafterfinished import TTLAfterFinishedController

__all__ = [
    "Controller", "ControllerManager", "CronJobController",
    "DaemonSetController", "DeploymentController", "DisruptionController",
    "EndpointsController", "EndpointSliceController", "GarbageCollector",
    "HorizontalPodAutoscalerController", "JobController",
    "NamespaceController", "NodeLifecycleController", "PodGCController",
    "ReplicaSetController", "ReplicationControllerController",
    "ResourceClaimController",
    "ServiceAccountController", "StatefulSetController",
    "TTLAfterFinishedController", "TokenController", "active_pods",
    "controller_of",
]
