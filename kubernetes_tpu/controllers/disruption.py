"""Disruption controller — keep PodDisruptionBudget status current.

Reference: ``pkg/controller/disruption/disruption.go`` (``trySync`` /
``updatePdbStatus``: count matching healthy pods, derive desiredHealthy from
minAvailable / maxUnavailable, publish disruptionsAllowed). The eviction
subresource reads these budgets (store/apiserver.py) and the scheduler's
preemption prefers victims whose budgets still allow disruption
(sched/preemption.py).
"""

from __future__ import annotations

from kubernetes_tpu.api.policy import compute_pdb_status
from kubernetes_tpu.client.clientset import ApiError
from kubernetes_tpu.client.informer import InformerFactory
from kubernetes_tpu.controllers.base import Controller, split_key


class DisruptionController(Controller):
    name = "disruption"

    def register(self, factory: InformerFactory) -> None:
        self.pdb_informer = factory.informer("poddisruptionbudgets", None)
        self.pdb_informer.add_event_handler(self.handler())
        self.pod_informer = factory.informer("pods", None)
        self.pod_informer.add_event_handler(self.handler(self._enqueue_pdbs))

    def _enqueue_pdbs(self, pod: dict) -> None:
        # getPdbsForPod: only budgets whose selector covers this pod resync
        # (a bind storm must not turn PDB maintenance quadratic)
        from kubernetes_tpu.api.policy import _matches
        md = pod.get("metadata") or {}
        ns = md.get("namespace", "")
        labels = md.get("labels") or {}
        for pdb in self.pdb_informer.store.list():
            if (pdb.get("metadata") or {}).get("namespace", "") != ns:
                continue
            if _matches((pdb.get("spec") or {}).get("selector"), labels):
                self.enqueue(pdb)

    def sync(self, key: str) -> None:
        ns, name = split_key(key)
        pdb = self.pdb_informer.store.get(key)
        if pdb is None:
            return
        pods = [p for p in self.pod_informer.store.list()
                if (p.get("metadata") or {}).get("namespace", "") == ns]
        status = compute_pdb_status(pdb, pods)
        if (pdb.get("status") or {}) == status:
            return
        desired = dict(pdb)
        desired["status"] = status
        try:
            self.client.resource("poddisruptionbudgets", ns).update_status(desired)
        except ApiError as e:
            if e.code not in (404, 409):  # deleted / raced: requeue later
                raise
