"""Churn patches for the device-resident drain context.

Reference shape: ``pkg/scheduler/internal/cache/cache.go`` keeps per-node
generation counters so ``UpdateSnapshot`` copies only what changed; the
scheduler never rebuilds its whole view because one node flapped. The TPU
analog: the fused drain keeps the cluster encoding resident in HBM
(models/gang.py drain_step), and this module turns the cache's delta log
(sched/cache.py) into STATIC-SHAPE scatter arrays a single jitted program
(models/gang.py apply_ctx_patch) applies to that resident encoding — node
and pod churn become a ~KB host->device transfer instead of a multi-MB
re-encode + re-upload per scheduling pop.

Layout contract with drain_step:
- epod rows [0, fill) hold device-folded committed pods (packed upward);
  PATCHED pods take slots from the TOP of the free region downward, so the
  two allocators never collide. ``free_floor`` (lowest patched slot) bounds
  how far folds may grow before a rebuild repacks.
- node rows beyond the live cluster (``node_free``) absorb node ADDs; a
  node DELETE retires its row until no bound pod references it.
- nominee reservations (nom_* tensors) live at a fixed bucket M so
  preemption storms patch reservations instead of dropping the context.

Anything that does not fit — bucket overflow, a new resource kind or
topology key (static args!), pods with host ports/volumes (they own
node-side port/volume state) — compiles to ``None`` and the caller
rebuilds the context from a fresh host snapshot. Correct first, resident
when provable.
"""

from __future__ import annotations

from dataclasses import dataclass, field as dc_field
from typing import Any, Optional

import numpy as np

from kubernetes_tpu.api.types import Node, Pod
from kubernetes_tpu.encode.dictionary import next_bucket
from kubernetes_tpu.encode.scaling import UNLIMITED, scale_allocatable
from kubernetes_tpu.encode.snapshot import (
    EFFECTC,
    NODE_NAME_LABEL,
    SnapshotMeta,
    _selset_arrays,
    _selset_fill,
)
from kubernetes_tpu.encode.termprep import (
    affinity_term_selector,
    resolve_term_namespaces,
)

# minimum write-bucket widths: generous floors so virtually every patch in
# a run reuses ONE compiled apply_ctx_patch variant (the warmup compiles
# exactly this combination); scatters over padded rows are cheap, an XLA
# recompile mid-window is seconds
_MIN_PODS = 64
_MIN_NODES = 64
_MIN_NOMS = 64


@dataclass
class CtxPatchState:
    """Host-side bookkeeping for ONE device-resident drain context.

    Forked from the encoder's post-encode ``_PatchState`` (same slot/row
    maps) but evolves independently: the device context folds committed
    pods into slots the host snapshot never sees, so the two replicas stop
    agreeing on slot assignment after the first drain."""

    resources: list[str]
    res_index: dict[str, int]
    node_index: dict[str, int]
    K: int
    ET: int
    EAX: int
    EAV: int
    NSB: int
    N: int
    V: int
    T: int
    I: int
    IMG: int
    E: int
    # Pod-side label width of the RESIDENT epod arrays. extend_cluster
    # unifies epod_labels/ea_* to max(cluster, batch) widths, so a batch
    # whose label keys crossed a bucket AFTER the cluster encode leaves the
    # context wider than the encoder's K — patches write at EK (and the
    # scheduler re-syncs ET/EAX/EAV/NSB from the staged arrays) or the
    # scatter rows would not broadcast. K keeps addressing the node rows.
    EK: int = 0
    slot_of: dict[str, int] = dc_field(default_factory=dict)
    slot_node: dict[str, int] = dc_field(default_factory=dict)
    slot_req: dict[str, Any] = dc_field(default_factory=dict)
    unpatchable: set = dc_field(default_factory=set)
    # Slot allocation: device folds fill [0, fill_host) UPWARD; patches
    # allocate DOWNWARD from ``top`` (starts at e0). Freed slots are never
    # reused — a freed slot in the folds' path would be silently
    # overwritten as fill grows — so deletes leak their slot and the
    # context rebuilds (repacking) when the cursors meet. The scheduler
    # re-checks fill_bound + batch <= top AFTER compiling each patch.
    top: int = 0
    fill_host: int = 0        # host's view of the device fold watermark
    node_free: list[int] = dc_field(default_factory=list)  # ascending rows
    node_retired: set = dc_field(default_factory=set)
    row_pods: dict[int, int] = dc_field(default_factory=dict)
    # pods deliberately invisible (bound to nodes this context dropped):
    # key -> Pod, re-materialized if their node (re)appears
    ignored: dict = dc_field(default_factory=dict)
    # a device fold included a pod owning node-side port/volume state the
    # fold cannot reproduce -> the context must rebuild at next dispatch
    tainted: bool = False
    # our own device-side folds: key -> node name (assume log entries for
    # these are already reflected in the resident encoding)
    folded: dict[str, str] = dc_field(default_factory=dict)
    # nominee reservations resident on device: key -> (slot, node, prio)
    nom_applied: dict[str, tuple] = dc_field(default_factory=dict)
    nom_free: list[int] = dc_field(default_factory=list)


def fork_patch_state(pstate) -> Optional[CtxPatchState]:
    """CtxPatchState seeded from the encoder's ``_PatchState`` right after a
    full encode (slot maps still agree at that instant). Returns None when
    the encoder has no patch state (nothing encoded yet)."""
    if pstate is None or pstate.N == 0:
        return None
    e0 = pstate.E
    fill = len(pstate.slot_of)
    return CtxPatchState(
        resources=list(pstate.resources), res_index=dict(pstate.res_index),
        node_index=dict(pstate.node_index),
        K=pstate.K, ET=pstate.ET, EAX=pstate.EAX, EAV=pstate.EAV,
        NSB=pstate.NSB, N=pstate.N, V=pstate.V, T=pstate.T, I=pstate.I,
        IMG=pstate.IMG, E=e0, EK=pstate.K,
        slot_of=dict(pstate.slot_of), slot_node=dict(pstate.slot_node),
        slot_req={k: np.array(v) for k, v in pstate.slot_req.items()},
        unpatchable=set(pstate.unpatchable),
        top=e0, fill_host=fill,
        node_free=list(pstate.node_free),
        row_pods=dict(pstate.row_pods),
    )


def sync_resident_widths(cs: CtxPatchState, ct_all) -> CtxPatchState:
    """Align the patch state's POD-SIDE bucket widths with the staged drain
    context's actual arrays. extend_cluster unifies epod/anti-term widths to
    max(cluster, batch); when a batch's label keys or anti terms crossed a
    bucket after the cluster encode, the resident arrays are wider than the
    encoder's post-encode widths — patches compiled at the narrow widths
    would fail to broadcast at apply time (and reject pods the resident
    buckets can in fact hold)."""
    cs.EK = int(ct_all.epod_labels.shape[1])
    cs.ET = int(ct_all.ea_valid.shape[1])
    cs.EAX = int(ct_all.ea_sel.key.shape[2])
    cs.EAV = int(ct_all.ea_sel.vals.shape[3])
    cs.NSB = int(ct_all.ea_ns_mask.shape[2])
    return cs


def fork_meta(meta: SnapshotMeta) -> SnapshotMeta:
    """Context-private copy of the snapshot meta: node patches append names
    the host's cached encoding must never see. node_names is pre-extended to
    the N bucket so any patched row resolves."""
    m = SnapshotMeta(
        keys=meta.keys, values=meta.values, namespaces=meta.namespaces,
        ips=meta.ips, images=meta.images, resources=list(meta.resources),
        node_names=list(meta.node_names), node_index=dict(meta.node_index),
        pod_keys=list(meta.pod_keys), topo_keys=meta.topo_keys,
        generation=meta.generation,
    )
    return m


class _Unfit(Exception):
    """Internal: delta does not fit the resident buckets -> rebuild."""


def entries_all_folded(cs: CtxPatchState, entries: list) -> bool:
    """True when every delta-log entry is an ``assume`` this context already
    folded device-side (``cs.folded``) — i.e. the log contains nothing the
    resident encoding doesn't know. The pipelined scheduler then advances
    its log cursor WITHOUT compiling a patch and, critically, without
    draining the dispatch pipeline first: a compile needs the patch state
    current with every in-flight drain's folds, but a no-op advance does
    not. This is the steady-state gate that lets drain k+1 dispatch while
    drain k still executes (sched/scheduler.py _schedule_drain)."""
    for _seq, op, payload in entries:
        if op != "assume":
            return False
        key, node_name, _pod = payload
        if cs.folded.get(key) != node_name:
            return False
    return True


def entries_fold_safe(cs: CtxPatchState, entries: list,
                      inflight_keys: set) -> bool:
    """True when the delta-log entries can be compiled into a patch WITHOUT
    first draining the dispatch pipeline — the fused-fold gate.

    The patch state's slot/row maps lag the device by exactly the in-flight
    drains' folds (mirrored at resolve). A delta is fold-safe when nothing
    it touches depends on those unmirrored folds:

    - pod-level entries (``assume``/``pod``/``poddel``) must not name a pod
      an in-flight drain is scheduling: its fold slot is unknown until
      resolve, so a delete/rebind could not be addressed;
    - ``nodedel`` is never fold-safe while drains are in flight: the
      retire-or-free decision reads ``row_pods``, which does not yet count
      in-flight folds — a row could be freed (and later reused by a node
      add) while folded pods still reference it;
    - ``full`` always forces the rebuild path (compile would refuse it
      anyway, but the caller should not burn a compile to learn that).

    Node upserts are safe: existing rows rewrite in place, and new rows
    come from ``node_free`` — rows no in-flight fold can reference (folds
    only land on valid winner rows). Slot-cursor collisions are handled
    separately: the caller compiles with ``fold_floor`` set to its
    dispatch-side fill reservation."""
    for _seq, op, payload in entries:
        if op in ("full", "nodedel"):
            return False
        if op == "assume":
            key = payload[0]
        elif op == "pod":
            key = payload.key
        elif op == "poddel":
            key = payload
        elif op == "node":
            continue
        else:
            return False  # unknown op: fail safe
        if key in inflight_keys:
            return False
    return True


def compile_patch(encoder, meta: SnapshotMeta, cs: CtxPatchState,
                  entries: list, nom_target: dict,
                  nom_bucket: int, fold_floor: int = 0) -> Optional[dict]:
    """Delta-log entries + nominee target set -> numpy scatter arrays for
    apply_ctx_patch, updating ``cs``/``meta`` bookkeeping in the same pass.

    ``entries``: [(seq, op, payload)] in log order with op in
    {"assume", "pod", "poddel", "node", "nodedel", "full"}.
    ``nom_target``: pod_key -> (node_name, priority, Pod) — the COMPLETE
    desired reservation set; the diff against ``cs.nom_applied`` is patched.
    ``fold_floor``: lowest slot the patch allocator may descend to — the
    fused-fold path passes the scheduler's dispatch-side fill reservation
    (``fill_bound``), which is ahead of ``fill_host`` by exactly the
    in-flight drains' pods, so a patch compiled without draining the
    pipeline can never hand out a slot an unresolved fold will take.

    Returns None when any delta does not fit (caller rebuilds; ``cs`` is
    then discarded, so no rollback is attempted)."""
    try:
        return _compile(encoder, meta, cs, entries, nom_target, nom_bucket,
                        fold_floor)
    except _Unfit:
        return None


def _compile(encoder, meta, cs, entries, nom_target, nom_bucket,
             fold_floor=0):
    R = len(cs.resources)
    # final-value accumulators
    pod_writes: dict[int, Optional[tuple]] = {}
    node_writes: dict[int, Optional[tuple]] = {}
    nom_writes: dict[int, Optional[tuple]] = {}
    req_delta = np.zeros((cs.N, R), np.int32)

    def _retire_check(row: int):
        if row in cs.node_retired and cs.row_pods.get(row, 0) == 0:
            cs.node_retired.discard(row)
            cs.node_free.append(row)

    def _vec(v):
        # slot_req stores either the vector or the Pod itself (resolve-time
        # folds defer the compute: most pods are never deleted/rebound)
        if isinstance(v, np.ndarray):
            return v
        return encoder._request_vector(v, cs.resources)

    def _drop_pod(key: str):
        if key in cs.unpatchable:
            # the pod owns node-side port/volume state a slot clear cannot
            # undo (the host patch path refuses these too)
            raise _Unfit
        slot = cs.slot_of.pop(key, None)
        cs.folded.pop(key, None)
        cs.ignored.pop(key, None)
        if slot is None:
            return
        row = cs.slot_node.pop(key)
        req_delta[row] -= _vec(cs.slot_req.pop(key))
        cs.row_pods[row] = cs.row_pods.get(row, 1) - 1
        _retire_check(row)
        pod_writes[slot] = None  # slot leaks by design (see CtxPatchState)

    def _upsert_pod(p: Pod):
        key = p.key
        if key in cs.unpatchable:
            raise _Unfit
        if p.spec.volumes or p.host_ports():
            raise _Unfit  # owns node-side port/volume state
        reqs = encoder._effective_requests(p)
        if any(r not in cs.res_index for r in reqs):
            raise _Unfit
        ns_id = encoder.namespaces.intern(p.metadata.namespace)
        if ns_id >= cs.NSB:
            raise _Unfit  # candidate-pod ns indexes [*,NSB] term masks
        label_ids = encoder._label_ids(p.metadata.labels)
        if any(kid >= cs.EK for kid in label_ids):
            raise _Unfit
        aff = p.spec.affinity
        pan = aff.pod_anti_affinity if aff else None
        terms = []
        for t in (pan.required if pan else []):
            eff = affinity_term_selector(t, p.metadata.labels)
            valid, exprs = encoder._compile_selector(eff)
            ns_set = resolve_term_namespaces(
                t, p.metadata.namespace, encoder._namespace_labels)
            ns_ids = (None if ns_set is None else
                      tuple(encoder.namespaces.intern(n)
                            for n in sorted(ns_set)))
            topo = encoder.keys.intern(t.topology_key)
            if topo not in meta.topo_keys:
                raise _Unfit  # topo_keys is a STATIC drain arg
            terms.append((topo, valid, exprs, ns_ids))
        if (len(terms) > cs.ET
                or any(len(ex) > cs.EAX for (_, _, ex, _) in terms)
                or any(len(v) > cs.EAV for (_, _, ex, _) in terms
                       for (_, _, v, _) in ex)
                or any(nid >= cs.NSB for (_, _, _, ns) in terms
                       if ns is not None for nid in ns)):
            raise _Unfit
        ni = cs.node_index.get(p.spec.node_name, -1)
        had_slot = key in cs.slot_of
        if had_slot:
            # remove the old incarnation's contribution, keep the slot
            slot = cs.slot_of[key]
            old_row = cs.slot_node[key]
            req_delta[old_row] -= _vec(cs.slot_req[key])
            cs.row_pods[old_row] = cs.row_pods.get(old_row, 1) - 1
            _retire_check(old_row)
        if ni < 0:
            # bound to a node this context dropped: invisible (parked in
            # ``ignored``) until the node (re)appears — _upsert_node
            # re-materializes it then
            if had_slot:
                pod_writes[cs.slot_of.pop(key)] = None
                cs.slot_node.pop(key, None)
                cs.slot_req.pop(key, None)
            cs.ignored[key] = p
            cs.folded.pop(key, None)
            return
        if not had_slot:
            if cs.top <= max(cs.fill_host, fold_floor):
                raise _Unfit  # patch cursor met the fold watermark
            cs.top -= 1
            slot = cs.top
            cs.slot_of[key] = slot
        vec = encoder._request_vector(p, cs.resources)
        req_delta[ni] += vec
        cs.slot_node[key] = ni
        cs.slot_req[key] = vec
        cs.row_pods[ni] = cs.row_pods.get(ni, 0) + 1
        cs.ignored.pop(key, None)
        pod_writes[slot] = (ni, ns_id, label_ids, terms)

    def _upsert_node(n: Node):
        name = n.metadata.name
        alloc = dict(n.allocatable_canonical())
        if encoder._dra is not None:
            alloc.update(encoder._dra.node_capacity(name))
        if any(r not in cs.res_index for r in alloc):
            raise _Unfit  # new resource kind widens R
        label_ids = encoder._label_ids(n.metadata.labels,
                                       {NODE_NAME_LABEL: name})
        if any(kid >= cs.K for kid in label_ids):
            raise _Unfit
        if any(vid >= cs.V for vid in label_ids.values()):
            raise _Unfit  # node label values index label_value_num[V]
        if len(n.spec.taints) > cs.T:
            raise _Unfit
        if len(n.status.images) > cs.I:
            raise _Unfit
        img_ids = []
        for img in n.status.images:
            if not img.names:
                continue
            iid = encoder._intern_image(img.names[0], img.size_bytes)
            if iid >= cs.IMG:
                raise _Unfit  # image_sizes bucket overflow
            img_ids.append(iid)
        ni = cs.node_index.get(name)
        reset = False
        if ni is None:
            if not cs.node_free:
                raise _Unfit
            ni = cs.node_free.pop(0)
            cs.node_index[name] = ni
            meta.node_index[name] = ni
            while len(meta.node_names) <= ni:
                meta.node_names.append("")
            meta.node_names[ni] = name
            reset = True
            req_delta[ni] = 0  # cancel pre-reset contributions on this row
            # pods that were parked because this node was unknown (informer
            # delivered them first, or the node flapped) become visible now
            parked = [q for q in cs.ignored.values()
                      if q.spec.node_name == name]
        alloc_row = np.zeros(R, np.int32)
        for r, amt in alloc.items():
            alloc_row[cs.res_index[r]] = min(
                scale_allocatable(r, amt), UNLIMITED)
        if "pods" not in alloc:
            alloc_row[cs.res_index["pods"]] = UNLIMITED
        taints = [(encoder.keys.intern(t.key),
                   encoder.values.intern(t.value),
                   EFFECTC.get(t.effect, 0)) for t in n.spec.taints]
        if any(vid >= cs.V for (_, vid, _) in taints):
            raise _Unfit  # values table crossed the V bucket
        from kubernetes_tpu.sched.volumebinding import node_attach_limit
        lim = node_attach_limit(n.status.allocatable)
        node_writes[ni] = (alloc_row, bool(n.spec.unschedulable), label_ids,
                           taints, img_ids,
                           np.int32(lim if lim >= 0 else UNLIMITED), reset)
        if reset:
            for q in parked:
                _upsert_pod(q)

    def _delete_node(name: str):
        ni = cs.node_index.pop(name, None)
        meta.node_index.pop(name, None)
        if ni is None:
            return
        node_writes[ni] = None
        if cs.row_pods.get(ni, 0) == 0:
            cs.node_free.append(ni)
        else:
            cs.node_retired.add(ni)

    for _seq, op, payload in entries:
        if op == "full":
            raise _Unfit
        if op == "assume":
            key, node_name, pod = payload
            if cs.folded.get(key) == node_name:
                continue  # our own device-side fold, already resident
            _upsert_pod(pod)
        elif op == "pod":
            _upsert_pod(payload)
        elif op == "poddel":
            _drop_pod(payload)
        elif op == "node":
            _upsert_node(payload)
        elif op == "nodedel":
            _delete_node(payload)
        else:
            raise _Unfit  # unknown op: fail safe

    # ---- nominee reservation diff ---------------------------------------
    if not cs.nom_free and not cs.nom_applied:
        cs.nom_free = list(range(nom_bucket))
    for key in [k for k in cs.nom_applied if k not in nom_target]:
        slot, _n, _p = cs.nom_applied.pop(key)
        nom_writes[slot] = None
        cs.nom_free.append(slot)
    for key, (node_name, prio, pod) in nom_target.items():
        prev = cs.nom_applied.get(key)
        ni = cs.node_index.get(node_name, -1)
        if prev is not None:
            if prev[1] == node_name and prev[2] == prio and ni >= 0:
                continue
            slot = prev[0]
            cs.nom_applied.pop(key)
            nom_writes[slot] = None
            cs.nom_free.append(slot)
        if ni < 0:
            continue  # nominated node vanished: reservation is moot
        if not cs.nom_free:
            raise _Unfit
        slot = cs.nom_free.pop()
        vec = encoder._request_vector(pod, cs.resources)
        nom_writes[slot] = (ni, np.int32(prio), vec)
        cs.nom_applied[key] = (slot, node_name, prio)

    if len(encoder.values) > cs.V:
        raise _Unfit  # label_value_num bucket overflow

    # ---- materialize static-shape arrays --------------------------------
    MP = next_bucket(len(pod_writes), minimum=_MIN_PODS)
    MN = next_bucket(len(node_writes), minimum=_MIN_NODES)
    MM = next_bucket(len(nom_writes), minimum=_MIN_NOMS)
    patch = {
        "pod_slot": np.full(MP, -1, np.int32),
        "pod_node": np.full(MP, -1, np.int32),
        "pod_ns": np.full(MP, -1, np.int32),
        "pod_labels": np.full((MP, cs.EK), -1, np.int32),
        "pod_valid": np.zeros(MP, bool),
        "ea_topo": np.full((MP, cs.ET), -1, np.int32),
        "ea_valid": np.zeros((MP, cs.ET), bool),
        "ea_ns_explicit": np.zeros((MP, cs.ET), bool),
        "ea_ns_mask": np.zeros((MP, cs.ET, cs.NSB), bool),
        "node_row": np.full(MN, -1, np.int32),
        "n_alloc": np.zeros((MN, R), np.int32),
        "n_valid": np.zeros(MN, bool),
        "n_unsched": np.zeros(MN, bool),
        "n_labels": np.full((MN, cs.K), -1, np.int32),
        "n_taint_key": np.full((MN, cs.T), -1, np.int32),
        "n_taint_val": np.full((MN, cs.T), -1, np.int32),
        "n_taint_effect": np.full((MN, cs.T), -1, np.int32),
        "n_taint_valid": np.zeros((MN, cs.T), bool),
        "n_images": np.full((MN, cs.I), -1, np.int32),
        "n_attach_limit": np.full(MN, UNLIMITED, np.int32),
        "n_reset": np.zeros(MN, bool),
        "nom_slot": np.full(MM, -1, np.int32),
        "nom_node": np.full(MM, -1, np.int32),
        "nom_prio": np.zeros(MM, np.int32),
        "nom_req": np.zeros((MM, R), np.int32),
        "nom_valid": np.zeros(MM, bool),
        "req_delta": req_delta,
    }
    ea = _selset_arrays((MP, cs.ET), cs.EAX, cs.EAV)
    for i, (slot, w) in enumerate(sorted(pod_writes.items())):
        patch["pod_slot"][i] = slot
        if w is None:
            continue  # all-invalid row = clear
        ni, ns_id, label_ids, terms = w
        patch["pod_node"][i] = ni
        patch["pod_ns"][i] = ns_id
        for kid, vid in label_ids.items():
            patch["pod_labels"][i, kid] = vid
        patch["pod_valid"][i] = True
        for t_idx, (topo, valid, exprs, ns_ids) in enumerate(terms):
            patch["ea_topo"][i, t_idx] = topo
            patch["ea_valid"][i, t_idx] = True
            _selset_fill(ea, (i, t_idx), valid, exprs)
            if ns_ids is not None:
                patch["ea_ns_explicit"][i, t_idx] = True
                for nid in ns_ids:
                    patch["ea_ns_mask"][i, t_idx, nid] = True
    for f, arr in ea.items():
        patch[f"ea_sel_{f}"] = arr
    for i, (row, w) in enumerate(sorted(node_writes.items())):
        patch["node_row"][i] = row
        if w is None:
            continue
        alloc_row, unsched, label_ids, taints, img_ids, lim, reset = w
        patch["n_alloc"][i] = alloc_row
        patch["n_valid"][i] = True
        patch["n_unsched"][i] = unsched
        for kid, vid in label_ids.items():
            patch["n_labels"][i, kid] = vid
        for t_idx, (kid, vid, eff) in enumerate(taints):
            patch["n_taint_key"][i, t_idx] = kid
            patch["n_taint_val"][i, t_idx] = vid
            patch["n_taint_effect"][i, t_idx] = eff
            patch["n_taint_valid"][i, t_idx] = True
        for im_idx, iid in enumerate(img_ids):
            patch["n_images"][i, im_idx] = iid
        patch["n_attach_limit"][i] = lim
        patch["n_reset"][i] = reset
    for i, (slot, w) in enumerate(sorted(nom_writes.items())):
        patch["nom_slot"][i] = slot
        if w is None:
            continue
        ni, prio, vec = w
        patch["nom_node"][i] = ni
        patch["nom_prio"][i] = prio
        patch["nom_req"][i] = vec
        patch["nom_valid"][i] = True
    # label-value numeric table: values interned since the encode extend it
    # (a [V] float32 — KBs; always shipped rather than tracking dirtiness)
    lvn = np.full(cs.V, np.nan, np.float32)
    nums = encoder.values.numeric_values()
    lvn[:len(nums)] = np.asarray(nums, np.float32)
    patch["label_value_num"] = lvn
    return patch
