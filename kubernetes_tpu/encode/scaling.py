"""Per-resource integer scaling shared by the tensor path AND the oracle.

Resource amounts must fit int32 tensors exactly (float32 loses integers above
2^24, so raw bytes are out). Each resource gets a canonical tensor unit:

  cpu                milli-cores (already canonical, scale 1)
  memory / storage   Mi (2^20 bytes)  -> int32 caps at 2 PiB per node
  hugepages-*        Mi
  pods / extended    count (scale 1)

Requests round UP and allocatable rounds DOWN, so scaling never admits a pod
the byte-exact reference would reject. The oracle (sched/oracle.py) uses these
same scaled units — feasibility parity with the tensor path is therefore exact,
and divergence from the byte-exact reference is bounded to <1Mi per resource in
the conservative direction.
"""

from __future__ import annotations

MI = 1 << 20

_MI_SCALED_PREFIXES = ("hugepages-",)
_MI_SCALED = {"memory", "ephemeral-storage", "storage"}

# Nodes in the reference always publish a "pods" allocatable (default 110).
# Test fixtures often omit it; treat absence as unlimited.
UNLIMITED = (1 << 31) - 1


def resource_scale(resource: str) -> int:
    if resource in _MI_SCALED or resource.startswith(_MI_SCALED_PREFIXES):
        return MI
    return 1


def scale_request(resource: str, canonical_amount: int) -> int:
    """Canonical (milli/bytes/count) -> tensor units, rounding up."""
    s = resource_scale(resource)
    return -(-int(canonical_amount) // s)


def scale_allocatable(resource: str, canonical_amount: int) -> int:
    """Canonical -> tensor units, rounding down (conservative)."""
    return int(canonical_amount) // resource_scale(resource)
