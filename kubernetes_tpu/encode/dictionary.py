"""String interning tables — the bridge from k8s's stringly-typed objects to
dense integer tensors.

The reference matches label strings at scheduling time (labels.Selector over
map[string]string). The TPU path cannot; instead every label key, label value,
namespace, image name, etc. is interned once at encode time and all tensor
comparisons are integer equality. ``-1`` is the universal "absent" id.
"""

from __future__ import annotations

import math


class StringTable:
    """Monotone intern table: str -> dense int id (0-based); -1 = absent."""

    def __init__(self, initial: list[str] | None = None):
        self._ids: dict[str, int] = {}
        self._strs: list[str] = []
        for s in initial or []:
            self.intern(s)

    def intern(self, s: str) -> int:
        i = self._ids.get(s)
        if i is None:
            i = len(self._strs)
            self._ids[s] = i
            self._strs.append(s)
        return i

    def get(self, s: str) -> int:
        """Lookup without growing; -1 if unknown."""
        return self._ids.get(s, -1)

    def lookup(self, i: int) -> str:
        return self._strs[i]

    def __len__(self) -> int:
        return len(self._strs)

    def __contains__(self, s: str) -> bool:
        return s in self._ids

    def strings(self) -> list[str]:
        return list(self._strs)

    def numeric_values(self) -> list[float]:
        """Integer-parse of each interned string (labels Gt/Lt compare ints);
        NaN for non-numeric values, which makes the comparison false."""
        out = []
        for s in self._strs:
            try:
                out.append(float(int(s)))
            except (TypeError, ValueError):
                out.append(math.nan)
        return out


def next_bucket(n: int, minimum: int = 0) -> int:
    """Round a dimension up to the next power of two (static-shape bucketing:
    limits XLA recompiles as the cluster grows). 0 stays 0 — empty reductions
    are valid and free."""
    n = max(n, minimum)
    if n <= 0:
        return 0
    return 1 << (n - 1).bit_length()
