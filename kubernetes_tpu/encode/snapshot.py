"""Snapshot encoder: cluster objects -> bucketed static-shape tensors.

This is the TPU analog of the scheduler cache snapshot
(``pkg/scheduler/internal/cache/snapshot.go`` — immutable per-cycle view). The
Go scheduler hands each plugin a ``*NodeInfo``; we hand the jitted scheduling
step two pytrees:

  ClusterTensors  node-side state: allocatable/requested [N,R], labels [N,K],
                  taints, used host-ports, images, plus existing-pods tensors
                  [E,...] for relational plugins (spread / inter-pod affinity).
  PodBatch        pod-side state for the P pods being scheduled this step:
                  requests [P,R], tolerations, node-selector & affinity terms
                  compiled to int-set tables, spread constraints, host-ports.

All strings are interned (encode/dictionary.py); all comparisons downstream
are integer equality. All dims are bucketed to powers of two so XLA recompiles
only when the cluster crosses a bucket boundary, not on every churn.

Design notes:
- Node names are injected as a pseudo-label ``metadata.name`` so matchFields
  terms compile through the same expression machinery as matchExpressions.
- Topology domains need no dictionary: for a topology key k, two nodes are in
  the same domain iff ``node_labels[:, k]`` agree; domain aggregation becomes
  one-hot matmuls on the MXU (see ops/topology.py).
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field as dc_field
from typing import Any, Optional

import numpy as np
from flax import struct

from kubernetes_tpu.api.types import (
    EFFECT_NO_EXECUTE,
    EFFECT_NO_SCHEDULE,
    EFFECT_PREFER_NO_SCHEDULE,
    NODE_INCLUSION_HONOR,
    NODE_INCLUSION_IGNORE,
    OP_DOES_NOT_EXIST,
    OP_EXISTS,
    OP_GT,
    OP_IN,
    OP_LT,
    OP_NOT_IN,
    TOL_OP_EXISTS,
    LabelSelector,
    Node,
    NodeSelectorTerm,
    Pod,
    Requirement,
)
from kubernetes_tpu.encode.dictionary import StringTable, next_bucket
from kubernetes_tpu.encode.scaling import UNLIMITED, scale_allocatable, scale_request
from kubernetes_tpu.encode.termprep import (
    affinity_term_selector,
    resolve_term_namespaces,
    spread_selector,
)

# --- integer op/effect codes used inside tensors -------------------------------

OPC = {OP_IN: 0, OP_NOT_IN: 1, OP_EXISTS: 2, OP_DOES_NOT_EXIST: 3, OP_GT: 4, OP_LT: 5}
EFFECTC = {EFFECT_NO_SCHEDULE: 0, EFFECT_PREFER_NO_SCHEDULE: 1, EFFECT_NO_EXECUTE: 2}
TOLOPC_EQUAL, TOLOPC_EXISTS = 0, 1
PROTOC = {"TCP": 0, "UDP": 1, "SCTP": 2}
NODE_NAME_LABEL = "metadata.name"
WILDCARD_IP = "0.0.0.0"
# Taint the NodeUnschedulable plugin synthesizes for .spec.unschedulable
# (reference: nodeunschedulable/node_unschedulable.go). Pre-interned so its
# key id is the Python-level constant UNSCHED_TAINT_KEY_ID.
UNSCHED_TAINT_KEY = "node.kubernetes.io/unschedulable"
# Fleet tenancy plane (sched/fleet.py): the fleet runner stamps every
# ingested pod/node/namespace with this label, and the label columns
# node_labels[:, TENANT_KEY_ID] / pod_labels[:, TENANT_KEY_ID] ARE the
# tenant_of_node / tenant_of_pod planes — no new tensor field, so churn
# patches, sharding specs, overlays and the staging arena all carry
# tenancy for free. Pre-interned so the id is a Python constant and the
# first tenant-labelled object can never cross a key bucket mid-run.
# Absent label = -1 on both sides, and -1 == -1 passes, so single-tenant
# clusters are bit-identical to the pre-fleet behavior.
TENANT_LABEL = "kubernetes-tpu.io/tenant"
# ICI-torus coordinate plane (topology/): nodes advertise their position
# on the wrap-around mesh via these labels, and — same trick as tenancy —
# the label COLUMNS node_labels[:, TOPO_*_KEY_ID] combined with the
# existing label_value_num numeric-parse plane ARE the coordinate fields.
# No new tensor member, so churn patches, overlays and AOT signatures are
# untouched and the carver's occupancy grid is always current. Pre-interned
# so the ids are Python constants visible to jitted code.
TOPO_X_LABEL = "kubernetes-tpu.io/topology-x"
TOPO_Y_LABEL = "kubernetes-tpu.io/topology-y"
TOPO_Z_LABEL = "kubernetes-tpu.io/topology-z"
NODE_NAME_KEY_ID = 0
UNSCHED_TAINT_KEY_ID = 1
TENANT_KEY_ID = 2
TOPO_X_KEY_ID = 3
TOPO_Y_KEY_ID = 4
TOPO_Z_KEY_ID = 5


def tenant_label_of(labels: Optional[dict]) -> Optional[str]:
    """The ONE way to read an object's tenant id from its labels (None =
    untenanted). Every consumer — oracle filter, victim guard, audit
    invariant, fleet queue — goes through here so the tenancy convention
    can never drift between them."""
    return (labels or {}).get(TENANT_LABEL)
EMPTY_VALUE_ID = 0  # "" pre-interned: empty taint values / tolerations compare to it

# batch-derived bucket dims of a PodBatch, in row-signature order (the
# row-pack cache keys on (resources, K, NSB) + these widths)
_ROW_DIMS = ("TREQ", "TPREF", "VT", "VG", "VB", "X", "VV", "S", "TOL",
             "PP", "CI", "AT", "BT", "CT", "SC", "AX", "AV")


class TermSet(struct.PyTreeNode):
    """Compiled node-selector terms: OR over terms, AND over exprs within a term.

    Shapes: key/op/num/expr_valid [P,T,X]; vals [P,T,X,V]; term_valid [P,T];
    weight [P,T] (1.0 for required terms); has_any [P].
    """

    key: Any
    op: Any
    vals: Any
    num: Any
    expr_valid: Any
    term_valid: Any
    weight: Any
    has_any: Any


class SelectorSet(struct.PyTreeNode):
    """Compiled label selectors (AND of exprs), e.g. pod-affinity term selectors
    or spread-constraint selectors. Shapes: key/op/expr_valid [..., X];
    vals [..., X, V]; valid [...] marks real (non-pad) selectors.
    A valid selector with zero exprs matches everything (empty selector);
    invalid (pad) selectors match nothing.
    """

    key: Any
    op: Any
    vals: Any
    expr_valid: Any
    valid: Any


def _selset_arrays(shape_prefix: tuple[int, ...], AX: int, AV: int) -> dict:
    return dict(
        key=np.full(shape_prefix + (AX,), -1, np.int32),
        op=np.zeros(shape_prefix + (AX,), np.int32),
        vals=np.full(shape_prefix + (AX, AV), -1, np.int32),
        expr_valid=np.zeros(shape_prefix + (AX,), bool),
        valid=np.zeros(shape_prefix, bool),
    )


def _selset_fill(arrs: dict, idx: tuple[int, ...], valid: bool, exprs: list):
    arrs["valid"][idx] = valid
    for x_idx, (kid, opc, vals, _num) in enumerate(exprs):
        arrs["key"][idx + (x_idx,)] = kid
        arrs["op"][idx + (x_idx,)] = opc
        arrs["expr_valid"][idx + (x_idx,)] = True
        for v_idx, v in enumerate(vals):
            arrs["vals"][idx + (x_idx, v_idx)] = v


class ClusterTensors(struct.PyTreeNode):
    allocatable: Any      # [N,R] int32 (scaled units; missing "pods" -> UNLIMITED)
    requested: Any        # [N,R] int32
    node_valid: Any       # [N] bool
    unschedulable: Any    # [N] bool
    node_labels: Any      # [N,K] int32 value-id, -1 absent
    label_value_num: Any  # [V] float32 integer-parse of value strings (NaN if not)
    taint_key: Any        # [N,T] int32
    taint_val: Any        # [N,T] int32
    taint_effect: Any     # [N,T] int32
    taint_valid: Any      # [N,T] bool
    port_proto: Any       # [N,PRT] int32
    port_port: Any        # [N,PRT] int32
    port_ip: Any          # [N,PRT] int32 (0 = wildcard 0.0.0.0)
    port_valid: Any       # [N,PRT] bool
    node_images: Any      # [N,I] int32 image-id, -1 pad
    image_sizes: Any      # [IMG] float32 bytes
    epod_node: Any        # [E] int32 node index of existing pod
    epod_ns: Any          # [E] int32 namespace id
    epod_labels: Any      # [E,K] int32
    epod_valid: Any       # [E] bool
    # existing pods' REQUIRED anti-affinity terms (symmetry veto)
    ea_sel: "SelectorSet"  # [E,ET,...]
    ea_topo: Any           # [E,ET] int32
    ea_valid: Any          # [E,ET] bool
    # terms with explicit namespaces/namespaceSelector: resolved ns-id mask
    # (False rows = "owning pod's own namespace" semantics)
    ea_ns_explicit: Any    # [E,ET] bool
    ea_ns_mask: Any        # [E,ET,NSB] bool over interned namespace ids
    # volumes (VolumeRestrictions / NodeVolumeLimits node side)
    used_rwo: Any          # [N,VN] int32 pv-name id of node-exclusive PVs in use
    used_rwo_valid: Any    # [N,VN] bool
    attach_used: Any       # [N] int32 attachable volumes currently on node
    attach_limit: Any      # [N] int32 (UNLIMITED if node reports no limit)
    # nominated-but-unbound pods (preemption nominees): their requests are
    # reserved on nom_node against pods of LOWER priority
    # (RunFilterPluginsWithNominatedPods — schedule_one.go)
    nom_node: Any          # [M] int32 node index
    nom_prio: Any          # [M] int32
    nom_req: Any           # [M,R] int32
    nom_valid: Any         # [M] bool


class PodBatch(struct.PyTreeNode):
    requests: Any      # [P,R] int32
    pod_valid: Any     # [P] bool
    priority: Any      # [P] int32
    forced_node: Any   # [P] int32: -1 none, -2 named node unknown
    pod_ns: Any        # [P] int32
    pod_labels: Any    # [P,K] int32
    tol_key: Any       # [P,TOL] int32 (-1 = empty key -> matches all keys)
    tol_op: Any        # [P,TOL] int32
    tol_val: Any       # [P,TOL] int32
    tol_effect: Any    # [P,TOL] int32 (-1 = all effects)
    tol_valid: Any     # [P,TOL] bool
    sel_key: Any       # [P,S] int32 nodeSelector (AND of equality)
    sel_val: Any       # [P,S] int32
    sel_valid: Any     # [P,S] bool
    req_terms: TermSet   # required node affinity (+ matchFields)
    pref_terms: TermSet  # preferred node affinity, weight per term
    port_proto: Any    # [P,PP] int32
    port_port: Any     # [P,PP] int32
    port_ip: Any       # [P,PP] int32
    port_valid: Any    # [P,PP] bool
    pod_images: Any    # [P,CI] int32
    image_bytes: Any   # [P] float32 total bytes of pod's images (ImageLocality cap)
    # --- relational terms (spread / inter-pod affinity), see ops/topology.py ---
    aff_sel: SelectorSet    # [P,AT,...] required pod-affinity selectors
    aff_topo: Any           # [P,AT] int32 topology key-id
    aff_valid: Any          # [P,AT] bool
    aff_ns_explicit: Any    # [P,AT] bool: term has explicit namespaces
    aff_ns_mask: Any        # [P,AT,NSB] bool: resolved namespace-id set
    anti_sel: SelectorSet   # [P,BT,...] required anti-affinity selectors
    anti_topo: Any          # [P,BT] int32
    anti_valid: Any         # [P,BT] bool
    anti_ns_explicit: Any   # [P,BT] bool
    anti_ns_mask: Any       # [P,BT,NSB] bool
    paff_sel: SelectorSet   # [P,CT,...] preferred pod-affinity selectors
    paff_topo: Any          # [P,CT] int32
    paff_weight: Any        # [P,CT] float32 (negative for preferred anti-affinity)
    paff_valid: Any         # [P,CT] bool
    paff_ns_explicit: Any   # [P,CT] bool
    paff_ns_mask: Any       # [P,CT,NSB] bool
    sc_sel: SelectorSet     # [P,SC,...] spread-constraint selectors
    sc_topo: Any            # [P,SC] int32
    sc_maxskew: Any         # [P,SC] int32
    sc_hard: Any            # [P,SC] bool (DoNotSchedule)
    sc_valid: Any           # [P,SC] bool
    sc_min_domains: Any     # [P,SC] int32 (0 = unset)
    sc_honor_affinity: Any  # [P,SC] bool: nodeAffinityPolicy == Honor
    sc_honor_taints: Any    # [P,SC] bool: nodeTaintsPolicy == Honor
    # volumes (VolumeBinding/VolumeZone as grouped node-selector terms:
    # OR within a group = any candidate PV; AND across groups = every PVC)
    vol_terms: TermSet      # [P,VT,...]
    vol_group: Any          # [P,VT] int32 group id of each term (-1 pad)
    vol_group_valid: Any    # [P,VG] bool real groups (a group with no terms
    #                         is unsatisfiable: valid here, no matching term)
    rwo_pv: Any             # [P,VB] int32 node-exclusive pv ids the pod mounts
    rwo_valid: Any          # [P,VB] bool
    attach_req: Any         # [P] int32 attachable volumes the pod adds


@dataclass
class _PatchState:
    """Book-keeping from the last full encode enabling in-place pod deltas
    (the analog of ``Cache.UpdateSnapshot``'s generation-counter incremental
    path — pkg/scheduler/internal/cache/cache.go): which existing-pod slot
    each bound pod occupies, free slots, and the bucket sizes that bound what
    a patch may grow."""

    generation: int
    resources: list[str]
    res_index: dict[str, int]
    node_index: dict[str, int]
    # bucket sizes bounding what a patch may add
    K: int
    ET: int
    EAX: int
    EAV: int
    NSB: int
    slot_of: dict[str, int] = dc_field(default_factory=dict)
    free: list[int] = dc_field(default_factory=list)
    slot_node: dict[str, int] = dc_field(default_factory=dict)
    slot_req: dict[str, Any] = dc_field(default_factory=dict)
    # pods whose encode contributed node port/volume state — removing or
    # replacing one requires a full re-encode
    unpatchable: set = dc_field(default_factory=set)
    # ---- node-side patch bookkeeping (drain-context churn patches:
    # encode/patch.py). Bucket widths of the node-axis arrays plus the free
    # node rows the N bucket left (node_valid False), so node ADD/REMOVE can
    # patch the encoding instead of forcing a full rebuild under churn.
    N: int = 0
    V: int = 0
    T: int = 0
    I: int = 0
    IMG: int = 0  # filled prefix of image_sizes: a NEW image id needs its
    #               size shipped, which patches don't do -> rebuild
    PRT: int = 0
    VN: int = 0
    E: int = 0
    node_free: list[int] = dc_field(default_factory=list)  # ascending rows
    row_pods: dict[int, int] = dc_field(default_factory=dict)  # row -> #pods


@dataclass
class SnapshotMeta:
    """Host-side static metadata accompanying the tensors (NOT a pytree)."""

    keys: StringTable
    values: StringTable
    namespaces: StringTable
    ips: StringTable
    images: StringTable
    resources: list[str] = dc_field(default_factory=list)
    node_names: list[str] = dc_field(default_factory=list)
    node_index: dict[str, int] = dc_field(default_factory=dict)
    pod_keys: list[str] = dc_field(default_factory=list)  # keys of the encoded batch
    topo_keys: tuple[int, ...] = ()  # distinct topology key-ids in play (static)
    generation: int = 0


def _is_device_backed(ct: ClusterTensors) -> bool:
    """True when the encoding's arrays live on device (a drain-context
    resident image) rather than host numpy — the overlay methods route
    these through encode/overlay.py so the image never round-trips."""
    return not isinstance(ct.node_valid, np.ndarray)


def _resource_union(nodes: list[Node], pods: list[Pod]) -> list[str]:
    seen = ["cpu", "memory", "pods"]
    seen_set = set(seen)
    for n in nodes:
        for r in n.status.allocatable:
            if r not in seen_set:
                seen.append(r)
                seen_set.add(r)
    for p in pods:
        for r in p.resource_requests():
            if r not in seen_set:
                seen.append(r)
                seen_set.add(r)
    return seen


class SnapshotEncoder:
    """Persistent encoder: intern tables survive across snapshots so ids are
    stable and incremental re-encoding stays cheap."""

    def __init__(self):
        self.keys = StringTable([NODE_NAME_LABEL, UNSCHED_TAINT_KEY,
                                 TENANT_LABEL, TOPO_X_LABEL, TOPO_Y_LABEL,
                                 TOPO_Z_LABEL])
        self.values = StringTable([""])
        self.namespaces = StringTable(["default"])
        self.ips = StringTable([WILDCARD_IP])
        self.images = StringTable()
        self.pv_names = StringTable()
        self._image_sizes: list[float] = []
        self._cluster_topo_keys: set[int] = set()
        self._volumes = None  # VolumeCatalog | None
        self._dra = None  # sched/dra.DraCatalog | None
        self._namespace_labels: dict[str, dict] = {}
        # does any encoded existing-pod anti term carry a namespaceSelector?
        # (only then does the cluster encoding depend on namespace labels)
        self._cluster_ns_selector_terms = False
        self._rwop_in_use: set = set()
        self._patch: Optional[_PatchState] = None
        self.generation = 0
        # bucket headroom so CHURN patches fit without re-encoding: free
        # node rows for node ADDs, spare label-value ids for the new values
        # they intern (every node interns its own name). 0 = tight buckets
        # (kernels/parity tests); the scheduler cache raises them.
        self.node_headroom = 0
        self.value_headroom = 0
        self.ns_headroom = 0
        # informer-event-time pod compile cache (precompile_pod): key ->
        # [pod object, epoch, compiled record, row sig, row pack]. Hits are
        # validated by OBJECT IDENTITY (informers build a fresh Pod per
        # event, so a new version never aliases a cached one) and by the
        # catalog epoch below — any volume/namespace/DRA catalog change
        # invalidates every record. The row pack is the pod's PRE-FILLED
        # numpy rows at the current bucket signature: encode_pods then
        # assembles the batch with one np.stack per field instead of the
        # per-pod Python fill loop (the 1136 ms encode residual the churn
        # bench showed with the compile cache already hot).
        self._pod_cache: dict[str, list] = {}
        self._pod_cache_max = 65536
        self._pod_epoch = 0
        # Per-tenant catalog epochs: namespace-label churn in one tenant
        # must not invalidate every OTHER tenant's precompiled pod records
        # (a fleet runs K tenants' churn through ONE encoder, and the
        # global epoch made any tenant's namespace update a fleet-wide
        # row-cache wipe). A record's effective epoch is the (global,
        # tenant) pair; volumes/DRA stay global — those catalogs are
        # genuinely shared.
        self._tenant_epochs: dict[Optional[str], int] = {}
        self.pod_cache_hits = 0
        self.pod_cache_misses = 0
        # sticky existing-pod slot bucket (see encode_cluster): E never
        # shrinks, so churn oscillating around a bucket boundary cannot
        # recompile the drain programs at alternating widths
        self._slot_floor = 0
        # sticky batch bucket widths (monotone max across encodes) so row
        # packs prebuilt at informer time keep matching the batch signature;
        # power-of-two buckets only ever grow, exactly like the intern
        # tables, so stickiness costs padding, never correctness
        self._row_widths: dict[str, int] = {}
        self._row_sig: Optional[tuple] = None
        self._row_env: Optional[tuple] = None  # (resources, K, NSB, widths)
        self.pod_rows_stacked = 0  # rows bulk-assembled from prebuilt packs
        self.pod_rows_filled = 0   # rows built by the per-pod fill loop

    def set_volumes(self, catalog) -> None:
        """Attach the PVC/PV/StorageClass catalog consulted by the next
        encode_cluster/encode_pods pair (sched/volumebinding.VolumeCatalog)."""
        self._volumes = catalog
        self._pod_epoch += 1  # precompiled pod records may embed stale state

    def set_namespaces(self, namespace_labels: dict[str, dict],
                       changed_tenants=None) -> None:
        """Attach the namespace-name -> labels snapshot used to resolve
        affinity terms' namespaceSelector (GetNamespaceLabelsSnapshot
        analog).

        ``changed_tenants``: optional iterable of tenant ids (values of the
        ``kubernetes-tpu.io/tenant`` label; None = untenanted) whose
        namespaces this update touched. When given, only those tenants'
        pod-record epochs bump — nsSelector resolution is tenant-scoped
        (encode/termprep.py), so a sibling tenant's records stay valid.
        Omitted/None = conservative global bump (pre-fleet behavior)."""
        self._namespace_labels = dict(namespace_labels or {})
        if changed_tenants is None:
            self._pod_epoch += 1  # term namespace resolution may change
        else:
            for t in changed_tenants:
                self._tenant_epochs[t] = self._tenant_epochs.get(t, 0) + 1

    def _epoch_for(self, p: Pod) -> tuple:
        """The (global, tenant) catalog epoch a pod's precompiled record is
        valid under — per-tenant so one tenant's namespace churn cannot
        wipe the whole fleet's row cache. Keyed by the POD'S NAMESPACE'S
        tenant (the same identity ``set_namespaces`` bumps and termprep's
        nsSelector scoping resolves against); the pod's own label is only
        the fallback for namespaces absent from the snapshot."""
        t = tenant_label_of(self._namespace_labels.get(p.metadata.namespace))
        if t is None:
            t = tenant_label_of(p.metadata.labels)
        # the tenant id itself is part of the key: a namespace RELABELLED
        # to another tenant must miss even when the two tenants' counters
        # happen to be numerically equal
        return (self._pod_epoch, t, self._tenant_epochs.get(t, 0))

    def set_dra(self, catalog) -> None:
        """Attach the DRA catalog (sched/dra.DraCatalog): device classes
        become synthetic ``dra:<class>`` resources on the shared axis —
        slices extend node allocatable, claim demands extend pod requests."""
        self._dra = catalog
        self._pod_epoch += 1  # precompiled pod records may embed stale state

    @property
    def dra(self):
        """The attached DRA catalog (or None). Background planners sync
        their cold-fallback encoders to the cache encoder's catalogs so a
        resident overlay and its cold baseline gate claims identically."""
        return self._dra

    @property
    def volumes(self):
        """The attached volume catalog (or None); see ``dra``."""
        return self._volumes

    @property
    def cluster_depends_on_namespace_labels(self) -> bool:
        """True when the last cluster encoding resolved a namespaceSelector,
        i.e. namespace-label churn invalidates it (vs. only affecting future
        pod batches, which always read the fresh snapshot)."""
        return self._cluster_ns_selector_terms

    # -- small helpers ------------------------------------------------------

    def _intern_image(self, name: str, size: float = 0.0) -> int:
        i = self.images.intern(name)
        if i == len(self._image_sizes):
            self._image_sizes.append(float(size))
        elif size:
            self._image_sizes[i] = max(self._image_sizes[i], float(size))
        return i

    def _label_ids(self, labels: dict[str, str], extra: dict[str, str] | None = None):
        out = {}
        for k, v in {**labels, **(extra or {})}.items():
            out[self.keys.intern(k)] = self.values.intern(v)
        return out

    # -- cluster side -------------------------------------------------------

    def encode_cluster(self, nodes: list[Node], bound_pods: list[Pod],
                       pending_pods: Optional[list[Pod]] = None,
                       slot_headroom: int = 0,
                       pending_slots: bool = True,
                       ) -> tuple[ClusterTensors, SnapshotMeta]:
        """Encode node-side state. ``bound_pods`` are pods already assigned
        (their requests fold into ``requested`` and they populate the
        existing-pods tensors). ``pending_pods`` only widen the resource axis so
        cluster and batch tensors agree on R. ``slot_headroom``: reserve at
        least this many free existing-pod slots (typically the scheduler's
        total queue depth) so subsequent binds patch incrementally without
        growing the E bucket — keeping tensor shapes, and therefore the
        compiled XLA program, stable across the whole drain.
        ``pending_slots=False`` skips reserving epod slots for pending pods
        (gang_drain appends its own per-batch extension slots; double-
        reserving would widen every relational contraction for nothing)."""
        self.generation += 1
        resources = _resource_union(nodes, bound_pods + list(pending_pods or []))
        if self._dra is not None:
            from kubernetes_tpu.sched.dra import DRA_PREFIX
            for cname in sorted(self._dra.class_names()):
                if DRA_PREFIX + cname not in resources:
                    resources.append(DRA_PREFIX + cname)
        R = len(resources)
        N = next_bucket(len(nodes) + self.node_headroom, minimum=1)

        node_index = {n.metadata.name: i for i, n in enumerate(nodes)}
        # Pre-intern all labels so the key bucket covers everything.
        node_label_ids = [self._label_ids(n.metadata.labels, {NODE_NAME_LABEL: n.metadata.name})
                          for n in nodes]
        epods = [p for p in bound_pods if p.spec.node_name in node_index]
        epod_label_ids = [self._label_ids(p.metadata.labels) for p in epods]

        # existing pods' required anti-affinity terms (symmetry veto) — compile
        # before fixing K so their keys are covered by the bucket. Terms are
        # normalized host-side (encode/termprep.py): matchLabelKeys merged
        # into the selector using the OWNING pod's labels, namespaces +
        # namespaceSelector resolved to interned-id lists (None = own ns).
        self._cluster_ns_selector_terms = False

        def _anti_terms(p: Pod) -> list:
            aff = p.spec.affinity
            pan = aff.pod_anti_affinity if aff else None
            terms = []
            for t in (pan.required if pan else []):
                eff = affinity_term_selector(t, p.metadata.labels)
                valid, exprs = self._compile_selector(eff)
                if t.namespace_selector is not None:
                    self._cluster_ns_selector_terms = True
                ns_set = resolve_term_namespaces(
                    t, p.metadata.namespace, self._namespace_labels)
                ns_ids = (None if ns_set is None else
                          tuple(self.namespaces.intern(n) for n in sorted(ns_set)))
                terms.append((self.keys.intern(t.topology_key), valid, exprs,
                              ns_ids))
            return terms

        ea_terms = [_anti_terms(p) for p in epods]
        self._cluster_topo_keys = {k for ts in ea_terms for (k, _, _, _) in ts}
        # Pre-intern pending pods' labels + anti terms and leave slot headroom
        # so that when they bind, the incremental patch path (apply_pod_deltas)
        # fits them without a full re-encode.
        pend = list(pending_pods or [])
        pend_terms = []
        for p in pend:
            self._label_ids(p.metadata.labels)
            self.namespaces.intern(p.metadata.namespace)
            pend_terms.append(_anti_terms(p))
        for p in epods:
            self.namespaces.intern(p.metadata.namespace)
        K = next_bucket(len(self.keys), minimum=1)
        # namespace-mask width: covers every id interned so far (epods, pend
        # pods, and all resolved term sets), so patches stay in-bucket
        NSB = next_bucket(len(self.namespaces) + self.ns_headroom, minimum=1)

        allocatable = np.zeros((N, R), np.int32)
        requested = np.zeros((N, R), np.int32)
        node_valid = np.zeros(N, bool)
        unschedulable = np.zeros(N, bool)
        node_labels = np.full((N, K), -1, np.int32)
        T = next_bucket(max((len(n.spec.taints) for n in nodes), default=0))
        taint_key = np.full((N, T), -1, np.int32)
        taint_val = np.full((N, T), -1, np.int32)
        taint_effect = np.full((N, T), -1, np.int32)
        taint_valid = np.zeros((N, T), bool)

        ports_per_node: list[list[tuple[str, str, int]]] = [[] for _ in range(N)]
        for p in epods:
            ni = node_index[p.spec.node_name]
            for trip in p.host_ports():
                ports_per_node[ni].append(trip)
        PRT = next_bucket(max((len(x) for x in ports_per_node), default=0))
        port_proto = np.full((N, PRT), -1, np.int32)
        port_port = np.full((N, PRT), -1, np.int32)
        port_ip = np.full((N, PRT), -1, np.int32)
        port_valid = np.zeros((N, PRT), bool)

        I = next_bucket(max((len(n.status.images) for n in nodes), default=0))
        node_images = np.full((N, I), -1, np.int32)

        for i, n in enumerate(nodes):
            node_valid[i] = True
            unschedulable[i] = n.spec.unschedulable
            alloc = dict(n.allocatable_canonical())
            if self._dra is not None:
                alloc.update(self._dra.node_capacity(n.metadata.name))
            for r_idx, r in enumerate(resources):
                if r in alloc:
                    allocatable[i, r_idx] = min(scale_allocatable(r, alloc[r]), UNLIMITED)
                elif r == "pods":
                    allocatable[i, r_idx] = UNLIMITED
            for kid, vid in node_label_ids[i].items():
                node_labels[i, kid] = vid
            for t_idx, t in enumerate(n.spec.taints):
                taint_key[i, t_idx] = self.keys.intern(t.key)
                taint_val[i, t_idx] = self.values.intern(t.value)
                taint_effect[i, t_idx] = EFFECTC.get(t.effect, 0)
                taint_valid[i, t_idx] = True
            for img_idx, img in enumerate(n.status.images):
                if img.names:
                    node_images[i, img_idx] = self._intern_image(img.names[0], img.size_bytes)
            for pt_idx, (ip, proto, port) in enumerate(ports_per_node[i]):
                port_proto[i, pt_idx] = PROTOC.get(proto, 3)
                port_port[i, pt_idx] = port
                port_ip[i, pt_idx] = self.ips.intern(ip)
                port_valid[i, pt_idx] = True

        # Fold bound pods into requested[N,R].
        for p in epods:
            requested[node_index[p.spec.node_name]] += \
                self._request_vector(p, resources)

        # Sticky slot bucket: like the pod-batch row widths, E only ever
        # GROWS across this encoder's lifetime. The bound-pod count under
        # churn naturally oscillates around bucket boundaries, and letting
        # E flap 64<->128 recompiled the drain/gang programs on every
        # capacity rebuild that crossed — the direct enemy of the
        # one-warm-program steady state (FleetChurn gates on 0 XLA
        # compiles). Stickiness costs padded rows, never correctness:
        # every slot past the fill is invalid.
        E = next_bucket(len(epods) + (max(len(pend), slot_headroom)
                                      if pending_slots else slot_headroom),
                        minimum=self._slot_floor)
        self._slot_floor = max(self._slot_floor, E)
        epod_node = np.full(E, -1, np.int32)
        epod_ns = np.full(E, -1, np.int32)
        epod_labels = np.full((E, K), -1, np.int32)
        epod_valid = np.zeros(E, bool)
        for e, p in enumerate(epods):
            epod_node[e] = node_index[p.spec.node_name]
            epod_ns[e] = self.namespaces.intern(p.metadata.namespace)
            for kid, vid in epod_label_ids[e].items():
                epod_labels[e, kid] = vid
            epod_valid[e] = True

        all_terms = ea_terms + pend_terms
        ET = next_bucket(max((len(t) for t in all_terms), default=0))
        EAX = next_bucket(max((len(ex) for ts in all_terms for (_, _, ex, _) in ts), default=0))
        EAV = next_bucket(max((len(v) for ts in all_terms for (_, _, ex, _) in ts
                               for (_, _, v, _) in ex), default=0))
        ea_arrs = _selset_arrays((E, ET), EAX, EAV)
        ea_topo = np.full((E, ET), -1, np.int32)
        ea_valid = np.zeros((E, ET), bool)
        ea_ns_explicit = np.zeros((E, ET), bool)
        ea_ns_mask = np.zeros((E, ET, NSB), bool)
        for e, terms in enumerate(ea_terms):
            for t_idx, (topo, valid, exprs, ns_ids) in enumerate(terms):
                ea_topo[e, t_idx] = topo
                ea_valid[e, t_idx] = True
                _selset_fill(ea_arrs, (e, t_idx), valid, exprs)
                if ns_ids is not None:
                    ea_ns_explicit[e, t_idx] = True
                    for nid in ns_ids:
                        ea_ns_mask[e, t_idx, nid] = True

        # volumes: node-side VolumeRestrictions / NodeVolumeLimits state
        from kubernetes_tpu.sched.volumebinding import (
            cluster_volume_state,
            node_attach_limit,
        )
        per_node_rwo, per_node_attach, self._rwop_in_use = \
            cluster_volume_state(epods, self._volumes)
        VN = next_bucket(max((len(v) for v in per_node_rwo.values()), default=0))
        used_rwo = np.full((N, VN), -1, np.int32)
        used_rwo_valid = np.zeros((N, VN), bool)
        attach_used = np.zeros(N, np.int32)
        attach_limit = np.full(N, UNLIMITED, np.int32)
        for i, n in enumerate(nodes):
            lim = node_attach_limit(n.status.allocatable)
            if lim >= 0:
                attach_limit[i] = lim
            attach_used[i] = per_node_attach.get(n.metadata.name, 0)
            for v_idx, pv in enumerate(per_node_rwo.get(n.metadata.name, [])):
                used_rwo[i, v_idx] = self.pv_names.intern(pv)
                used_rwo_valid[i, v_idx] = True

        V = next_bucket(len(self.values) + self.value_headroom, minimum=1)
        label_value_num = np.full(V, np.nan, np.float32)
        nums = self.values.numeric_values()
        label_value_num[:len(nums)] = np.asarray(nums, np.float32)

        IMG = next_bucket(len(self._image_sizes), minimum=1)
        image_sizes = np.zeros(IMG, np.float32)
        image_sizes[:len(self._image_sizes)] = self._image_sizes

        meta = SnapshotMeta(
            keys=self.keys, values=self.values, namespaces=self.namespaces,
            ips=self.ips, images=self.images, resources=resources,
            node_names=[n.metadata.name for n in nodes], node_index=node_index,
            topo_keys=tuple(sorted(self._cluster_topo_keys)),
            generation=self.generation,
        )
        row_pods: dict[int, int] = {}
        for p in epods:
            ni = node_index[p.spec.node_name]
            row_pods[ni] = row_pods.get(ni, 0) + 1
        self._patch = _PatchState(
            generation=self.generation, resources=resources,
            res_index={r: i for i, r in enumerate(resources)},
            node_index=node_index, K=K, ET=ET, EAX=EAX, EAV=EAV, NSB=NSB,
            slot_of={p.key: e for e, p in enumerate(epods)},
            free=list(range(len(epods), E))[::-1],
            slot_node={p.key: node_index[p.spec.node_name] for p in epods},
            slot_req={p.key: self._request_vector(p, resources) for p in epods},
            unpatchable={p.key for p in epods
                         if p.spec.volumes or p.host_ports()},
            N=N, V=V, T=T, I=I, IMG=len(self._image_sizes),
            PRT=PRT, VN=VN, E=E,
            node_free=list(range(len(nodes), N)),
            row_pods=row_pods,
        )
        ct = ClusterTensors(
            allocatable=allocatable, requested=requested, node_valid=node_valid,
            unschedulable=unschedulable, node_labels=node_labels,
            label_value_num=label_value_num,
            taint_key=taint_key, taint_val=taint_val, taint_effect=taint_effect,
            taint_valid=taint_valid,
            port_proto=port_proto, port_port=port_port, port_ip=port_ip,
            port_valid=port_valid,
            node_images=node_images, image_sizes=image_sizes,
            epod_node=epod_node, epod_ns=epod_ns, epod_labels=epod_labels,
            epod_valid=epod_valid,
            ea_sel=SelectorSet(**ea_arrs), ea_topo=ea_topo, ea_valid=ea_valid,
            ea_ns_explicit=ea_ns_explicit, ea_ns_mask=ea_ns_mask,
            used_rwo=used_rwo, used_rwo_valid=used_rwo_valid,
            attach_used=attach_used, attach_limit=attach_limit,
            nom_node=np.zeros(0, np.int32), nom_prio=np.zeros(0, np.int32),
            nom_req=np.zeros((0, R), np.int32), nom_valid=np.zeros(0, bool),
        )
        return ct, meta

    def with_hypothetical(self, ct: ClusterTensors, meta: "SnapshotMeta",
                          nodes: list[Node],
                          ) -> tuple[ClusterTensors, list[int]]:
        """Overlay K hypothetical nodes onto an encoded snapshot — the
        cluster-autoscaler's "would the pending pods fit on a node from
        group g?" question, asked for every candidate group in ONE tensor
        program instead of K sequential binpacking passes (the reference
        delegates this to simulator.SchedulerBasedPredicateChecker in
        kubernetes/autoscaler).

        The overlay is ephemeral and copy-on-write: node-axis arrays widen
        to the next bucket past N+K and the template rows fill in after the
        existing bucket, so real rows (and the incremental-patch bookkeeping,
        which is NOT touched) keep their indices. Template labels/taints
        intern into the shared tables; node_labels' key axis and the
        label-value-number table widen if a template introduces new ids.
        Template resources outside the encoded resource axis are ignored —
        encode the cluster with the pending pods so R already covers them.

        Returns (overlaid tensors, row index per hypothetical node).

        Handed a DEVICE-RESIDENT encoding (the scheduler's drain-context
        tensors), the overlay stays resident: template planes are built
        host-side at the resident bucket widths and appended with ONE
        jitted concatenate program — no device_get of the cluster image.
        A template that overflows a resident bucket (new label key past K,
        more taints than T, a value past V) falls back to pulling the
        tensors host-side and running the numpy path below — correct,
        just cold (encode/overlay.py's planners decline instead).
        """
        K = len(nodes)
        if K == 0:
            return ct, []
        if _is_device_backed(ct):
            from kubernetes_tpu.encode import overlay
            out = overlay.resident_with_hypothetical(self, ct, meta, nodes)
            if out is not None:
                return out
            import jax
            ct = jax.tree_util.tree_map(np.asarray, ct)
        N = ct.node_valid.shape[0]
        N2 = next_bucket(N + K, minimum=1)
        rows = list(range(N, N + K))

        # intern template state first so every bucket decision sees it
        tmpl_labels = [self._label_ids(n.metadata.labels,
                                       {NODE_NAME_LABEL: n.metadata.name})
                       for n in nodes]
        tmpl_taints = [[(self.keys.intern(t.key), self.values.intern(t.value),
                         EFFECTC.get(t.effect, 0)) for t in n.spec.taints]
                       for n in nodes]

        def _widen(arr, axis, new, fill):
            arr = np.asarray(arr)
            if arr.shape[axis] >= new:
                return np.array(arr)
            pad = [(0, 0)] * arr.ndim
            pad[axis] = (0, new - arr.shape[axis])
            return np.pad(arr, pad, constant_values=fill)

        K2 = max(np.asarray(ct.node_labels).shape[1],
                 next_bucket(len(self.keys), minimum=1))
        T2 = max(np.asarray(ct.taint_key).shape[1],
                 next_bucket(max((len(t) for t in tmpl_taints), default=0)))
        allocatable = _widen(ct.allocatable, 0, N2, 0)
        requested = _widen(ct.requested, 0, N2, 0)
        node_valid = _widen(ct.node_valid, 0, N2, False)
        unschedulable = _widen(ct.unschedulable, 0, N2, False)
        node_labels = _widen(_widen(ct.node_labels, 1, K2, -1), 0, N2, -1)
        taint_key = _widen(_widen(ct.taint_key, 1, T2, -1), 0, N2, -1)
        taint_val = _widen(_widen(ct.taint_val, 1, T2, -1), 0, N2, -1)
        taint_effect = _widen(_widen(ct.taint_effect, 1, T2, -1), 0, N2, -1)
        taint_valid = _widen(_widen(ct.taint_valid, 1, T2, False), 0, N2, False)
        port_proto = _widen(ct.port_proto, 0, N2, -1)
        port_port = _widen(ct.port_port, 0, N2, -1)
        port_ip = _widen(ct.port_ip, 0, N2, -1)
        port_valid = _widen(ct.port_valid, 0, N2, False)
        node_images = _widen(ct.node_images, 0, N2, -1)
        used_rwo = _widen(ct.used_rwo, 0, N2, -1)
        used_rwo_valid = _widen(ct.used_rwo_valid, 0, N2, False)
        attach_used = _widen(ct.attach_used, 0, N2, 0)
        attach_limit = _widen(ct.attach_limit, 0, N2, UNLIMITED)

        from kubernetes_tpu.sched.volumebinding import node_attach_limit
        for k, n in enumerate(nodes):
            i = rows[k]
            node_valid[i] = True
            unschedulable[i] = n.spec.unschedulable
            alloc = n.allocatable_canonical()
            for r_idx, r in enumerate(meta.resources):
                if r in alloc:
                    allocatable[i, r_idx] = min(
                        scale_allocatable(r, alloc[r]), UNLIMITED)
                elif r == "pods":
                    allocatable[i, r_idx] = UNLIMITED
            for kid, vid in tmpl_labels[k].items():
                node_labels[i, kid] = vid
            for t_idx, (tk, tv, te) in enumerate(tmpl_taints[k]):
                taint_key[i, t_idx] = tk
                taint_val[i, t_idx] = tv
                taint_effect[i, t_idx] = te
                taint_valid[i, t_idx] = True
            lim = node_attach_limit(n.status.allocatable)
            if lim >= 0:
                attach_limit[i] = lim

        # label values the templates interned may spill past the V bucket
        V2 = max(np.asarray(ct.label_value_num).shape[0],
                 next_bucket(len(self.values), minimum=1))
        label_value_num = np.full(V2, np.nan, np.float32)
        nums = self.values.numeric_values()
        label_value_num[:len(nums)] = np.asarray(nums, np.float32)

        return ct.replace(
            allocatable=allocatable, requested=requested,
            node_valid=node_valid, unschedulable=unschedulable,
            node_labels=node_labels, label_value_num=label_value_num,
            taint_key=taint_key, taint_val=taint_val,
            taint_effect=taint_effect, taint_valid=taint_valid,
            port_proto=port_proto, port_port=port_port, port_ip=port_ip,
            port_valid=port_valid, node_images=node_images,
            used_rwo=used_rwo, used_rwo_valid=used_rwo_valid,
            attach_used=attach_used, attach_limit=attach_limit,
        ), rows

    def without_pods(self, ct: ClusterTensors, meta: "SnapshotMeta",
                     pod_keys: list[str]) -> Optional[ClusterTensors]:
        """``with_hypothetical`` in reverse: mask bound pods OUT of an
        encoded snapshot — the descheduler's "what does the cluster look
        like after these evictions?" question. The victims' epod rows
        invalidate (their relational footprint — anti-affinity symmetry,
        spread counts — disappears) and their request vectors leave
        ``requested``; everything else is shared with the source encoding.

        Ephemeral and copy-on-write like the other overlays: the
        incremental-patch bookkeeping still considers the pods resident
        (use ``apply_pod_deltas`` for a real delete). Returns None when a
        key is outside the current patch state or carries port/volume node
        state an overlay cannot reconstruct — callers fall back to a full
        re-encode without the victims.

        A DEVICE-RESIDENT encoding stays resident: the subtraction runs as
        one jitted scatter against the live tensors (the planners' "what
        if these evictions happened" view without a device_get).
        """
        st = self._patch
        if st is None or st.generation != meta.generation:
            return None
        if any(k in st.unpatchable for k in pod_keys):
            return None
        if any(k not in st.slot_of for k in pod_keys):
            return None
        if _is_device_backed(ct):
            from kubernetes_tpu.encode import overlay
            return overlay.resident_without_pods(st, ct, pod_keys)
        requested = np.array(ct.requested)
        epod_valid = np.array(ct.epod_valid)
        for k in set(pod_keys):
            requested[st.slot_node[k]] -= st.slot_req[k]
            epod_valid[st.slot_of[k]] = False
        return ct.replace(requested=requested, epod_valid=epod_valid)

    def with_nominated(self, ct: ClusterTensors, meta: "SnapshotMeta",
                       nominated: list, min_m: int = 0) -> ClusterTensors:
        """Overlay nominated-pod reservations onto an encoded snapshot.
        ``nominated``: [(node_name, priority, Pod)]. Cheap (tiny M-bucketed
        arrays), so it applies on every scheduling cycle without touching the
        incremental-patch bookkeeping. ``min_m`` pins the bucket: a
        preemption storm's nominee count varies per cycle, and every new M
        is a fresh gang program compile mid-window."""
        R = ct.nom_req.shape[1]
        entries = [(meta.node_index[n], prio,
                    self._request_vector(p, meta.resources))
                   for (n, prio, p) in nominated if n in meta.node_index]
        M = next_bucket(max(len(entries), min_m), minimum=1) \
            if entries or min_m else 0
        nom_node = np.full(M, -1, np.int32)
        nom_prio = np.zeros(M, np.int32)
        nom_req = np.zeros((M, R), np.int32)
        nom_valid = np.zeros(M, bool)
        for m, (ni, prio, vec) in enumerate(entries):
            nom_node[m] = ni
            nom_prio[m] = prio
            nom_req[m] = vec
            nom_valid[m] = True
        return ct.replace(nom_node=nom_node, nom_prio=nom_prio,
                          nom_req=nom_req, nom_valid=nom_valid)

    # -- incremental pod deltas --------------------------------------------

    def _effective_requests(self, p: Pod) -> dict:
        """resource -> canonical amount, including DRA device demands."""
        reqs = dict(p.resource_requests())
        if self._dra is not None:
            reqs.update(self._dra.pod_demands(p))
        return reqs

    def _request_vector(self, p: Pod, resources: list[str]) -> np.ndarray:
        reqs = self._effective_requests(p)
        vec = np.zeros(len(resources), np.int32)
        for r_idx, r in enumerate(resources):
            if r in reqs:
                vec[r_idx] = scale_request(r, reqs[r])
        return vec

    def apply_pod_deltas(self, ct: ClusterTensors, meta: SnapshotMeta,
                         upserts: list[Pod], deletes: list[str],
                         ) -> Optional[ClusterTensors]:
        """Patch bound-pod deltas into an existing encoding without a full
        re-encode (the reference's incremental ``Cache.UpdateSnapshot``).

        Returns the patched ClusterTensors (copy-on-write on touched arrays),
        or None when a delta doesn't fit the encoded buckets (new label key,
        more anti-affinity terms than reserved, pod with host ports/volumes,
        unknown node, no free slot) — the caller then falls back to a full
        encode_cluster.
        """
        st = self._patch
        if st is None or st.generation != meta.generation:
            return None
        if any(k in st.unpatchable for k in deletes) or \
                any(p.key in st.unpatchable for p in upserts):
            return None

        # ---- validate + compile everything before mutating anything ------
        compiled = []
        for p in upserts:
            if p.spec.volumes or p.host_ports():
                return None          # port/volume node state isn't patchable
            ni = st.node_index.get(p.spec.node_name, -1)
            if ni < 0:
                return None
            reqs = self._effective_requests(p)
            if any(r not in st.res_index for r in reqs):
                return None          # new resource kind widens R
            label_ids = self._label_ids(p.metadata.labels)
            if any(kid >= st.K for kid in label_ids):
                return None          # label key beyond the K bucket
            aff = p.spec.affinity
            pan = aff.pod_anti_affinity if aff else None
            terms = []
            for t in (pan.required if pan else []):
                eff = affinity_term_selector(t, p.metadata.labels)
                valid, exprs = self._compile_selector(eff)
                if t.namespace_selector is not None:
                    self._cluster_ns_selector_terms = True
                ns_set = resolve_term_namespaces(
                    t, p.metadata.namespace, self._namespace_labels)
                ns_ids = (None if ns_set is None else
                          tuple(self.namespaces.intern(n) for n in sorted(ns_set)))
                terms.append((self.keys.intern(t.topology_key), valid, exprs,
                              ns_ids))
            if (len(terms) > st.ET
                    or any(len(ex) > st.EAX for (_, _, ex, _) in terms)
                    or any(len(v) > st.EAV for (_, _, ex, _) in terms
                           for (_, _, v, _) in ex)
                    or any(nid >= st.NSB for (_, _, _, ns) in terms
                           if ns is not None for nid in ns)):
                return None  # ns beyond the NSB bucket widens the mask
            compiled.append((p, ni, label_ids, terms,
                             self._request_vector(p, st.resources)))

        freed = sum(1 for k in set(deletes) if k in st.slot_of)
        needed = sum(1 for (p, *_rest) in compiled if p.key not in st.slot_of)
        if needed > len(st.free) + freed:
            return None

        # ---- copy-on-write the arrays a pod delta touches ----------------
        requested = np.array(ct.requested)
        epod_node = np.array(ct.epod_node)
        epod_ns = np.array(ct.epod_ns)
        epod_labels = np.array(ct.epod_labels)
        epod_valid = np.array(ct.epod_valid)
        ea = {f: np.array(getattr(ct.ea_sel, f))
              for f in ("key", "op", "vals", "expr_valid", "valid")}
        ea_topo = np.array(ct.ea_topo)
        ea_valid = np.array(ct.ea_valid)
        ea_ns_explicit = np.array(ct.ea_ns_explicit)
        ea_ns_mask = np.array(ct.ea_ns_mask)

        def _clear(slot: int):
            epod_valid[slot] = False
            epod_labels[slot, :] = -1
            ea_topo[slot, :] = -1
            ea_valid[slot, :] = False
            ea["valid"][slot, :] = False
            ea["expr_valid"][slot, :, :] = False
            ea["key"][slot, :, :] = -1
            ea["vals"][slot, :, :, :] = -1
            ea_ns_explicit[slot, :] = False
            ea_ns_mask[slot, :, :] = False

        for k in set(deletes):
            slot = st.slot_of.pop(k, None)
            if slot is None:
                continue
            requested[st.slot_node.pop(k)] -= st.slot_req.pop(k)
            _clear(slot)
            st.free.append(slot)

        new_topo: set[int] = set()
        for p, ni, label_ids, terms, req_vec in compiled:
            key = p.key
            slot = st.slot_of.get(key)
            if slot is not None:
                requested[st.slot_node[key]] -= st.slot_req[key]
                _clear(slot)
            else:
                slot = st.free.pop()
                st.slot_of[key] = slot
            epod_node[slot] = ni
            epod_ns[slot] = self.namespaces.intern(p.metadata.namespace)
            for kid, vid in label_ids.items():
                epod_labels[slot, kid] = vid
            epod_valid[slot] = True
            for t_idx, (topo, valid, exprs, ns_ids) in enumerate(terms):
                ea_topo[slot, t_idx] = topo
                ea_valid[slot, t_idx] = True
                _selset_fill(ea, (slot, t_idx), valid, exprs)
                if ns_ids is not None:
                    ea_ns_explicit[slot, t_idx] = True
                    for nid in ns_ids:
                        ea_ns_mask[slot, t_idx, nid] = True
                new_topo.add(topo)
            requested[ni] += req_vec
            st.slot_node[key] = ni
            st.slot_req[key] = req_vec

        if new_topo - set(meta.topo_keys):
            self._cluster_topo_keys |= new_topo
            meta.topo_keys = tuple(sorted(set(meta.topo_keys) | new_topo))
        return ct.replace(
            requested=requested, epod_node=epod_node, epod_ns=epod_ns,
            epod_labels=epod_labels, epod_valid=epod_valid,
            ea_sel=SelectorSet(**ea), ea_topo=ea_topo, ea_valid=ea_valid,
            ea_ns_explicit=ea_ns_explicit, ea_ns_mask=ea_ns_mask,
        )

    # -- selector compilation ----------------------------------------------

    def _compile_requirement(self, req: Requirement):
        kid = self.keys.intern(req.key)
        opc = OPC[req.operator]
        vals = [self.values.intern(v) for v in req.values]
        num = math.nan
        if req.operator in (OP_GT, OP_LT) and req.values:
            try:
                num = float(int(req.values[0]))
            except (TypeError, ValueError):
                num = math.nan
        return kid, opc, vals, num

    def _compile_terms(self, term_weight_pairs: list[tuple[NodeSelectorTerm, float]],
                       caps: tuple[int, int, int]):
        """-> per-pod lists ready for array fill: [(weight, [exprs...])]."""
        out = []
        for term, weight in term_weight_pairs:
            exprs = []
            for e in term.match_expressions:
                exprs.append(self._compile_requirement(e))
            for e in term.match_fields:
                # matchFields address node fields; metadata.name is the only
                # field the reference supports. It rides the pseudo-label.
                exprs.append(self._compile_requirement(
                    Requirement(NODE_NAME_LABEL, e.operator, e.values)))
            out.append((weight, exprs))
        return out

    def _compile_selector(self, sel: Optional[LabelSelector]):
        """LabelSelector -> (valid, [compiled exprs]); None -> invalid
        (nil matches nothing), empty -> valid with no exprs (matches all)."""
        if sel is None:
            return (False, [])
        return (True, [self._compile_requirement(r) for r in sel.requirements()])

    # -- pod side -----------------------------------------------------------

    def _compile_pod(self, p: Pod) -> dict:
        """Host-side compile of ONE pod: selectors/affinity terms to int-set
        tables, tolerations/ports/images interned. This is the expensive
        half of ``encode_pods`` (the array fill is cheap); it only reads the
        intern tables (append-only) and the volume/namespace/DRA catalogs,
        so it can run at informer-event time (``precompile_pod``) instead of
        on the drain hot path."""
        aff = p.spec.affinity
        na = aff.node_affinity if aff else None
        req_pairs = [(t, 1.0) for t in (na.required if na else [])]
        pref_pairs = [(t.preference, float(t.weight)) for t in (na.preferred if na else [])]
        req_terms = self._compile_terms(req_pairs, (0, 0, 0))
        pref_terms = self._compile_terms(pref_pairs, (0, 0, 0))
        sel = [(self.keys.intern(k), self.values.intern(v))
               for k, v in sorted(p.spec.node_selector.items())]
        tols = []
        for t in p.spec.tolerations:
            tols.append((
                self.keys.intern(t.key) if t.key else -1,
                TOLOPC_EXISTS if t.operator == TOL_OP_EXISTS else TOLOPC_EQUAL,
                self.values.intern(t.value) if t.value else self.values.intern(""),
                EFFECTC[t.effect] if t.effect else -1,
            ))
        ports = [(PROTOC.get(proto, 3), port, self.ips.intern(ip))
                 for (ip, proto, port) in p.host_ports()]
        images = []
        for c in p.spec.containers:
            if c.image:
                images.append(self._intern_image(c.image))
        pa = aff.pod_affinity if aff else None
        pan = aff.pod_anti_affinity if aff else None
        own_ns = self.namespaces.intern(p.metadata.namespace)

        def _term_ns(t):
            ns_set = resolve_term_namespaces(
                t, p.metadata.namespace, self._namespace_labels)
            return (None if ns_set is None else
                    tuple(self.namespaces.intern(n) for n in sorted(ns_set)))

        def _pod_terms(terms):
            out = []
            for t in terms:
                eff = affinity_term_selector(t, p.metadata.labels)
                valid, exprs = self._compile_selector(eff)
                out.append((self.keys.intern(t.topology_key), valid, exprs,
                            _term_ns(t)))
            return out

        aff_req = _pod_terms(pa.required if pa else [])
        anti_req = _pod_terms(pan.required if pan else [])
        paff = []
        for wt in (pa.preferred if pa else []):
            kid = self.keys.intern(wt.term.topology_key)
            eff = affinity_term_selector(wt.term, p.metadata.labels)
            valid, exprs = self._compile_selector(eff)
            paff.append((kid, valid, exprs, float(wt.weight),
                         _term_ns(wt.term)))
        for wt in (pan.preferred if pan else []):
            kid = self.keys.intern(wt.term.topology_key)
            eff = affinity_term_selector(wt.term, p.metadata.labels)
            valid, exprs = self._compile_selector(eff)
            paff.append((kid, valid, exprs, -float(wt.weight),
                         _term_ns(wt.term)))
        spreads = []
        for sc in p.spec.topology_spread_constraints:
            eff = spread_selector(sc, p.metadata.labels)
            valid, exprs = self._compile_selector(eff)
            spreads.append((self.keys.intern(sc.topology_key), valid, exprs,
                            int(sc.max_skew),
                            sc.when_unsatisfiable == "DoNotSchedule",
                            int(sc.min_domains or 0),
                            sc.node_affinity_policy != NODE_INCLUSION_IGNORE,
                            sc.node_taints_policy == NODE_INCLUSION_HONOR))
        labels = self._label_ids(p.metadata.labels)
        # volumes: PVC groups -> (group_id, compiled term) pairs
        from kubernetes_tpu.sched.volumebinding import compile_pod_volumes
        vinfo = compile_pod_volumes(p, self._volumes, self._rwop_in_use)
        vol_terms = []
        for g_idx, group in enumerate(vinfo.groups):
            for _w, exprs in self._compile_terms([(t, 1.0) for t in group],
                                                 (0, 0, 0)):
                vol_terms.append((g_idx, exprs))
        vol_rwo = [self.pv_names.intern(n) for n in vinfo.rwo_pv_names]
        return dict(
            pod=p, req_terms=req_terms, pref_terms=pref_terms, sel=sel,
            tols=tols, ports=ports, images=images, labels=labels, ns=own_ns,
            aff_req=aff_req, anti_req=anti_req, paff=paff, spreads=spreads,
            vol_terms=vol_terms, vol_groups=len(vinfo.groups),
            vol_rwo=vol_rwo, attach_req=vinfo.attach_count,
        )

    def precompile_pod(self, p: Pod) -> bool:
        """Compile a pod's encode record AND its numpy row pack AHEAD of
        batch-encode time — the informer layer calls this per watch event,
        so by the time the drain pops the pod, ``encode_pods`` pays one
        np.stack per field, zero per-pod fill work (the incremental-encode
        half of the connected-path pipeline; see sched/cache.py
        precompile_pod for the locking discipline).

        Volume-carrying pods are skipped: their compile reads catalog state
        (``_rwop_in_use``) that every cluster encode rewrites. Returns True
        when the record was cached."""
        if p.spec.volumes:
            return False
        if len(self._pod_cache) >= self._pod_cache_max:
            self._pod_cache.clear()  # backstop; steady state evicts per key
        epoch = self._epoch_for(p)
        c = self._compile_pod(p)
        sig = pack = None
        if self._row_sig is not None:
            resources, K, NSB, w = self._row_env
            res_index = {r: i for i, r in enumerate(resources)}
            if all(r in res_index for r in self._effective_requests(p)):
                try:
                    pack = self._build_rows(c, resources, K, NSB, w)
                    sig = self._row_sig
                except IndexError:
                    # the pod outgrows the current buckets (wider terms, a
                    # key past K, ...): encode_pods promotes the signature
                    # when this pod actually pops, and fills its rows then
                    pack = None
        self._pod_cache[p.key] = [p, epoch, c, sig, pack]
        return True

    def pod_cache_discard(self, key: str) -> None:
        """Drop a pod's precompiled record — bound/deleted pods never
        encode again, and keeping their Pod + compiled tables alive would
        grow the cache to the wholesale-clear backstop (which would dump
        live pending pods' records too). Plain dict.pop: GIL-atomic, safe
        from informer threads WITHOUT the encode lock (a concurrent
        encode_pods either sees the entry or recompiles; both correct)."""
        self._pod_cache.pop(key, None)

    def encode_pods(self, pods: list[Pod], meta: SnapshotMeta,
                    min_p: int = 1, cache_rows: bool = True) -> PodBatch:
        """``min_p`` pins the pod-axis bucket floor so callers with a fixed
        batch shape (the fused drain) never trigger a smaller-bucket
        recompile for a partial chunk. ``cache_rows=False`` skips storing
        compile records for misses — for callers encoding DERIVED pod
        objects (a profile's addedAffinity wrap) whose identity will never
        be seen again; storing those would evict live precompiled records."""
        P = next_bucket(len(pods), minimum=min_p)
        R = len(meta.resources)
        meta.pod_keys = [p.key for p in pods]
        n = len(pods)

        # First pass: compile everything host-side, find bucket sizes.
        # Pods precompiled at informer-event time (``precompile_pod``) skip
        # the compile entirely — the drain hot path then assembles their
        # PREBUILT rows. Identity + epoch guard staleness: a new watch
        # object or any catalog change (volumes/namespaces/DRA) misses.
        compiled = []
        entries: list[Optional[list]] = []  # live cache record per pod
        for p in pods:
            ent = self._pod_cache.get(p.key)
            if (ent is not None and ent[0] is p
                    and ent[1] == self._epoch_for(p)):
                compiled.append(ent[2])
                entries.append(ent)
                self.pod_cache_hits += 1
                continue
            # snapshot the epoch BEFORE compiling: a catalog change racing
            # the compile (informer threads bump the epoch without the
            # encode lock) must invalidate this record, not get tagged on it
            epoch = self._epoch_for(p)
            c = self._compile_pod(p)
            compiled.append(c)
            self.pod_cache_misses += 1
            ent = None
            if cache_rows and not p.spec.volumes:
                # failure re-pops carry the SAME Pod object back through
                # here — cache so the retry encode is stack-only too
                if len(self._pod_cache) >= self._pod_cache_max:
                    self._pod_cache.clear()
                ent = [p, epoch, c, None, None]
                self._pod_cache[p.key] = ent
            entries.append(ent)

        K = next_bucket(len(self.keys), minimum=1)

        def _bucket(fn, minimum=0):
            return next_bucket(max((fn(c) for c in compiled), default=0), minimum=minimum)

        w = {}
        w["TREQ"] = _bucket(lambda c: len(c["req_terms"]))
        w["TPREF"] = _bucket(lambda c: len(c["pref_terms"]))
        w["VT"] = _bucket(lambda c: len(c["vol_terms"]))
        w["VG"] = _bucket(lambda c: c["vol_groups"])
        w["VB"] = _bucket(lambda c: len(c["vol_rwo"]))
        w["X"] = _bucket(lambda c: max((len(e) for _, e in c["req_terms"] + c["pref_terms"]
                                        + c["vol_terms"]), default=0))
        w["VV"] = _bucket(lambda c: max((len(v) for _, ex in c["req_terms"] + c["pref_terms"]
                                         + c["vol_terms"]
                                         for (_, _, v, _) in ex), default=0))
        w["S"] = _bucket(lambda c: len(c["sel"]))
        w["TOL"] = _bucket(lambda c: len(c["tols"]))
        w["PP"] = _bucket(lambda c: len(c["ports"]))
        w["CI"] = _bucket(lambda c: len(c["images"]))
        w["AT"] = _bucket(lambda c: len(c["aff_req"]))
        w["BT"] = _bucket(lambda c: len(c["anti_req"]))
        w["CT"] = _bucket(lambda c: len(c["paff"]))
        w["SC"] = _bucket(lambda c: len(c["spreads"]))
        AX = _bucket(lambda c: max((len(e) for (_, _, e, _) in c["aff_req"] + c["anti_req"]), default=0))
        AX = max(AX, _bucket(lambda c: max((len(e) for (_, _, e, _, _) in c["paff"]), default=0)))
        AX = max(AX, _bucket(lambda c: max((len(t[2]) for t in c["spreads"]), default=0)))
        AV = _bucket(lambda c: max((len(v) for (_, _, e, _) in c["aff_req"] + c["anti_req"]
                                    for (_, _, v, _) in e), default=0))
        AV = max(AV, _bucket(lambda c: max((len(v) for (_, _, e, _, _) in c["paff"]
                                            for (_, _, v, _) in e), default=0)))
        AV = max(AV, _bucket(lambda c: max((len(v) for t in c["spreads"]
                                            for (_, _, v, _) in t[2]), default=0)))
        w["AX"], w["AV"] = AX, AV
        # sticky promotion: widths never shrink across encodes, so a pod's
        # prebuilt row pack stays valid batch to batch (padding is inert
        # behind validity flags; stable widths also mean stable compiled
        # program shapes — unify_batches/pad_batch_to become no-ops in
        # steady state)
        for k in _ROW_DIMS:
            w[k] = max(w[k], self._row_widths.get(k, 0))
        self._row_widths = {k: w[k] for k in _ROW_DIMS}
        # namespace-mask width: all term ns sets are already interned above
        NSB = next_bucket(len(self.namespaces) + self.ns_headroom, minimum=1)
        sig = (tuple(meta.resources), K, NSB) + tuple(w[k] for k in _ROW_DIMS)
        self._row_sig = sig
        self._row_env = (list(meta.resources), K, NSB, dict(w))

        # Second pass: one row pack per pod — PREBUILT at informer-event
        # time when the signature matches (the steady state: zero per-pod
        # fill work on this path), built here otherwise and cached back so
        # failure re-pops stack too.
        packs = []
        forced = []
        image_bytes_v = []
        for (c, ent) in zip(compiled, entries):
            if ent is not None and ent[3] == sig and ent[4] is not None:
                packs.append(ent[4])
                self.pod_rows_stacked += 1
            else:
                pk = self._build_rows(c, meta.resources, K, NSB, w)
                self.pod_rows_filled += 1
                if ent is not None:
                    ent[3], ent[4] = sig, pk
                packs.append(pk)
            p: Pod = c["pod"]
            # scalars a cached pack must not freeze: node pinning reads the
            # CURRENT node_index and DRA allocation state; image bytes read
            # the live size table (node status may raise a size later)
            fn = -1
            if p.spec.node_name:
                fn = meta.node_index.get(p.spec.node_name, -2)
            if self._dra is not None and p.spec.resource_claims:
                if not self._dra.pod_claims_ready(p):
                    # referenced claim doesn't exist yet (template race):
                    # hold unschedulable, never drop the device demand
                    fn = -2
                else:
                    # an already-allocated claim pins the pod to its node
                    # (dynamicresources.go Filter on claim.status.allocation)
                    alloc_node = self._dra.pod_allocated_node(p)
                    if alloc_node and not p.spec.node_name:
                        fn = meta.node_index.get(alloc_node, -2)
            forced.append(fn)
            image_bytes_v.append(
                float(sum(self._image_sizes[im] for im in c["images"]))
                if c["images"] else 0.0)

        TREQ, TPREF, VT, VG, VB = w["TREQ"], w["TPREF"], w["VT"], w["VG"], w["VB"]
        X, VV, S, TOL, PP, CI = w["X"], w["VV"], w["S"], w["TOL"], w["PP"], w["CI"]
        AT, BT, CT, SC = w["AT"], w["BT"], w["CT"], w["SC"]

        def _new_termset(T):
            return dict(
                key=np.full((P, T, X), -1, np.int32),
                op=np.zeros((P, T, X), np.int32),
                vals=np.full((P, T, X, VV), -1, np.int32),
                num=np.full((P, T, X), np.nan, np.float32),
                expr_valid=np.zeros((P, T, X), bool),
                term_valid=np.zeros((P, T), bool),
                weight=np.zeros((P, T), np.float32),
                has_any=np.zeros(P, bool),
            )

        req_a = _new_termset(TREQ)
        pref_a = _new_termset(TPREF)
        vol_a = _new_termset(VT)
        vol_group = np.full((P, VT), -1, np.int32)
        vol_group_valid = np.zeros((P, VG), bool)
        rwo_pv = np.full((P, VB), -1, np.int32)
        rwo_valid = np.zeros((P, VB), bool)
        attach_req = np.zeros(P, np.int32)

        def _new_selset(shape_prefix):
            return _selset_arrays(shape_prefix, AX, AV)

        requests = np.zeros((P, R), np.int32)
        pod_valid = np.zeros(P, bool)
        priority = np.zeros(P, np.int32)
        forced_node = np.full(P, -1, np.int32)
        pod_ns = np.full(P, -1, np.int32)
        pod_labels = np.full((P, K), -1, np.int32)
        tol_key = np.full((P, TOL), -1, np.int32)
        tol_op = np.zeros((P, TOL), np.int32)
        tol_val = np.full((P, TOL), -1, np.int32)
        tol_effect = np.full((P, TOL), -1, np.int32)
        tol_valid = np.zeros((P, TOL), bool)
        sel_key = np.full((P, S), -1, np.int32)
        sel_val = np.full((P, S), -1, np.int32)
        sel_valid = np.zeros((P, S), bool)
        pport_proto = np.full((P, PP), -1, np.int32)
        pport_port = np.full((P, PP), -1, np.int32)
        pport_ip = np.full((P, PP), -1, np.int32)
        pport_valid = np.zeros((P, PP), bool)
        pod_images = np.full((P, CI), -1, np.int32)
        image_bytes = np.zeros(P, np.float32)
        aff_sel = _new_selset((P, AT))
        aff_topo = np.full((P, AT), -1, np.int32)
        aff_valid = np.zeros((P, AT), bool)
        aff_ns_explicit = np.zeros((P, AT), bool)
        aff_ns_mask = np.zeros((P, AT, NSB), bool)
        anti_sel = _new_selset((P, BT))
        anti_topo = np.full((P, BT), -1, np.int32)
        anti_valid = np.zeros((P, BT), bool)
        anti_ns_explicit = np.zeros((P, BT), bool)
        anti_ns_mask = np.zeros((P, BT, NSB), bool)
        paff_sel = _new_selset((P, CT))
        paff_topo = np.full((P, CT), -1, np.int32)
        paff_weight = np.zeros((P, CT), np.float32)
        paff_valid = np.zeros((P, CT), bool)
        paff_ns_explicit = np.zeros((P, CT), bool)
        paff_ns_mask = np.zeros((P, CT, NSB), bool)
        sc_sel = _new_selset((P, SC))
        sc_topo = np.full((P, SC), -1, np.int32)
        sc_maxskew = np.ones((P, SC), np.int32)
        sc_hard = np.zeros((P, SC), bool)
        sc_valid = np.zeros((P, SC), bool)
        sc_min_domains = np.zeros((P, SC), np.int32)
        sc_honor_affinity = np.zeros((P, SC), bool)
        sc_honor_taints = np.zeros((P, SC), bool)

        # ---- assembly: one bulk np.stack per field (no per-pod fill) -----
        if n:
            def put(dst, key):
                dst[:n] = np.stack([pk[key] for pk in packs])

            def put_scalar(dst, key, dtype):
                dst[:n] = np.fromiter((pk[key] for pk in packs), dtype, n)

            pod_valid[:n] = True
            forced_node[:n] = forced
            image_bytes[:n] = image_bytes_v
            put(requests, "requests")
            put_scalar(priority, "priority", np.int32)
            put_scalar(pod_ns, "ns", np.int32)
            put_scalar(attach_req, "attach_req", np.int32)
            put(pod_labels, "labels")
            for dst, f in ((tol_key, "tol_key"), (tol_op, "tol_op"),
                           (tol_val, "tol_val"), (tol_effect, "tol_effect"),
                           (tol_valid, "tol_valid")):
                put(dst, f)
            put(sel_key, "sel_key")
            put(sel_val, "sel_val")
            put(sel_valid, "sel_valid")
            for prefix, arrs in (("req", req_a), ("pref", pref_a),
                                 ("vol", vol_a)):
                for f in ("key", "op", "vals", "num", "expr_valid",
                          "term_valid", "weight"):
                    put(arrs[f], f"{prefix}_{f}")
                put_scalar(arrs["has_any"], f"{prefix}_has_any", bool)
            put(vol_group, "vol_group")
            put(vol_group_valid, "vol_group_valid")
            put(rwo_pv, "rwo_pv")
            put(rwo_valid, "rwo_valid")
            put(pport_proto, "port_proto")
            put(pport_port, "port_port")
            put(pport_ip, "port_ip")
            put(pport_valid, "port_valid")
            put(pod_images, "images")
            for prefix, selset, extras in (
                    ("aff", aff_sel,
                     ((aff_topo, "topo"), (aff_valid, "valid"),
                      (aff_ns_explicit, "ns_explicit"),
                      (aff_ns_mask, "ns_mask"))),
                    ("anti", anti_sel,
                     ((anti_topo, "topo"), (anti_valid, "valid"),
                      (anti_ns_explicit, "ns_explicit"),
                      (anti_ns_mask, "ns_mask"))),
                    ("paff", paff_sel,
                     ((paff_topo, "topo"), (paff_valid, "valid"),
                      (paff_weight, "weight"),
                      (paff_ns_explicit, "ns_explicit"),
                      (paff_ns_mask, "ns_mask"))),
                    ("sc", sc_sel,
                     ((sc_topo, "topo"), (sc_valid, "valid"),
                      (sc_maxskew, "maxskew"), (sc_hard, "hard"),
                      (sc_min_domains, "min_domains"),
                      (sc_honor_affinity, "honor_affinity"),
                      (sc_honor_taints, "honor_taints")))):
                for f in ("key", "op", "vals", "expr_valid", "valid"):
                    put(selset[f], f"{prefix}_sel_{f}")
                for dst, f in extras:
                    put(dst, f"{prefix}_{f}")

        batch_topo = {int(k) for k in np.concatenate([
            aff_topo[aff_valid], anti_topo[anti_valid],
            paff_topo[paff_valid], sc_topo[sc_valid]]).tolist()} if P else set()
        meta.topo_keys = tuple(sorted(set(meta.topo_keys) | batch_topo))

        return PodBatch(
            requests=requests, pod_valid=pod_valid, priority=priority,
            forced_node=forced_node, pod_ns=pod_ns, pod_labels=pod_labels,
            tol_key=tol_key, tol_op=tol_op, tol_val=tol_val, tol_effect=tol_effect,
            tol_valid=tol_valid,
            sel_key=sel_key, sel_val=sel_val, sel_valid=sel_valid,
            req_terms=TermSet(**req_a), pref_terms=TermSet(**pref_a),
            port_proto=pport_proto, port_port=pport_port, port_ip=pport_ip,
            port_valid=pport_valid,
            pod_images=pod_images, image_bytes=image_bytes,
            aff_sel=SelectorSet(**aff_sel), aff_topo=aff_topo, aff_valid=aff_valid,
            aff_ns_explicit=aff_ns_explicit, aff_ns_mask=aff_ns_mask,
            anti_sel=SelectorSet(**anti_sel), anti_topo=anti_topo, anti_valid=anti_valid,
            anti_ns_explicit=anti_ns_explicit, anti_ns_mask=anti_ns_mask,
            paff_sel=SelectorSet(**paff_sel), paff_topo=paff_topo,
            paff_weight=paff_weight, paff_valid=paff_valid,
            paff_ns_explicit=paff_ns_explicit, paff_ns_mask=paff_ns_mask,
            sc_sel=SelectorSet(**sc_sel), sc_topo=sc_topo, sc_maxskew=sc_maxskew,
            sc_hard=sc_hard, sc_valid=sc_valid,
            sc_min_domains=sc_min_domains, sc_honor_affinity=sc_honor_affinity,
            sc_honor_taints=sc_honor_taints,
            vol_terms=TermSet(**vol_a), vol_group=vol_group,
            vol_group_valid=vol_group_valid,
            rwo_pv=rwo_pv, rwo_valid=rwo_valid, attach_req=attach_req,
        )

    def _build_rows(self, c: dict, resources: list[str], K: int, NSB: int,
                    w: dict) -> dict:
        """ONE pod's PodBatch rows as small numpy arrays at the bucket
        signature ``(resources, K, NSB, w)`` — the per-pod half of the
        vectorized ``encode_pods`` assembly. Runs at informer-event time
        (``precompile_pod``) in the steady state; the batch hot path then
        does one np.stack per field and no per-pod fill work. Raises
        IndexError when the pod outgrows the widths (callers treat that as
        "no pack"; encode_pods always passes covering widths)."""
        X, VV, AX, AV = w["X"], w["VV"], w["AX"], w["AV"]
        p: Pod = c["pod"]
        rows: dict = {
            "priority": int(p.spec.priority), "ns": int(c["ns"]),
            "attach_req": int(c["attach_req"]),
        }

        rows["requests"] = self._request_vector(p, resources)

        labels = np.full(K, -1, np.int32)
        for kid, vid in c["labels"].items():
            labels[kid] = vid
        rows["labels"] = labels

        tol_key = np.full(w["TOL"], -1, np.int32)
        tol_op = np.zeros(w["TOL"], np.int32)
        tol_val = np.full(w["TOL"], -1, np.int32)
        tol_effect = np.full(w["TOL"], -1, np.int32)
        tol_valid = np.zeros(w["TOL"], bool)
        for t_idx, (kid, opc, vid, eff) in enumerate(c["tols"]):
            tol_key[t_idx], tol_op[t_idx] = kid, opc
            tol_val[t_idx], tol_effect[t_idx] = vid, eff
            tol_valid[t_idx] = True
        rows.update(tol_key=tol_key, tol_op=tol_op, tol_val=tol_val,
                    tol_effect=tol_effect, tol_valid=tol_valid)

        sel_key = np.full(w["S"], -1, np.int32)
        sel_val = np.full(w["S"], -1, np.int32)
        sel_valid = np.zeros(w["S"], bool)
        for s_idx, (kid, vid) in enumerate(c["sel"]):
            sel_key[s_idx], sel_val[s_idx] = kid, vid
            sel_valid[s_idx] = True
        rows.update(sel_key=sel_key, sel_val=sel_val, sel_valid=sel_valid)

        def termset_rows(prefix, T, terms):
            a = dict(
                key=np.full((T, X), -1, np.int32),
                op=np.zeros((T, X), np.int32),
                vals=np.full((T, X, VV), -1, np.int32),
                num=np.full((T, X), np.nan, np.float32),
                expr_valid=np.zeros((T, X), bool),
                term_valid=np.zeros(T, bool),
                weight=np.zeros(T, np.float32),
            )
            for t_idx, (weight, exprs) in enumerate(terms):
                a["term_valid"][t_idx] = True
                a["weight"][t_idx] = weight
                for x_idx, (kid, opc, vals, num) in enumerate(exprs):
                    a["key"][t_idx, x_idx] = kid
                    a["op"][t_idx, x_idx] = opc
                    a["num"][t_idx, x_idx] = num
                    a["expr_valid"][t_idx, x_idx] = True
                    for v_idx, v in enumerate(vals):
                        a["vals"][t_idx, x_idx, v_idx] = v
            for f, arr in a.items():
                rows[f"{prefix}_{f}"] = arr
            rows[f"{prefix}_has_any"] = len(terms) > 0

        vol_terms = [(float(g), e) for g, e in c["vol_terms"]]
        termset_rows("req", w["TREQ"], c["req_terms"])
        termset_rows("pref", w["TPREF"], c["pref_terms"])
        # vol terms reuse the TermSet layout with group id in place of
        # weight, then split the group id out into vol_group
        termset_rows("vol", w["VT"], vol_terms)
        vol_group = np.full(w["VT"], -1, np.int32)
        for t_idx, (g, _e) in enumerate(c["vol_terms"]):
            vol_group[t_idx] = g
        vol_group_valid = np.zeros(w["VG"], bool)
        vol_group_valid[:c["vol_groups"]] = True
        rwo_pv = np.full(w["VB"], -1, np.int32)
        rwo_valid = np.zeros(w["VB"], bool)
        for b_idx, pvid in enumerate(c["vol_rwo"]):
            rwo_pv[b_idx] = pvid
            rwo_valid[b_idx] = True
        rows.update(vol_group=vol_group, vol_group_valid=vol_group_valid,
                    rwo_pv=rwo_pv, rwo_valid=rwo_valid)

        port_proto = np.full(w["PP"], -1, np.int32)
        port_port = np.full(w["PP"], -1, np.int32)
        port_ip = np.full(w["PP"], -1, np.int32)
        port_valid = np.zeros(w["PP"], bool)
        for pt_idx, (proto, port, ip) in enumerate(c["ports"]):
            port_proto[pt_idx], port_port[pt_idx] = proto, port
            port_ip[pt_idx] = ip
            port_valid[pt_idx] = True
        rows.update(port_proto=port_proto, port_port=port_port,
                    port_ip=port_ip, port_valid=port_valid)

        images = np.full(w["CI"], -1, np.int32)
        for ci_idx, img in enumerate(c["images"]):
            images[ci_idx] = img
        rows["images"] = images

        def selset_rows(prefix, T, items, scalars):
            """items: [(topo, valid, exprs, *extras, ns_ids)] with extras
            per ``scalars``: [(name, dtype, default)]."""
            a = _selset_arrays((T,), AX, AV)
            topo = np.full(T, -1, np.int32)
            valid = np.zeros(T, bool)
            ns_explicit = np.zeros(T, bool)
            ns_mask = np.zeros((T, NSB), bool)
            extra_arrs = {nm: np.full(T, dflt, dt)
                          for nm, dt, dflt in scalars}
            for t_idx, item in enumerate(items):
                tk, sv, exprs = item[0], item[1], item[2]
                ns_ids = item[-1]
                topo[t_idx] = tk
                valid[t_idx] = True
                _selset_fill(a, (t_idx,), sv, exprs)
                for (nm, _dt, _df), val in zip(scalars, item[3:-1]):
                    extra_arrs[nm][t_idx] = val
                if ns_ids is not None:
                    ns_explicit[t_idx] = True
                    for nid in ns_ids:
                        ns_mask[t_idx, nid] = True
            for f, arr in a.items():
                rows[f"{prefix}_sel_{f}"] = arr
            rows[f"{prefix}_topo"] = topo
            rows[f"{prefix}_valid"] = valid
            rows[f"{prefix}_ns_explicit"] = ns_explicit
            rows[f"{prefix}_ns_mask"] = ns_mask
            for nm, arr in extra_arrs.items():
                rows[f"{prefix}_{nm}"] = arr

        selset_rows("aff", w["AT"], c["aff_req"], [])
        selset_rows("anti", w["BT"], c["anti_req"], [])
        selset_rows("paff", w["CT"], c["paff"],
                    [("weight", np.float32, 0.0)])
        # spreads: (topo, valid, exprs, skew, hard, mind, haff, htaint) —
        # no ns_ids slot, so append a None sentinel for the shared driver
        selset_rows("sc", w["SC"],
                    [t + (None,) for t in c["spreads"]],
                    [("maxskew", np.int32, 1), ("hard", bool, False),
                     ("min_domains", np.int32, 0),
                     ("honor_affinity", bool, False),
                     ("honor_taints", bool, False)])
        return rows
